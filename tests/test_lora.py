"""LoRA parameter-efficient fine-tuning (models/lora.py).

Beyond-parity: the reference trains every weight with full Adam state
(reference scripts/train.py:113,117). LoRA freezes the base model and
trains low-rank factors on targeted kernels; these tests pin down the
contract: zero-init delta, frozen base, adapter-only optimizer state,
merged export, sidecar roundtrip, and mesh-sharded training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.traverse_util import flatten_dict

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
    count_params,
    init_lora_params,
    load_adapters,
    merge_lora,
    save_adapters,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 16


def _cfg(**kw):
    base = dict(vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=SEQ)
    base.update(kw)
    return EncoderConfig(**base)


def _params(cfg, seed=0):
    return init_params(BertForSequenceClassification(cfg, num_labels=2), cfg, seed=seed)


def test_zero_init_delta_is_identity():
    """B starts at zero, so merging freshly-initialized adapters must
    reproduce the base params bit-for-bit."""
    cfg = _cfg()
    params = _params(cfg)
    lora = init_lora_params(params, rank=4, targets="attention", seed=0)
    merged = merge_lora(params, lora, scaling=2.0)
    for (pa, a), (pb, b) in zip(sorted(flatten_dict(params).items()),
                                sorted(flatten_dict(merged).items())):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_targeting_presets():
    cfg = _cfg()
    params = _params(cfg)
    att = flatten_dict(init_lora_params(params, 4, "attention"))
    att_paths = {"/".join(p[:-1]) for p in att}
    assert all(any(n in p for n in ("query", "key", "value", "attention_out"))
               for p in att_paths)
    # 2 layers x 4 projections, a+b each
    assert len(att) == 2 * 4 * 2

    mlp_paths = {"/".join(p[:-1]) for p in
                 flatten_dict(init_lora_params(params, 4, "mlp"))}
    assert all("intermediate" in p or "ffn_out" in p for p in mlp_paths)
    assert len(mlp_paths) == 2 * 2            # 2 layers x (in, out) kernels
    all_paths = {"/".join(p[:-1]) for p in
                 flatten_dict(init_lora_params(params, 4, "all"))}
    assert mlp_paths < all_paths
    with pytest.raises(ValueError, match="matched no kernels"):
        init_lora_params(params, 4, r"nonexistent_module_xyz")


def test_merge_changes_only_targets():
    cfg = _cfg()
    params = _params(cfg)
    lora = init_lora_params(params, rank=4, targets="attention", seed=0)
    # force a nonzero delta
    lora = jax.tree.map(lambda x: jnp.ones_like(x) * 0.01, lora)
    merged = flatten_dict(merge_lora(params, lora, scaling=1.0))
    base = flatten_dict(params)
    lora_kernels = {p[:-1] for p in flatten_dict(lora)}
    for path, leaf in base.items():
        if path in lora_kernels:
            assert not np.array_equal(np.asarray(merged[path]),
                                      np.asarray(leaf)), path
        else:
            np.testing.assert_array_equal(np.asarray(merged[path]),
                                          np.asarray(leaf))


def _fit_lora(devices, rank=4, **cfg_kw):
    mesh = build_mesh(MeshConfig(dp=-1), devices=devices)
    model_cfg = _cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg, seed=0)
    # host snapshot BEFORE the trainer takes ownership: the train step
    # donates its state, deleting the original device buffers
    params0 = jax.device_get(params)
    cfg = TrainConfig(task="seq-cls", dtype="float32", learning_rate=2e-2,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=8, lora_rank=rank,
                      **cfg_kw)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    return trainer, params0, hist


@pytest.mark.slow
def test_lora_trains_and_base_stays_frozen(devices8):
    import re

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
        HEAD_REGEX_DEFAULT,
    )

    trainer, params0, hist = _fit_lora(devices8)
    # the backbone is a frozen RANDOM init here (no pretrained weights in
    # the test env), so adapters+head learn slowly and noisily — assert a
    # clear improvement, not monotone descent
    assert min(hist["loss"]) < hist["loss"][0] - 0.02
    # the backbone must be bit-identical to its initial values; only the
    # task head (classifier/pooler — fresh-init, modules_to_save
    # semantics) is allowed to move
    head_rx = re.compile(HEAD_REGEX_DEFAULT)
    after = flatten_dict(jax.device_get(trainer.state.params["model"]))
    head_moved = False
    for path, p0 in flatten_dict(params0).items():
        p1 = after[path]
        if head_rx.search("/".join(path)):
            head_moved = head_moved or not np.array_equal(
                np.asarray(p0), np.asarray(p1))
        else:
            np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
    assert head_moved
    # adapters actually moved (B no longer all-zero)
    bs = [np.asarray(v) for k, v in
          flatten_dict(jax.device_get(trainer.state.params["lora"])).items()
          if k[-1] == "b"]
    assert any(np.abs(b).max() > 0 for b in bs)
    # merged export differs from the initial params on targeted kernels
    merged = flatten_dict(jax.device_get(trainer.export_params))
    base = flatten_dict(params0)
    assert any(not np.array_equal(np.asarray(merged[p]), np.asarray(base[p]))
               for p in base)


@pytest.mark.slow
def test_lora_optimizer_state_is_adapter_sized(devices8):
    """The HBM story: Adam m/v exist for adapters only — total optimizer
    state is a sliver of the base-param count, not 2x it."""
    import re

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
        HEAD_REGEX_DEFAULT,
    )

    trainer, params0, _ = _fit_lora(devices8)
    n_base = count_params(params0)
    n_lora = count_params(trainer.state.params["lora"])
    head_rx = re.compile(HEAD_REGEX_DEFAULT)
    n_head = sum(int(np.prod(v.shape))
                 for k, v in flatten_dict(params0).items()
                 if head_rx.search("/".join(k)))
    n_opt = count_params(jax.device_get(trainer.state.opt_state))
    assert n_lora + n_head < n_base // 5
    # mu + nu for adapters+heads + a few scalars; nothing backbone-sized
    assert n_opt <= 2 * (n_lora + n_head) + 64


@pytest.mark.slow
def test_lora_adapter_sidecar_roundtrip(tmp_path, devices8):
    trainer, _, _ = _fit_lora(devices8)
    lora = jax.device_get(trainer.state.params["lora"])
    save_adapters(str(tmp_path / "adapter"), lora, rank=4, alpha=16.0,
                  targets="attention")
    loaded, meta = load_adapters(str(tmp_path / "adapter"))
    assert meta == {"lora_rank": 4, "lora_alpha": 16.0,
                    "lora_targets": "attention"}
    for (ka, va), (kb, vb) in zip(sorted(flatten_dict(lora).items()),
                                  sorted(flatten_dict(loaded).items())):
        assert ka == kb
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))


@pytest.mark.slow
def test_lora_grad_accumulation_matches_big_batch(devices8):
    """LoRA + accumulation: accum=2 at global batch 8 must produce the
    same final (base, adapter) state as one update at global batch 16 —
    MultiSteps under multi_transform accumulates only the trainable
    subtree (MaskedNode placeholders carry no leaves)."""
    final = {}
    for accum, gb in ((1, 16), (2, 8)):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        # dropout-free: per-micro-step rng draws would otherwise differ
        # from the single-big-step draw (same as the non-LoRA accum test)
        model_cfg = _cfg(hidden_dropout=0.0, attention_dropout=0.0)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="seq-cls", dtype="float32",
                          learning_rate=1e-2, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          lora_rank=4, gradient_accumulation_steps=accum)
        trainer = Trainer(cfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, labels = synthetic_text_classification(64, seed=7)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
        for batch in ShardedBatcher(ds, gb, mesh, shuffle=False,
                                    seed=0).global_arrays(0):
            trainer.state, _ = trainer._train_step(trainer.state, batch)
        final[accum] = jax.device_get(trainer.state.params)
    for x, y in zip(jax.tree.leaves(final[1]), jax.tree.leaves(final[2])):
        np.testing.assert_allclose(np.asarray(y), np.asarray(x),
                                   atol=1e-5, rtol=1e-4)


@pytest.mark.slow
def test_lora_composes_with_fused_vocab_ce(devices8):
    """LoRA wraps whatever loss the task selected — including the fused
    vocab-CE path (the merge happens before hidden_and_embedding sees
    the params). Fused and unfused first-step losses must match on the
    same adapters."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        make_fused_causal_lm_loss,
    )

    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(16, seed=2)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=SEQ)

    def first_loss(fused):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        model_cfg = Gpt2Config(vocab_size=256, hidden_size=128,
                               num_layers=2, num_heads=4,
                               intermediate_size=256,
                               max_position_embeddings=SEQ,
                               hidden_dropout=0.0, embd_dropout=0.0,
                               attention_dropout=0.0)
        model = Gpt2LMHeadModel(model_cfg)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="causal-lm", dtype="float32",
                          learning_rate=1e-3, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          lora_rank=4, lora_train_heads="",
                          fused_vocab_ce=fused)
        trainer = Trainer(cfg, model, params, mesh)
        if fused:
            # rebuild the fused loss in interpret mode for CPU, then
            # re-wrap it with the SAME lora merge the Trainer installed
            inner = make_fused_causal_lm_loss(model, interpret=True)
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
                merge_lora,
            )
            scaling = trainer._lora_scaling

            def lora_fused(apply_fn, split, batch, rngs, train):
                merged = merge_lora(jax.lax.stop_gradient(split["model"]),
                                    split["lora"], scaling)
                return inner(apply_fn, merged, batch, rngs, train)

            trainer.loss_fn = lora_fused
        batch = next(ShardedBatcher(ds, 16, mesh, shuffle=False,
                                    seed=0).global_arrays(0))
        _, m = trainer._train_step(trainer.state, batch)
        return float(jax.device_get(m["loss"]))

    np.testing.assert_allclose(first_loss(True), first_loss(False),
                               rtol=2e-5)


@pytest.mark.slow
def test_lora_trains_on_tp_mesh(devices8):
    """Adapters stay replicated while the base is tensor/fsdp-sharded:
    training on dp2 x tp2 x fsdp2 must produce the same loss sequence as
    plain dp (the merge is sharding-transparent — XLA reshards the tiny
    A@B delta onto the base's layout)."""
    def losses(mesh_cfg):
        mesh = build_mesh(mesh_cfg, devices=devices8)
        model_cfg = _cfg(hidden_size=64, intermediate_size=128)
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg, seed=0)
        cfg = TrainConfig(task="seq-cls", dtype="float32",
                          learning_rate=2e-2, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry", epochs=2,
                          lora_rank=4)
        trainer = Trainer(cfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, labels = synthetic_text_classification(32, seed=0)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
        hist = trainer.fit(ShardedBatcher(ds, 8, mesh, shuffle=False,
                                          seed=0))
        return hist["loss"]

    ref = losses(MeshConfig(dp=-1))
    tp = losses(MeshConfig(dp=2, tp=2, fsdp=2))
    np.testing.assert_allclose(ref, tp, rtol=2e-5)


@pytest.mark.slow
def test_lora_checkpoint_resume_roundtrip(tmp_path, devices8):
    """The split {"model","lora"} state (and the multi_transform
    opt_state with its masked placeholders) round-trips through the
    Orbax checkpointer into a FRESH trainer built from a different
    seed — the preemption story holds under LoRA."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.train.checkpoint import (
        Checkpointer,
    )

    def make(seed):
        mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
        model_cfg = _cfg()
        model = BertForSequenceClassification(model_cfg, num_labels=2)
        params = init_params(model, model_cfg, seed=seed)
        cfg = TrainConfig(task="seq-cls", dtype="float32",
                          learning_rate=2e-2, scale_lr_by_world_size=False,
                          log_every_steps=0, rng_impl="threefry",
                          lora_rank=4, checkpoint_dir=str(tmp_path / "ck"))
        trainer = Trainer(cfg, model, params, mesh)
        tok = WordHashTokenizer(vocab_size=256)
        texts, labels = synthetic_text_classification(32, seed=0)
        ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
        return cfg, trainer, ShardedBatcher(ds, 8, mesh, shuffle=False,
                                            seed=0)

    cfg, trainer, batcher = make(seed=0)
    for batch in batcher.global_arrays(0):
        trainer.state, _ = trainer._train_step(trainer.state, batch)
    ckpt = Checkpointer(cfg.checkpoint_dir)
    ckpt.save(trainer.state, epoch=1)
    ckpt.wait_until_finished()

    _, trainer2, _ = make(seed=9)
    restored, epoch, _ = Checkpointer(cfg.checkpoint_dir).restore(
        trainer2.state)
    assert epoch == 1
    assert set(restored.params.keys()) == {"model", "lora"}
    for x, y in zip(jax.tree.leaves(jax.device_get(trainer.state)),
                    jax.tree.leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    ckpt.close()


@pytest.mark.slow
def test_lora_keep_best_restores_adapters_and_heads(devices8, monkeypatch):
    """--keep_best under LoRA snapshots only what can change (adapters +
    trainable heads, NOT the frozen multi-size base) and restores the
    best epoch's values into the live state at fit end."""
    mesh = build_mesh(MeshConfig(dp=-1), devices=devices8)
    model_cfg = _cfg()
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg, seed=0)
    cfg = TrainConfig(task="seq-cls", dtype="float32", learning_rate=2e-2,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=3, lora_rank=4,
                      keep_best=True)
    trainer = Trainer(cfg, model, params, mesh)

    scripted = iter([0.5, 0.2, 0.9])
    captured = {}

    def fake_evaluate(batcher):
        loss = next(scripted)
        captured[loss] = jax.device_get(trainer.state.params)
        return {"eval_loss": loss, "eval_accuracy": 1.0 - loss}

    monkeypatch.setattr(trainer, "evaluate", fake_evaluate)
    tok = WordHashTokenizer(vocab_size=256)
    texts, labels = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0),
                eval_batcher=object())
    assert trainer.best_epoch == 1
    # the snapshot covers adapters + head leaves only
    best = captured[0.2]
    live = jax.device_get(trainer.state.params)
    for k, v in flatten_dict(best["lora"]).items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(flatten_dict(live["lora"])[k]))
    import re

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.lora import (
        HEAD_REGEX_DEFAULT,
    )

    rx = re.compile(HEAD_REGEX_DEFAULT)
    live_model = flatten_dict(live["model"])
    for k, v in flatten_dict(best["model"]).items():
        if rx.search("/".join(k)):
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(live_model[k]))
    # the snapshot itself was released after the restore
    assert trainer._best_params is None
