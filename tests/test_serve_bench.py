"""Tier-1 smoke of ``bench.py --serve`` (benchmarks/serve_bench.py):
the CPU gate runs the real measured bodies at smoke scale and pins the
structural guarantees — greedy exactness vs the static baseline,
bucketed-vs-full-width output identity, and compile flatness across the
measured (post-warmup) serving runs. The speedup/ratio acceptances
(≥2x continuous-vs-static, ≥1.3x bucketed decode) are measured by the
full ``bench.py --serve`` traces — exercised here only under the
``slow`` marker: at smoke scale dispatch overhead dominates and the
ratios are noise."""

import json

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs


def test_serve_bench_smoke(capsys, tmp_path):
    from benchmarks.serve_bench import bench_serve

    obs.reset(out_dir=str(tmp_path / "telemetry"), enabled=True)
    try:
        mixed, bucketed = bench_serve(smoke=True)
    finally:
        obs.reset()
    detail = mixed["detail"]
    assert detail["exact_match"] is True
    # compile flatness: the warm pass precompiles every bucket, so the
    # measured window sees 0 (the gate itself allows <= #buckets)
    assert detail["compiles_steady"] == 0
    assert mixed["value"] > 0 and detail["tokens"] > 0
    assert detail["ttft_p99_s"] >= detail["ttft_p50_s"] > 0
    assert 0 < detail["kv_peak_utilization"] <= 1
    assert 0 <= detail["gather_read_waste_mean"] <= 1

    bdetail = bucketed["detail"]
    assert bdetail["exact_match"] is True           # bucketed == full
    assert bdetail["compiles_steady_bucketed"] <= len(
        bdetail["gather_buckets"])
    assert bdetail["compiles_steady_fullwidth"] <= 1
    assert bucketed["value"] is not None            # gates structural
    assert bdetail["ratio_gated"] is False          # smoke: no >=1.3x
    # bucketing must actually reduce the mean padded-read waste
    assert (bdetail["gather_read_waste_mean_bucketed"]
            < bdetail["gather_read_waste_mean_fullwidth"])
    # the stdout lines are the driver contract: parseable JSON, both
    # metrics present
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    metrics = [json.loads(ln)["metric"] for ln in lines]
    assert metrics[-2:] == ["serve_continuous_vs_static_speedup",
                            "serve_bucketed_gather_decode_speedup"]


@pytest.mark.slow
def test_serve_bench_full_bucketed_trace(capsys):
    """The full CPU short-context trace — the ISSUE 5 acceptance
    surface where the ≥1.3x bucketed decode ratio IS enforced in the
    line (slow tier: the model is sized so compute dominates
    dispatch)."""
    from benchmarks.serve_bench import bench_serve_bucketed

    result = bench_serve_bucketed(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.3
    assert result["detail"]["ratio_gated"] is True
    assert result["detail"]["exact_match"] is True
