"""Tier-1 smoke of ``bench.py --serve`` (benchmarks/serve_bench.py):
the CPU gate runs the real measured body at smoke scale and pins the
structural guarantees — greedy exactness vs the static baseline and
ZERO new compiles across the measured (post-warmup) serving run. The
≥2x speedup acceptance is measured by the full ``bench.py --serve``
trace, not here: at smoke scale dispatch overhead dominates and the
ratio is noise."""

import json

from huggingface_sagemaker_tensorflow_distributed_tpu import obs


def test_serve_bench_smoke(capsys, tmp_path):
    from benchmarks.serve_bench import bench_serve

    obs.reset(out_dir=str(tmp_path / "telemetry"), enabled=True)
    try:
        result = bench_serve(smoke=True)
    finally:
        obs.reset()
    detail = result["detail"]
    assert detail["exact_match"] is True
    assert detail["compiles_steady"] == 0
    assert result["value"] > 0 and detail["tokens"] > 0
    assert detail["ttft_p99_s"] >= detail["ttft_p50_s"] > 0
    assert 0 < detail["kv_peak_utilization"] <= 1
    # the stdout line is the driver contract: one parseable JSON line
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    parsed = json.loads(lines[-1])
    assert parsed["metric"] == "serve_continuous_vs_static_speedup"
