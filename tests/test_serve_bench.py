"""Tier-1 smoke of ``bench.py --serve`` (benchmarks/serve_bench.py):
the CPU gate runs the real measured bodies at smoke scale and pins the
structural guarantees — greedy exactness vs the static baseline,
bucketed-vs-full-width output identity, speculative-vs-plain output
identity, and compile flatness across the measured (post-warmup)
serving runs. The speedup/ratio acceptances (≥2x continuous-vs-static,
≥1.3x bucketed decode, ≥1.5x speculative decode) are measured by the
full ``bench.py --serve`` traces — exercised here only under the
``slow`` marker: at smoke scale dispatch overhead dominates and the
ratios are noise."""

import json

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs


def test_serve_bench_smoke(capsys, tmp_path):
    from benchmarks.serve_bench import bench_serve

    obs.reset(out_dir=str(tmp_path / "telemetry"), enabled=True)
    try:
        (mixed, bucketed, spec, prefix, paged, overlap, tp, router,
         open_loop, kv_swap, disagg, slo_adm) = bench_serve(smoke=True)
    finally:
        obs.reset()
    detail = mixed["detail"]
    assert detail["exact_match"] is True
    # compile flatness: the warm pass precompiles every bucket, so the
    # measured window sees 0 (the gate itself allows <= #buckets)
    assert detail["compiles_steady"] == 0
    assert mixed["value"] > 0 and detail["tokens"] > 0
    assert detail["ttft_p99_s"] >= detail["ttft_p50_s"] > 0
    assert 0 < detail["kv_peak_utilization"] <= 1
    assert 0 <= detail["gather_read_waste_mean"] <= 1
    # the ISSUE 10 phase decomposition rides the detail line: a bench
    # regression names the PHASE, not just the ratio — fractions of
    # summed request e2e that close to 1 within rounding
    phases = [detail[f"{ph}_time_frac"] for ph in
              ("queue", "prefill", "decode", "preempted", "overhead")]
    assert all(isinstance(v, (int, float)) for v in phases)
    assert all(-0.01 <= v <= 1.0 for v in phases)
    assert sum(phases) == pytest.approx(1.0, abs=0.02)
    assert detail["decode_time_frac"] > 0
    assert detail["queue_wait_p99_s"] >= 0

    bdetail = bucketed["detail"]
    assert bdetail["exact_match"] is True           # bucketed == full
    assert bdetail["compiles_steady_bucketed"] <= len(
        bdetail["gather_buckets"])
    assert bdetail["compiles_steady_fullwidth"] <= 1
    assert bucketed["value"] is not None            # gates structural
    assert bdetail["ratio_gated"] is False          # smoke: no >=1.3x
    # bucketing must actually reduce the mean padded-read waste
    assert (bdetail["gather_read_waste_mean_bucketed"]
            < bdetail["gather_read_waste_mean_fullwidth"])
    # the ISSUE 6 speculative line: structural gates enforced at smoke
    # scale (exactness vs the plain engine, compile flatness), the
    # ≥1.5x ratio only on the full CPU trace (smoke is dispatch-bound)
    sdetail = spec["detail"]
    assert sdetail["exact_match"] is True           # spec == plain
    assert sdetail["compiles_steady_speculative"] <= \
        sdetail["warmed_variants_speculative"]
    assert sdetail["compiles_steady_plain"] <= \
        sdetail["warmed_variants_plain"]
    assert spec["value"] is not None                # gates structural
    assert sdetail["ratio_gated"] is False          # smoke: no >=1.5x
    # the skip-exact fixture really is high-acceptance, and the window
    # accounting is consistent with it
    assert sdetail["acceptance_rate"] >= 0.9
    assert 1.0 <= sdetail["accepted_per_window"] <= sdetail["window_ceiling"]
    assert 0 <= sdetail["verify_read_waste_mean"] <= 1
    # the ISSUE 8 prefix-cache line: structural gates enforced at smoke
    # scale (on/off output identity, zero new compiled variants on the
    # hit path, block conservation, a genuinely cache-friendly trace),
    # the ≥2x TTFT ratio only on the full CPU trace (smoke is
    # dispatch-bound)
    pdetail = prefix["detail"]
    assert pdetail["exact_match"] is True           # cache on == off
    assert pdetail["block_conservation"] is True
    assert pdetail["compiles_steady_on"] == 0       # hit path mints none
    assert pdetail["compiles_steady_off"] == 0
    assert prefix["value"] is not None              # gates structural
    assert pdetail["ratio_gated"] is False          # smoke: no >=2x
    assert pdetail["cache_hit_rate"] >= 0.5
    assert pdetail["blocks_shared_peak"] > 0        # sharing really ran
    assert pdetail["prefix_cached_tokens"] > 0
    # the ISSUE 9 paged-kernel line: structural gates enforced at smoke
    # scale (each side token-exact vs its own generate_causal oracle,
    # compile flatness, the EXACT per-step byte halving from the
    # engine's kv_bytes_read accounting), the ≥1.2x ratio only on the
    # full CPU trace (smoke is dispatch-bound)
    kdetail = paged["detail"]
    assert kdetail["exact_match_fp"] is True
    assert kdetail["exact_match_int8"] is True
    assert kdetail["compiles_steady_fp"] <= len(kdetail["gather_buckets"])
    assert kdetail["compiles_steady_int8"] <= len(
        kdetail["gather_buckets"])
    assert paged["value"] is not None               # gates structural
    assert kdetail["ratio_gated"] is False          # smoke: no >=1.2x
    assert 0 < kdetail["kv_bytes_ratio"] <= 0.6     # bytes REALLY halve
    assert (kdetail["kv_token_bytes_int8"]
            < kdetail["kv_token_bytes_fp"])
    # the ISSUE 12 dispatch-ahead line: structural gates enforced at
    # smoke scale (overlap-on output == overlap-off output, compile
    # flatness per side — the pipeline is host-side restructuring
    # only), the ≥1.15x ratio + strict overhead reduction only on the
    # full CPU trace
    odetail = overlap["detail"]
    assert odetail["exact_match"] is True           # on == off
    # one flatness window spans every measured pass of both modes
    assert odetail["compiles_steady"] <= len(odetail["gather_buckets"])
    assert overlap["value"] is not None             # gates structural
    assert odetail["ratio_gated"] is False          # smoke: no >=1.15x
    # both sides ran timeline-on: the phase decomposition is this
    # line's evidence, so the fractions must be present and sane
    for key in ("overhead_time_frac_overlap",
                "overhead_time_frac_serial"):
        assert isinstance(odetail[key], (int, float))
        assert -0.01 <= odetail[key] <= 1.0
    assert odetail["overlap_flushes"] >= 0
    # the ISSUE 13 tensor-parallel capacity line: EVERY gate on it is
    # deterministic capacity arithmetic, so unlike the wall-clock
    # ratio lines the full acceptance is enforced at smoke scale too —
    # TP=2 output token-identical to TP=1, per-device bytes/token
    # exactly halved, admission depth doubled on the same per-device
    # budget, compile flatness per side (sharding mints no variants)
    tdetail = tp["detail"]
    assert tp.get("error") is None
    assert tp["value"] is not None and tp["value"] >= 2.0
    assert tdetail["exact_match"] is True
    assert tdetail["ratio_gated"] is True
    assert 0 < tdetail["kv_pool_bytes_per_device_ratio"] <= 0.55
    assert (tdetail["admission_depth_tp"]
            >= 2 * tdetail["admission_depth_base"])
    assert tdetail["num_blocks_tp"] > tdetail["num_blocks_base"]
    assert tdetail["compiles_steady_tp"] <= len(
        tdetail["gather_buckets"])
    assert tdetail["compiles_steady_base"] <= len(
        tdetail["gather_buckets"])
    # the ISSUE 14 multi-replica router line: every scale-out gate a
    # shared CPU can honestly certify is deterministic and enforced at
    # smoke scale too — token identity per request across all three
    # placement policies, fleet admission depth exactly 2x one
    # engine's, affinity hit rate >= round-robin's on the templated
    # multi-family trace, least-loaded imbalance bounded, compile
    # flatness (replicas share the jitted steps); only the
    # tokens/sec parity ratio waits for the full trace
    rdetail = router["detail"]
    assert router.get("error") is None
    assert router["value"] is not None
    assert rdetail["ratio_gated"] is False          # smoke: no floor
    assert rdetail["exact_match"] is True
    assert rdetail["admission_depth_ratio"] >= 2.0
    assert (rdetail["admission_depth_fleet"]
            >= 2 * rdetail["admission_depth_single"])
    assert 1.0 <= rdetail["replica_load_imbalance"] \
        <= rdetail["imbalance_bound"]
    assert (rdetail["cache_hit_rate_affinity"]
            >= rdetail["cache_hit_rate_round_robin"])
    assert rdetail["cache_hit_rate_affinity"] > 0
    assert rdetail["compiles_steady"] <= 2 * len(
        rdetail["gather_buckets"])
    # the ISSUE 16 open-loop goodput line: EVERY gate on it is
    # deterministic (virtual clock), so the full acceptance is
    # enforced at smoke scale too — byte-identical replay across two
    # fresh runs of the same seeded schedule, attainment exactly 1.0
    # at the underload rate, strictly lower at the overload rate with
    # queue the dominant miss phase, compile flatness (arrival timing
    # is host-side only); the wall-clock knee sweep is full-trace-only
    gdetail = open_loop["detail"]
    assert open_loop.get("error") is None
    assert open_loop["value"] == 1.0                # attainment at λ_lo
    assert gdetail["replay_identical"] is True
    assert gdetail["attainment_lo"] == 1.0
    assert gdetail["attainment_hi"] < 1.0
    assert gdetail["dominant_miss_phase_hi"] == "queue"
    assert gdetail["miss_phases_hi"].get("queue", 0) > 0
    # overload REALLY queued: the deterministic backlog rider peaked
    # above the underload run's
    assert (gdetail["arrival_backlog_peak_hi"]
            > gdetail["arrival_backlog_peak_lo"])
    # goodput (deadline-meeting tokens) collapses under overload
    assert (gdetail["goodput_tokens_hi"]
            < gdetail["goodput_tokens_lo"])
    assert gdetail["compiles_steady"] <= 2 * len(
        gdetail["gather_buckets"])
    assert gdetail["wall_sweep"] == []              # smoke: no sleeps
    # the ISSUE 17 KV-hierarchy line: every structural gate is
    # deterministic and enforced at smoke scale too — token identity
    # across swap/recompute/tier-off, real preemption pressure, the
    # swap path actually used, the demotion tier's hit rate strictly
    # above evict-only's, strict compile flatness per side; only the
    # e2e p99 hierarchy-vs-pre-tier ratio waits for the full CPU trace
    wdetail = kv_swap["detail"]
    assert kv_swap.get("error") is None
    assert kv_swap["value"] is not None
    assert wdetail["ratio_gated"] is False          # smoke: no p99 gate
    assert wdetail["exact_match"] is True
    assert wdetail["preemptions_swap"] > 0
    assert wdetail["preemptions_recompute"] > 0
    assert wdetail["swap_outs"] > 0 and wdetail["swap_ins"] > 0
    assert wdetail["recompute_tokens_avoided"] > 0
    assert wdetail["swap_bytes"] > 0 and wdetail["restore_s"] >= 0
    assert wdetail["host_tier_hits_tier"] > 0
    assert (wdetail["cache_hit_rate_tier"]
            > wdetail["cache_hit_rate_off"])
    assert wdetail["compiles_steady_swap"] == 0     # strict: fixed geometry
    assert wdetail["compiles_steady_recompute"] == 0
    assert wdetail["compiles_steady_off"] == 0
    # the ISSUE 18 disaggregated prefill/decode line: the structural
    # gates are deterministic and enforced at smoke scale too — the
    # split fleet's outputs token-identical to the mixed fleet's,
    # byte-identical virtual replay, role separation airtight (zero
    # decode iterations on the prefill replica, zero prefill
    # dispatches on the decode replica), EVERY request crossing the
    # transport exactly once with real bytes moved, compile flatness
    # (migration reuses the swap-tier gather/scatter); only the ≥1.1x
    # attainment ratio + per-side no-worse claims wait for the full
    # CPU trace
    ddetail = disagg["detail"]
    assert disagg.get("error") is None
    assert disagg["value"] is not None
    assert ddetail["ratio_gated"] is False          # smoke: no >=1.1x
    assert ddetail["exact_match"] is True           # disagg == mixed
    assert ddetail["replay_identical"] is True
    assert ddetail["migrations"] == ddetail["requests"]
    assert ddetail["migration_bytes"] > 0
    assert ddetail["compiles_steady"] <= 2 * len(
        ddetail["gather_buckets"])
    # the per-role attribution rides the line: prefill rows own TTFT,
    # decode rows own TPOT + tokens/sec
    assert ddetail["per_role"]["prefill"]["ttft_p99_s"] > 0
    assert ddetail["per_role"]["decode"]["decode_tokens_per_sec"] > 0

    # the ISSUE 20 admission line: every deterministic gate holds at
    # smoke scale too — token identity across policies, bitwise
    # replay, deadline attainment ≥ fifo with misses strictly lower,
    # structured (counted, never silent) rate-limit rejections, and
    # ZERO compiled variants minted by reordering
    adetail = slo_adm["detail"]
    assert slo_adm.get("error") is None
    assert slo_adm["value"] is not None
    assert adetail["tokens_identical"] is True      # WHO, never WHAT
    assert adetail["replay_identical"] is True
    assert adetail["compiles_steady"] == 0
    assert (adetail["deadline_attainment_slo"]
            >= adetail["deadline_attainment_fifo"])
    assert (adetail["deadline_miss_frac_slo"]
            < adetail["deadline_miss_frac_fifo"])
    assert adetail["rate_limited"] > 0
    assert (adetail["rate_limited_served"] + adetail["rate_limited"]
            == adetail["requests"])
    # the stdout lines are the driver contract: parseable JSON, all
    # twelve metrics present
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    metrics = [json.loads(ln)["metric"] for ln in lines]
    assert metrics[-12:] == ["serve_continuous_vs_static_speedup",
                             "serve_bucketed_gather_decode_speedup",
                             "serve_speculative_decode_speedup",
                             "serve_prefix_cache_ttft_speedup",
                             "serve_paged_kernel_decode_speedup",
                             "serve_overlap_decode_speedup",
                             "serve_tp_shard_capacity",
                             "serve_router_scaleout",
                             "serve_open_loop_goodput",
                             "serve_kv_swap_vs_recompute",
                             "serve_disagg_goodput",
                             "serve_slo_admission_goodput"]


@pytest.mark.slow
def test_serve_bench_full_bucketed_trace(capsys):
    """The full CPU short-context trace — the ISSUE 5 acceptance
    surface where the ≥1.3x bucketed decode ratio IS enforced in the
    line (slow tier: the model is sized so compute dominates
    dispatch)."""
    from benchmarks.serve_bench import bench_serve_bucketed

    result = bench_serve_bucketed(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.3
    assert result["detail"]["ratio_gated"] is True
    assert result["detail"]["exact_match"] is True


@pytest.mark.slow
def test_serve_bench_full_speculative_trace(capsys):
    """The full CPU high-acceptance trace — the ISSUE 6 acceptance
    surface where the ≥1.5x speculative decode ratio IS enforced in
    the line (slow tier: both engines serve the whole trace twice)."""
    from benchmarks.serve_bench import bench_serve_speculative

    result = bench_serve_speculative(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.5
    assert result["detail"]["ratio_gated"] is True
    assert result["detail"]["exact_match"] is True
    assert result["detail"]["acceptance_rate"] >= 0.9


@pytest.mark.slow
def test_serve_bench_full_paged_kernel_trace(capsys):
    """The full CPU decode-dominated trace — the ISSUE 9 acceptance
    surface where the ≥1.2x int8-vs-fp decode ratio IS enforced in the
    line (measured 1.68x on this container; the per-step byte ratio
    ~0.28 is arithmetic and gated always)."""
    from benchmarks.serve_bench import bench_serve_paged_kernel

    result = bench_serve_paged_kernel(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.2
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["exact_match_fp"] is True
    assert detail["exact_match_int8"] is True
    assert detail["kv_bytes_ratio"] <= 0.6


@pytest.mark.slow
def test_serve_bench_full_overlap_trace(capsys):
    """The full CPU decode-dominated wide-batch trace — the ISSUE 12
    acceptance surface where the ≥1.15x dispatch-ahead decode ratio
    IS enforced in the line (measured 1.25-1.74x on this container)
    together with the strict overhead-fraction reduction: the
    decomposition PR 10 built must show the host overhead going
    CONCURRENT, not just the ratio moving."""
    from benchmarks.serve_bench import bench_serve_overlap

    result = bench_serve_overlap(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.15
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["exact_match"] is True
    assert (detail["overhead_time_frac_overlap"]
            < detail["overhead_time_frac_serial"])


@pytest.mark.slow
def test_serve_bench_full_tp_trace(capsys):
    """The full CPU tensor-parallel capacity trace — the ISSUE 13
    acceptance surface: ≥2x admission depth on the same per-device
    ``kv_pool_bytes``, per-device pool bytes/token ≤0.55x, TP=2 output
    token-identical to TP=1, one step compile per bucket per side. All
    deterministic gates (capacity arithmetic, not wall-clock), enforced
    in the line itself."""
    from benchmarks.serve_bench import bench_serve_tp

    result = bench_serve_tp(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 2.0
    detail = result["detail"]
    assert detail["exact_match"] is True
    assert detail["kv_pool_bytes_per_device_ratio"] <= 0.55
    assert (detail["admission_depth_tp"]
            >= 2 * detail["admission_depth_base"])
    assert detail["preemptions_tp"] == detail["preemptions_base"] == 0


@pytest.mark.slow
def test_serve_bench_full_router_trace(capsys):
    """The full CPU multi-replica router trace — the ISSUE 14
    acceptance surface: every deterministic scale-out gate (token
    identity per request across all three placements, 2x fleet
    admission depth, affinity >= round-robin hit rate, least-loaded
    imbalance bound, compile flatness) plus the aggregate decode
    tokens/sec parity floor, measured with the adjacent-pair scheme
    (measured 0.99-1.07x best-pair on this container; the floor is 0.8 —
    on one shared CPU device the fleet time-shares the chip, so the
    gate bounds router overhead and the Nx multiplication is banked
    for real multi-chip hardware)."""
    from benchmarks.serve_bench import bench_serve_router

    result = bench_serve_router(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 0.8
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["exact_match"] is True
    assert detail["admission_depth_ratio"] >= 2.0
    assert (detail["cache_hit_rate_affinity"]
            >= detail["cache_hit_rate_round_robin"])
    assert detail["replica_load_imbalance"] <= detail["imbalance_bound"]


@pytest.mark.slow
def test_serve_bench_full_open_loop_trace(capsys):
    """The full CPU open-loop trace — the ISSUE 16 surface with the
    wall-clock knee sweep included: the deterministic virtual-clock
    gates (replay identity, underload attainment 1.0, queue-bound
    overload, compile flatness) hold at full scale, and the wall
    sweep reports one attainment figure per swept rate (the knee
    itself is hardware-dependent and never gated)."""
    from benchmarks.serve_bench import bench_serve_open_loop

    result = bench_serve_open_loop(smoke=False)
    assert result.get("error") is None
    assert result["value"] == 1.0
    detail = result["detail"]
    assert detail["replay_identical"] is True
    assert detail["attainment_hi"] < 1.0
    assert detail["dominant_miss_phase_hi"] == "queue"
    assert len(detail["wall_sweep"]) == len(detail["wall_rates"]) > 0
    for row in detail["wall_sweep"]:
        assert 0.0 <= row["slo_attainment"] <= 1.0


@pytest.mark.slow
def test_serve_bench_full_prefix_trace(capsys):
    """The full CPU repeated-prefix trace — the ISSUE 8 acceptance
    surface where the ≥2x TTFT p50 ratio IS enforced in the line
    (slow tier: two primed engines serve the whole templated trace
    twice). Measured 4.2x on this container; the admission-depth win
    (shared template charged once) is asserted directionally."""
    from benchmarks.serve_bench import bench_serve_prefix

    result = bench_serve_prefix(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 2.0
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["exact_match"] is True
    assert detail["block_conservation"] is True
    assert detail["cache_hit_rate"] >= 0.8
    # effective KV capacity multiplied: the tight pool holds every
    # slot's request with the cache on, a fraction of them without
    assert (detail["admission_depth_cache_on"]
            > detail["admission_depth_cache_off"])


@pytest.mark.slow
def test_serve_bench_full_kv_swap_trace(capsys):
    """The full CPU forced-thrash trace — the ISSUE 17 acceptance
    surface where the e2e p99 latency claim IS enforced in the line:
    the full hierarchy (swap preemption + demotion tier) must beat
    the pre-tier evict-only engine at the tail by ≥ 1.2×
    (value = p99_off / p99_swap), on top of the deterministic gates
    (identity, swap usage, demotion hit-rate win, compile flatness)
    the smoke tier already enforces. The always-vs-never policy
    ratio is reported but never gated — the demotion tier sits in
    both of those arms, so they are at structural parity on CPU."""
    from benchmarks.serve_bench import bench_serve_kv_swap

    result = bench_serve_kv_swap(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.2
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["p99_ratio_vs_off"] == result["value"]
    assert detail["p99_ratio_vs_tier_recompute"] > 0  # reported, un-gated
    assert detail["exact_match"] is True
    assert detail["swap_outs"] > 0
    assert detail["recompute_tokens_avoided"] > 0
    assert detail["cache_hit_rate_tier"] > detail["cache_hit_rate_off"]


@pytest.mark.slow
def test_serve_bench_full_disagg_trace(capsys):
    """The full CPU prefill-heavy open-loop trace — the ISSUE 18
    acceptance surface where the ratio IS enforced in the line: a
    1 prefill + 1 decode pair must beat 2 mixed replicas on SLO
    attainment by ≥ 1.1× (measured 4.0x on this container — the mixed
    fleet's slot-cycle capacity collapses under the arrival rate while
    the prefill-only replica's slots recycle at migration), with the
    per-side no-worse claims (prefill-side TTFT p99, decode-side
    tokens/sec ≥ 0.9x) and every deterministic gate the smoke tier
    already pins."""
    from benchmarks.serve_bench import bench_serve_disagg

    result = bench_serve_disagg(smoke=False)
    assert result.get("error") is None
    assert result["value"] is not None and result["value"] >= 1.1
    detail = result["detail"]
    assert detail["ratio_gated"] is True
    assert detail["exact_match"] is True
    assert detail["replay_identical"] is True
    assert detail["migrations"] == detail["requests"]
    assert detail["ttft_p99_s_disagg"] <= detail["ttft_p99_s_mixed"]
    assert (detail["decode_tokens_per_sec_disagg"]
            >= 0.9 * detail["decode_tokens_per_sec_mixed"])


@pytest.mark.slow
def test_serve_bench_full_slo_admission_trace(capsys):
    """The full CPU open-loop trace past the fifo capacity knee — the
    ISSUE 20 acceptance surface where the ≥1.1x deadline-attainment
    ratio IS enforced in the line (measured 1.17x on this container:
    fifo head-blocks interactive work behind loose-deadline batch
    rows), with strictly fewer misses and every deterministic gate the
    smoke tier already pins."""
    from benchmarks.serve_bench import bench_serve_slo_admission

    result = bench_serve_slo_admission(smoke=False)
    assert result.get("error") is None
    detail = result["detail"]
    assert result["value"] is not None
    assert result["value"] >= 1.1 * result["vs_baseline"] > 0
    assert detail["tokens_identical"] is True
    assert detail["replay_identical"] is True
    assert detail["compiles_steady"] == 0
    assert (detail["deadline_miss_frac_slo"]
            < detail["deadline_miss_frac_fifo"])
    assert detail["rate_limited"] > 0
