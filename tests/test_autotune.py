"""Input-pipeline autotuning (ISSUE 2 tentpole #1): the prefetch-depth
controller (deterministic synthetic producer/consumer waits — no clocks),
the adaptive queue it drives, the live PrefetchIterator wiring, the
streaming read coalescer, and the compile-budget alert + bucket-ladder
cap."""

import queue
import threading

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.data.autotune import (
    ENV_AUTOTUNE,
    ENV_MAX,
    ENV_MEM_MB,
    PrefetchAutotuner,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.pipeline import (
    ArrayDataset,
    PrefetchIterator,
    ShardedBatcher,
    _AdaptiveQueue,
)


# -- controller (pure: synthetic cumulative waits drive every decision) ------

class _FakePipeline:
    """Deterministic fake-clock producer/consumer: each consumed batch
    adds fixed per-batch waits to the cumulative stats — exactly the
    numbers ``_PrefetchStats`` would accumulate, without threads."""

    def __init__(self, tuner, consumer_wait_per_batch, producer_wait_per_batch,
                 batch_bytes=1000):
        self.tuner = tuner
        self.cw = consumer_wait_per_batch
        self.pw = producer_wait_per_batch
        self.batch_bytes = batch_bytes
        self.consumed = 0
        self.producer_wait = 0.0
        self.consumer_wait = 0.0
        self.decisions = []

    def run(self, batches):
        for _ in range(batches):
            self.consumed += 1
            # waits scale down once the queue is deep enough to cover
            # the burstiness: model the consumer wait as inversely
            # proportional to depth beyond the fixed floor
            self.consumer_wait += self.cw * (2.0 / max(self.tuner.depth, 1))
            self.producer_wait += self.pw
            d = self.tuner.observe(self.producer_wait, self.consumer_wait,
                                   self.consumed, self.batch_bytes)
            if d is not None:
                self.decisions.append(d)


def test_controller_grows_to_cap_on_input_bound():
    tuner = PrefetchAutotuner(min_depth=1, max_depth=16, window=4,
                              initial_depth=2)
    pipe = _FakePipeline(tuner, consumer_wait_per_batch=0.01,
                         producer_wait_per_batch=0.0)
    pipe.run(64)
    assert tuner.depth == 16                      # converged to the cap
    reasons = {r for _, r in pipe.decisions}
    assert reasons == {"input_bound"}
    # growth is monotone: 2 -> 4 -> 8 -> 16
    assert [d for d, _ in pipe.decisions] == [4, 8, 16]


def test_controller_saturates_on_steadily_slow_producer():
    """A producer that is simply slower than the consumer (constant
    consumer wait regardless of depth) must NOT ratchet to the cap:
    the first no-gain growth latches saturation."""
    tuner = PrefetchAutotuner(min_depth=1, max_depth=64, window=4,
                              initial_depth=2)
    consumed, cw = 0, 0.0
    for _ in range(100):
        consumed += 1
        cw += 0.003                  # depth-independent starvation
        tuner.observe(0.0, cw, consumed, 1000)
    assert tuner.depth == 4          # one speculative grow, then latched
    # regime change: producer catches up (consumer stops waiting), then
    # real burstiness resumes — growth is allowed again
    for _ in range(16):
        consumed += 1
        tuner.observe(0.0, cw, consumed, 1000)   # dc == 0: clears latch
    pipe = _FakePipeline(tuner, consumer_wait_per_batch=0.01,
                         producer_wait_per_batch=0.0)
    pipe.consumed = consumed
    pipe.consumer_wait = cw
    pipe.run(60)
    assert tuner.depth > 4


def test_controller_shrinks_with_hysteresis_when_compute_bound():
    tuner = PrefetchAutotuner(min_depth=1, max_depth=16, window=4,
                              initial_depth=8, shrink_patience=3)
    pipe = _FakePipeline(tuner, consumer_wait_per_batch=0.0,
                         producer_wait_per_batch=0.01)
    # fewer than patience windows: no shrink yet (hysteresis)
    pipe.run(8)
    assert tuner.depth == 8 and not pipe.decisions
    pipe.run(120)
    assert tuner.depth == 1                       # decayed to the floor
    assert all(r == "compute_bound" for _, r in pipe.decisions)
    # one step per decision, never more (slow shrink)
    depths = [d for d, _ in pipe.decisions]
    assert depths == sorted(depths, reverse=True)
    assert all(a - b == 1 for a, b in zip(depths, depths[1:]))


def test_controller_memory_cap_bounds_depth():
    tuner = PrefetchAutotuner(min_depth=1, max_depth=64, window=2,
                              initial_depth=2,
                              mem_budget_bytes=10 * 1000)
    pipe = _FakePipeline(tuner, consumer_wait_per_batch=0.01,
                         producer_wait_per_batch=0.0, batch_bytes=1000)
    pipe.run(64)
    assert tuner.depth == 10                      # 10kB budget / 1kB batch
    assert tuner.hard_cap() == 10
    # a bigger batch shape arrives (bucket ladder): immediate clamp
    d = tuner.observe(pipe.producer_wait, pipe.consumer_wait,
                      pipe.consumed + 1, batch_bytes=2000)
    assert d == (5, "mem_cap")


def test_controller_noise_floor_holds_depth():
    tuner = PrefetchAutotuner(window=2, initial_depth=4)
    # microscopic waits on both sides: neither grow nor shrink
    for i in range(1, 41):
        assert tuner.observe(i * 1e-6, i * 1e-6, i) is None
    assert tuner.depth == 4


def test_from_env(monkeypatch):
    monkeypatch.setenv(ENV_AUTOTUNE, "0")
    assert PrefetchAutotuner.from_env() is None
    monkeypatch.setenv(ENV_AUTOTUNE, "1")
    monkeypatch.setenv(ENV_MAX, "7")
    monkeypatch.setenv(ENV_MEM_MB, "1")
    tuner = PrefetchAutotuner.from_env()
    assert tuner.max_depth == 7
    assert tuner.mem_budget_bytes == 1 << 20


# -- adaptive queue ----------------------------------------------------------

def test_adaptive_queue_capacity_change_unblocks_producer():
    q = _AdaptiveQueue(1)
    q.put("a")
    with pytest.raises(queue.Full):
        q.put("b", timeout=0.05)
    unblocked = threading.Event()

    def producer():
        q.put("b", timeout=5)
        unblocked.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    q.set_capacity(2)                 # wakes the blocked producer
    assert unblocked.wait(timeout=5)
    assert q.get() == "a" and q.get() == "b"
    with pytest.raises(queue.Empty):
        q.get_nowait()


def test_prefetch_iterator_autotuned_end_to_end():
    """Live threads: an autotuned iterator delivers every item in order
    and the achieved depth stays within [min, hard_cap]."""
    tuner = PrefetchAutotuner(min_depth=1, max_depth=8, window=2)
    it = PrefetchIterator(iter([{"x": np.zeros(4)} for _ in range(50)]),
                          autotuner=tuner)
    got = [item for item in it]
    assert len(got) == 50
    assert 1 <= it.depth <= tuner.hard_cap()


def test_batcher_carries_converged_depth_across_epochs():
    """A new epoch's controller starts from the previous epoch's
    converged depth, not back at 2."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )

    mesh = build_mesh(MeshConfig())
    ds = ArrayDataset({
        "input_ids": np.zeros((32, 8), np.int32),
        "attention_mask": np.ones((32, 8), np.int32),
        "labels": np.zeros(32, np.int32),
    })
    b = ShardedBatcher(ds, 8, mesh, shuffle=False,
                       process_index=0, process_count=1)
    it0 = b.global_arrays(0)
    assert b._auto_tuner is not None
    it0.close()
    b._auto_tuner.depth = 8          # pretend epoch 0 converged here
    it1 = b.global_arrays(1)
    assert b._auto_tuner.depth == 8  # fresh controller, seeded depth
    assert it1.depth == 8
    it1.close()


# -- streaming read coalescer ------------------------------------------------

def test_line_corpus_coalesced_reads_adapt_and_stay_exact(tmp_path):
    """Near-adjacent rows read in one call; sparse access shrinks the
    gap (waste-driven), dense access grows it back — and the decoded
    rows are byte-identical either way."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.streaming import (
        LineCorpus,
    )

    path = tmp_path / "c.txt"
    lines = [f"row {i} " + "x" * (i % 97) for i in range(400)]
    path.write_text("\n".join(lines) + "\n")
    corpus = LineCorpus(str(path))
    # dense (adjacent) window: big gap is all signal — it grows
    g0 = corpus._coalesce_gap
    dense = np.arange(64)
    assert corpus._read_lines(dense) == [lines[i] for i in dense]
    assert corpus._coalesce_gap >= g0
    # sparse far-apart rows: coalescing wastes most bytes — gap shrinks
    sparse = np.arange(0, 400, 97)
    for _ in range(6):
        assert corpus._read_lines(sparse) == [lines[i] for i in sparse]
    assert corpus._coalesce_gap < g0
    # duplicates and reverse order still come back in idx order
    tricky = np.asarray([5, 5, 300, 2])
    assert corpus._read_lines(tricky) == [lines[5], lines[5],
                                          lines[300], lines[2]]


# -- compile budget (ROADMAP "Compile-time budget") --------------------------

@pytest.fixture()
def obs_dir(tmp_path):
    out = tmp_path / "telemetry"
    obs.reset(out_dir=str(out), enabled=True)
    yield out
    obs.reset()


def _events(out):
    path = out / "events.jsonl"
    if not path.exists():
        return []
    return [e for _, e, err in obs.iter_events(str(path)) if err is None]


def test_compile_budget_alert_and_latch(obs_dir, capsys):
    tracker = obs.compile_tracker()
    tracker.budget_s = 0.5
    assert not obs.compile_budget_exceeded()
    tracker.observe("backend_compile_time", 0.3)
    assert not obs.compile_budget_exceeded()
    tracker.observe("backend_compile_time", 0.4)   # crosses 0.5s
    assert obs.compile_budget_exceeded()
    tracker.observe("backend_compile_time", 0.4)   # alert fires ONCE
    alerts = [e for e in _events(obs_dir) if e["type"] == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["name"] == "compile_budget"
    assert "HSTD_COMPILE_BUDGET_S" in alerts[0]["message"]
    assert "COMPILE BUDGET" in capsys.readouterr().err
    # the events file validates against the schema with the new types
    count, errors = obs.validate_events_file(str(obs_dir / "events.jsonl"))
    assert not errors and count >= 4


def test_bucket_ladder_capped_when_over_budget(obs_dir):
    """Once the budget latches, the batcher stops minting NEW bucket
    widths: unseen rungs widen to an already-used width (or the full
    column width), so no further compiles happen."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )

    n, width = 16, 64
    ids = np.zeros((n, width), np.int32)
    mask = np.zeros((n, width), np.int32)
    # batch 0 rows: length 10 (bucket 16); batch 1 rows: length 40
    # (bucket 48 — a NEW width once the budget is blown)
    for i in range(n):
        L = 10 if i < 8 else 40
        ids[i, :L] = 7
        mask[i, :L] = 1
    ds = ArrayDataset({"input_ids": ids, "attention_mask": mask,
                       "labels": np.zeros(n, np.int32)})
    mesh = build_mesh(MeshConfig())

    def widths():
        b = ShardedBatcher(ds, 8, mesh, shuffle=False,
                           bucket_sizes=[16, 32, 48, 64],
                           process_index=0, process_count=1)
        return [batch["input_ids"].shape[1] for batch in b.local_batches(0)]

    assert widths() == [16, 48]                   # unconstrained ladder
    tracker = obs.compile_tracker()
    tracker.budget_s = 0.1
    tracker.observe("backend_compile_time", 1.0)  # blow the budget
    # a FRESH batcher (no used widths yet) must fall back to full width
    # for both batches instead of minting 16 then 48
    assert widths() == [64, 64]


def test_bucket_ladder_multihost_caps_only_on_agreement(obs_dir):
    """Multi-host ladder capping (ROADMAP leftover from PR 2): a
    process_count > 1 batcher must IGNORE the host-local budget latch —
    the budget crosses at a host-local instant, and bucket widths
    derive from shared state, so one host capping alone would ship
    mismatched shapes into collectives. It caps only once the trainer's
    epoch-boundary collective (``agree_compile_budget_crossed``) has
    latched the agreed flag on every host together."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.distributed import (
        agree_compile_budget_crossed,
    )

    n, width = 16, 64
    ids = np.zeros((n, width), np.int32)
    mask = np.zeros((n, width), np.int32)
    for i in range(n):
        L = 10 if i < 8 else 40
        ids[i, :L] = 7
        mask[i, :L] = 1
    ds = ArrayDataset({"input_ids": ids, "attention_mask": mask,
                       "labels": np.zeros(n, np.int32)})
    mesh = build_mesh(MeshConfig())

    def widths():
        b = ShardedBatcher(ds, 8, mesh, shuffle=False,
                           bucket_sizes=[16, 32, 48, 64],
                           process_index=0, process_count=2)
        return [batch["input_ids"].shape[1] for batch in b.local_batches(0)]

    tracker = obs.compile_tracker()
    tracker.budget_s = 0.1
    tracker.observe("backend_compile_time", 1.0)   # local crossing only
    assert obs.compile_budget_exceeded()
    assert not obs.compile_budget_capped(2)
    assert widths() == [16, 48]                    # still minting
    # the epoch-boundary agreement (single-process: trivially local)
    assert agree_compile_budget_crossed(obs.compile_budget_exceeded())
    obs.set_compile_budget_agreed()
    assert obs.compile_budget_capped(2)
    assert widths() == [64, 64]                    # capped, all hosts alike
