"""GPT-2 family tests: HF torch numerics parity (fp32 CPU, the
SURVEY.md §7 stage-2 bar), Conv1D conversion fidelity both ways, KV-cache
incremental decode vs full forward, left-padded generation, and the
causal-lm training path on the 8-device mesh."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")
import transformers  # noqa: E402

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (  # noqa: E402
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (  # noqa: E402
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models import auto as auto_models  # noqa: E402
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (  # noqa: E402
    generate_causal,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer  # noqa: E402

TOL = 2e-4


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=3, n_head=4,
        n_inner=64, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        bos_token_id=1, eos_token_id=2, pad_token_id=2)
    d = str(tmp_path_factory.mktemp("gpt2"))
    m = transformers.GPT2LMHeadModel(cfg).eval()
    m.save_pretrained(d)
    return d, m, cfg


def _inputs(batch=3, seq=10, vocab=128, seed=0):
    r = np.random.RandomState(seed)
    ids = r.randint(3, vocab, (batch, seq))
    mask = np.ones((batch, seq), np.int64)
    return ids, mask


def test_gpt2_lm_parity(gpt2_dir):
    d, m, _ = gpt2_dir
    model, params, family, cfg = auto_models.from_pretrained(d, task="causal-lm")
    assert family == "gpt2"
    ids, mask = _inputs()
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        deterministic=True)
    np.testing.assert_allclose(np.asarray(j_out), t_out.logits.numpy(),
                               atol=TOL, rtol=1e-3)


def test_gpt2_parity_with_left_padding(gpt2_dir):
    """Left-padded batch: positions from the mask cumsum must match HF's
    position_ids handling."""
    d, m, _ = gpt2_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    ids, mask = _inputs()
    mask[1, :4] = 0
    ids[1, :4] = 2
    pos = np.clip(np.cumsum(mask, axis=1) - 1, 0, None)
    with torch.no_grad():
        t_out = m(input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask),
                  position_ids=torch.tensor(pos))
    j_out = model.apply({"params": params}, jnp.asarray(ids), jnp.asarray(mask),
                        position_ids=jnp.asarray(pos), deterministic=True)
    # padded rows produce garbage at pad positions on both sides; compare
    # real positions only
    j, t = np.asarray(j_out), t_out.logits.numpy()
    np.testing.assert_allclose(j[mask > 0], t[mask > 0], atol=TOL, rtol=1e-3)


def test_gpt2_export_roundtrip(gpt2_dir, tmp_path):
    """Our export loads back into HF torch with identical logits."""
    d, m, hf_cfg = gpt2_dir
    model, params, family, cfg = auto_models.from_pretrained(d, task="causal-lm")
    out = str(tmp_path / "export")
    auto_models.save_pretrained(out, params, family, cfg)
    m2 = transformers.GPT2LMHeadModel.from_pretrained(out).eval()
    ids, mask = _inputs()
    with torch.no_grad():
        a = m(input_ids=torch.tensor(ids)).logits.numpy()
        b = m2(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(b, a, atol=1e-5)


def test_gpt2_incremental_decode_matches_full(gpt2_dir):
    """Greedy generation via the KV cache must equal argmax continuation
    computed with full forward passes."""
    d, m, _ = gpt2_dir
    model, params, _, cfg = auto_models.from_pretrained(d, task="causal-lm")
    ids, mask = _inputs(batch=2, seq=6)
    new = 5
    got = np.asarray(generate_causal(model, params, ids, mask,
                                     max_new_tokens=new))

    # reference: repeated full forwards (no cache)
    cur = ids.copy()
    finished = np.zeros(2, bool)
    want = []
    for _ in range(new):
        logits = model.apply({"params": params}, jnp.asarray(cur),
                             jnp.ones_like(jnp.asarray(cur)),
                             deterministic=True)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], -1)).astype(np.int64)
        nxt = np.where(finished, cfg.pad_token_id, nxt)
        finished |= nxt == cfg.eos_token_id
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_gpt2_generate_left_padded(gpt2_dir):
    """A left-padded prompt generates the same continuation as the same
    prompt without padding (pads fully masked from the cache)."""
    d, _, _ = gpt2_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    prompt = np.asarray([[5, 9, 17, 33]])
    padded = np.asarray([[2, 2, 5, 9, 17, 33]])
    pmask = np.asarray([[0, 0, 1, 1, 1, 1]])
    a = np.asarray(generate_causal(model, params, prompt, max_new_tokens=4))
    b = np.asarray(generate_causal(model, params, padded, pmask,
                                   max_new_tokens=4))
    np.testing.assert_array_equal(a, b)


def test_gpt2_generate_right_padded(gpt2_dir):
    """Right-padded prompts (this repo's tokenizers pad right) generate
    the same continuation as the unpadded prompt: the prefill gathers
    each row's last REAL token, not the trailing pad."""
    d, _, _ = gpt2_dir
    model, params, _, _ = auto_models.from_pretrained(d, task="causal-lm")
    prompt = np.asarray([[5, 9, 17, 33]])
    padded = np.asarray([[5, 9, 17, 33, 2, 2]])
    pmask = np.asarray([[1, 1, 1, 1, 0, 0]])
    a = np.asarray(generate_causal(model, params, prompt, max_new_tokens=4))
    b = np.asarray(generate_causal(model, params, padded, pmask,
                                   max_new_tokens=4))
    np.testing.assert_array_equal(a, b)


def test_gpt2_causal_lm_training_learns(devices8):
    """End-to-end causal-lm task on the dp8 mesh: loss decreases on a
    tiny synthetic corpus."""
    tok = WordHashTokenizer(vocab_size=256)
    texts, _ = synthetic_text_classification(64, seed=0)
    ds = ArrayDataset.from_lm_texts(tok, texts, max_length=16)
    mesh = build_mesh(MeshConfig(), devices=devices8)
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
        Gpt2LMHeadModel,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params

    model_cfg = Gpt2Config(vocab_size=256, hidden_size=32, num_layers=2,
                           num_heads=4, intermediate_size=64,
                           max_position_embeddings=16, hidden_dropout=0.0,
                           embd_dropout=0.0, attention_dropout=0.0)
    model = Gpt2LMHeadModel(model_cfg)
    params = init_params(model, model_cfg)
    cfg = TrainConfig(task="causal-lm", dtype="float32", learning_rate=5e-3,
                      scale_lr_by_world_size=False, log_every_steps=0,
                      rng_impl="threefry", epochs=2)
    trainer = Trainer(cfg, model, params, mesh)
    batcher = ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0)
    history = trainer.fit(batcher)
    assert history["loss"][-1] < history["loss"][0] * 0.9


def test_gpt2_rejects_wrong_task(gpt2_dir):
    d, _, _ = gpt2_dir
    with pytest.raises(ValueError, match="causal-lm"):
        auto_models.from_pretrained(d, task="seq-cls")
