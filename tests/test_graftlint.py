"""graftlint tests (ISSUE 15): one positive + one negative fixture per
rule (R1–R7), pragma suppression + mandatory-reason hygiene, byte
determinism across input orderings, the CLI exit-code contract
(0 clean / 1 bad input / 2 findings, matching ``obsctl diff``), and —
the teeth — the tier-1 gate that runs the full linter over the real
tree with zero unsuppressed findings, plus R1's static jax-free-zone
reachability as the PRIMARY no-jax gate (the subprocess poison runs
are now the slow-tier backstop).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
    PACKAGE,
    LintInputError,
    lint_text,
    load_project,
    render_json,
    render_text,
    run_lint,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.rules import (
    RULES,
    check_r1,
    r1_reachability,
    r1_zone_roots,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_GRAFTLINT = os.path.join(_REPO, "scripts", "graftlint.py")
_OBSCTL = os.path.join(_REPO, "scripts", "obsctl.py")


def make_tree(tmp_path, files, readme=None):
    """A minimal repo layout the loader accepts: files are
    repo-relative paths under a package named like the real one."""
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    pkg_init = tmp_path / PACKAGE / "__init__.py"
    if not pkg_init.exists():
        pkg_init.parent.mkdir(parents=True, exist_ok=True)
        pkg_init.write_text("")
    if readme is not None:
        (tmp_path / "README.md").write_text(textwrap.dedent(readme))
    return str(tmp_path)


def active(result, rule=None):
    out = [f for f in result.findings if not f.suppressed]
    if rule is not None:
        out = [f for f in out if f.rule == rule]
    return out


# -- R1: jax-free zones -------------------------------------------------------

def test_r1_fires_on_transitive_import_time_jax(tmp_path):
    root = make_tree(tmp_path, {
        f"{PACKAGE}/obs/__init__.py": "from {p} import util\n".format(
            p=PACKAGE),
        f"{PACKAGE}/util.py": "import jax\n",
    })
    hits = active(run_lint(root, rules=["R1"]), "R1")
    assert len(hits) == 1
    assert hits[0].path == f"{PACKAGE}/util.py"
    assert "jax" in hits[0].message and "obs" in hits[0].message

def test_r1_lazy_import_is_legal(tmp_path):
    root = make_tree(tmp_path, {
        f"{PACKAGE}/obs/__init__.py": (
            "def heavy():\n    import jax\n    return jax\n"),
    })
    assert active(run_lint(root, rules=["R1"]), "R1") == []


# -- R2: host syncs on the hot path -------------------------------------------

_ENGINE = f"{PACKAGE}/serve/engine.py"

def test_r2_fires_on_hot_loop_fetch(tmp_path):
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(pending):
            return jax.device_get(pending)
        """})
    hits = active(run_lint(root, rules=["R2"]), "R2")
    assert len(hits) == 1 and "_commit_decode" in hits[0].message

def test_r2_matches_method_form_block_until_ready(tmp_path):
    # the idiomatic ARRAY-METHOD sync form blocks just like the
    # module-call form and must not slip through
    root = make_tree(tmp_path, {_ENGINE: """\
        def _dispatch_decode(pending):
            pending.nxt.block_until_ready()
            return pending
        """})
    hits = active(run_lint(root, rules=["R2"]), "R2")
    assert len(hits) == 1 and ".block_until_ready()" in hits[0].message

def test_r2_cold_path_fetch_is_legal(tmp_path):
    # the same fetch outside the hot-loop allowlist (warmup) is fine
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def warmup(tok):
            jax.block_until_ready(tok)
            return jax.device_get(tok)
        """})
    assert active(run_lint(root, rules=["R2"]), "R2") == []


# -- R3: jit static-key hygiene -----------------------------------------------

def test_r3_fires_on_undeclared_and_non_literal_statics(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": """\
        import functools
        import jax

        step = jax.jit(lambda x: x)
        spec = functools.partial(
            jax.jit, static_argnums=tuple(range(3)))
        """})
    hits = active(run_lint(root, rules=["R3"]), "R3")
    assert len(hits) == 2
    assert any("no static_argnums" in f.message for f in hits)
    assert any("not a literal" in f.message for f in hits)

def test_r3_literal_statics_are_legal(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": """\
        import functools
        import jax

        step = jax.jit(lambda m, x: x, static_argnums=(0,))
        fam = functools.partial(jax.jit,
                                static_argnames=("model", "width"))
        """})
    assert active(run_lint(root, rules=["R3"]), "R3") == []


# -- R4: telemetry field contract ---------------------------------------------

_SCHEMA = f"{PACKAGE}/obs/schema.py"
_SCHEMA_SRC = """\
    REQUIRED_FIELDS = {"serve": {"event": (str,)}}
    OPTIONAL_FIELDS = {"serve": {"request": (int,), "tokens": (int,)}}
    """

def test_r4_fires_on_undeclared_field(tmp_path):
    root = make_tree(tmp_path, {
        _SCHEMA: _SCHEMA_SRC,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "obs.serve('admit', request=1, slot=3)\n".format(p=PACKAGE)),
    })
    hits = active(run_lint(root, rules=["R4"]), "R4")
    assert len(hits) == 1 and "'slot'" in hits[0].message

def test_r4_declared_fields_and_dynamic_kwargs_are_legal(tmp_path):
    root = make_tree(tmp_path, {
        _SCHEMA: _SCHEMA_SRC,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "extra = {{}}\n"
            "obs.serve('finish', request=1, tokens=2, **extra)\n"
            .format(p=PACKAGE)),
    })
    assert active(run_lint(root, rules=["R4"]), "R4") == []

_SCHEMA_SRC_EVENTS = """\
    REQUIRED_FIELDS = {"serve": {"event": (str,)}}
    OPTIONAL_FIELDS = {"serve": {"request": (int,), "tokens": (int,)}}
    SERVE_EVENTS = ("admit", "finish")
    """

def test_r4_fires_on_undeclared_event_kind(tmp_path):
    """ISSUE 19: an emitter inventing a serve-event KIND outside the
    schema's SERVE_EVENTS vocabulary is the same silent drift for
    consumers switching on `event` as an undeclared field is for
    field type-checkers."""
    root = make_tree(tmp_path, {
        _SCHEMA: _SCHEMA_SRC_EVENTS,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "obs.serve('teleport', request=1)\n".format(p=PACKAGE)),
    })
    hits = active(run_lint(root, rules=["R4"]), "R4")
    assert len(hits) == 1 and "'teleport'" in hits[0].message
    assert "SERVE_EVENTS" in hits[0].message

def test_r4_declared_kinds_dynamic_kinds_and_no_registry_are_legal(
        tmp_path):
    # declared kinds and a non-literal kind (not statically checkable)
    root = make_tree(tmp_path, {
        _SCHEMA: _SCHEMA_SRC_EVENTS,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "kind = 'admit'\n"
            "obs.serve('finish', request=1)\n"
            "obs.serve(kind, request=1)\n".format(p=PACKAGE)),
    })
    assert active(run_lint(root, rules=["R4"]), "R4") == []
    # a schema without SERVE_EVENTS (pre-19 trees): kinds unchecked,
    # field checks still live
    root = make_tree(tmp_path / "old", {
        _SCHEMA: _SCHEMA_SRC,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "obs.serve('teleport', request=1)\n".format(p=PACKAGE)),
    })
    assert active(run_lint(root, rules=["R4"]), "R4") == []


# -- R5: env-knob registry ----------------------------------------------------

_README = """\
    # x

    | var | meaning |
    |---|---|
    | `HSTD_DOCUMENTED` | a knob |
    | `HSTD_ORPHANED` | stale row |
    """

def test_r5_fires_both_directions(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": """\
        import os
        A = os.environ.get("HSTD_DOCUMENTED", "")
        B = os.environ.get("HSTD_UNDOCUMENTED", "")
        """}, readme=_README)
    hits = active(run_lint(root, rules=["R5"]), "R5")
    assert len(hits) == 2
    undoc = [f for f in hits if "HSTD_UNDOCUMENTED" in f.message]
    orphan = [f for f in hits if "HSTD_ORPHANED" in f.message]
    assert undoc and undoc[0].path == f"{PACKAGE}/m.py"
    assert orphan and orphan[0].path == "README.md"

def test_r5_docstring_mention_is_not_a_read(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": '''\
        """Reads ``HSTD_NOT_REALLY_A_READ`` — prose only."""
        import os
        A = os.environ.get("HSTD_DOCUMENTED", "")
        ''' }, readme="| `HSTD_DOCUMENTED` | a knob |\n")
    assert active(run_lint(root, rules=["R5"]), "R5") == []


# -- R6: BlockManager discipline ----------------------------------------------

def test_r6_fires_on_raw_free_and_refcount_poke(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/serve/scheduler.py": """\
        def evict(blocks, table):
            blocks.free(table)
            blocks._refs[table[0]] -= 1
        """})
    hits = active(run_lint(root, rules=["R6"]), "R6")
    assert len(hits) == 2
    assert any(".free()" in f.message for f in hits)
    assert any("_refs" in f.message for f in hits)

def test_r6_release_and_manager_internals_are_legal(tmp_path):
    root = make_tree(tmp_path, {
        f"{PACKAGE}/serve/scheduler.py": (
            "def evict(blocks, table):\n"
            "    blocks.release(table)\n"),
        # the manager itself may touch its own refcounts, of course
        f"{PACKAGE}/serve/paged_kv.py": (
            "class BlockManager:\n"
            "    def release(self, t):\n"
            "        self._refs[t[0]] -= 1\n"
            "        self.free(t)\n"),
    })
    assert active(run_lint(root, rules=["R6"]), "R6") == []


# -- R7: admission policy stays jax-free --------------------------------------

def test_r7_fires_on_transitive_import_time_jax(tmp_path):
    root = make_tree(tmp_path, {
        f"{PACKAGE}/serve/policy.py": "from {p}.serve import kv\n".format(
            p=PACKAGE),
        f"{PACKAGE}/serve/kv.py": "import jax\n",
    })
    hits = active(run_lint(root, rules=["R7"]), "R7")
    assert len(hits) == 1
    assert hits[0].path == f"{PACKAGE}/serve/kv.py"
    assert "jax" in hits[0].message and "policy" in hits[0].message

def test_r7_host_side_policy_is_legal(tmp_path):
    root = make_tree(tmp_path, {
        f"{PACKAGE}/serve/policy.py": (
            "import math\n"
            "def key(req, now):\n"
            "    return (0, now, req.rid)\n"),
        # jax elsewhere in serve/ is fine — R7 roots at policy.py only
        f"{PACKAGE}/serve/engine.py": "import jax\n",
    })
    assert active(run_lint(root, rules=["R7"]), "R7") == []


# -- pragmas ------------------------------------------------------------------

def test_pragma_suppresses_with_reason_trailing_and_standalone(tmp_path):
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(p):
            # graftlint: allow[R2] deferred commit fetch, safe by design
            a = jax.device_get(p)
            b = jax.device_get(p)  # graftlint: allow[R2] same fetch, trailing form
            return a, b
        """})
    result = run_lint(root, rules=["R2"])
    assert active(result) == []
    assert len(result.suppressed) == 2
    assert all(f.reason for f in result.suppressed)

def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return jax.device_get(p)  # graftlint: allow[R2]
        """})
    result = run_lint(root, rules=["R2"])
    rules = sorted(f.rule for f in active(result))
    # the reasonless pragma does NOT suppress, and is flagged itself
    assert rules == ["R2", "pragma"]

def test_pragma_in_string_literal_is_inert(tmp_path):
    # pragma syntax QUOTED in prose (docstring/string) is neither a
    # phantom suppression nor a malformed-pragma finding — only real
    # comment tokens count
    root = make_tree(tmp_path, {_ENGINE: '''\
        """Suppress with `# graftlint: allow[R2] reason` — and a
        reasonless example: `# graftlint: allow[R2]` (also inert)."""
        import jax
        DOC = "# graftlint: allow[R2] not a comment either"
        def _commit_decode(p):
            return jax.device_get(p)
        '''})
    result = run_lint(root, rules=["R2"])
    assert [f.rule for f in active(result)] == ["R2"]
    assert result.suppressed == []

def test_pragma_for_wrong_rule_does_not_suppress(tmp_path):
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return jax.device_get(p)  # graftlint: allow[R3] wrong rule id
        """})
    assert len(active(run_lint(root, rules=["R2"]), "R2")) == 1


def test_unused_pragma_is_itself_a_finding(tmp_path):
    """ISSUE 16: a pragma whose rule does NOT fire on its line is a
    `pragma` finding — stale suppressions are landmines that silently
    swallow the next real finding on that line. The fixture pair: the
    same pragma on a line where R2 DOES fire stays a clean, counted
    suppression."""
    used = make_tree(tmp_path / "used", {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return jax.device_get(p)  # graftlint: allow[R2] deferred fetch
        """})
    result = run_lint(used, rules=["R2"])
    assert active(result) == []
    assert len(result.suppressed) == 1

    stale = make_tree(tmp_path / "stale", {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return p + 1  # graftlint: allow[R2] fetch long since removed
        """})
    result = run_lint(stale, rules=["R2"])
    assert [f.rule for f in active(result)] == ["pragma"]
    assert "unused pragma allow[R2]" in active(result)[0].message
    assert result.suppressed == []


def test_unused_pragma_only_flagged_for_selected_rules(tmp_path):
    """A pragma can only be judged stale by RUNNING its rule: under
    --rules R2 an allow[R3] pragma is unjudgeable (R3 never ran) and
    must not be flagged; selecting R3 over the same tree flags it."""
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return p + 1  # graftlint: allow[R3] stale sync claim
        """})
    assert active(run_lint(root, rules=["R2"])) == []
    assert [f.rule for f in active(run_lint(root, rules=["R3"]))] \
        == ["pragma"]


def test_unused_pragma_detected_on_stdin_snippets():
    """The `obsctl lint -` path judges stale pragmas too — but only
    for the rules that CAN fire on a bare snippet (R2/R3); a zone or
    registry pragma is not judgeable without the tree."""
    result = lint_text(
        "def _step(x):\n"
        "    return x + 1  # graftlint: allow[R2] no fetch here anymore\n")
    assert [f.rule for f in active(result)] == ["pragma"]
    # the same pragma id on a genuinely-firing line suppresses cleanly
    fired = lint_text(
        "import jax\n"
        "def _step(x):\n"
        "    return jax.device_get(x)  # graftlint: allow[R2] safe fetch\n")
    assert active(fired) == []
    assert len(fired.suppressed) == 1
    # tree-anchored rules (e.g. R1 zones) are never judged on stdin
    zone = lint_text("x = 1  # graftlint: allow[R1] zone claim\n")
    assert active(zone) == []


# -- determinism --------------------------------------------------------------

def test_output_byte_identical_across_input_orderings(tmp_path):
    files = {
        f"{PACKAGE}/serve/engine.py": (
            "import jax\n\ndef _decode_all(x):\n"
            "    return jax.device_get(x)\n"),
        f"{PACKAGE}/a.py": "import jax\nf = jax.jit(lambda x: x)\n",
        f"{PACKAGE}/obs/__init__.py": "import jax\n",
    }
    root = make_tree(tmp_path, files)
    paths = sorted(files) + [f"{PACKAGE}/__init__.py"]
    fwd = run_lint(root, paths=list(paths))
    rev = run_lint(root, paths=list(reversed(paths)))
    assert render_json(fwd) == render_json(rev)
    assert render_text(fwd) == render_text(rev)
    assert render_json(fwd) == render_json(
        run_lint(root, paths=list(paths)))   # and stable across runs


# -- bad input ----------------------------------------------------------------

def test_unparseable_source_is_bad_input(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": "def broken(:\n"})
    with pytest.raises(LintInputError):
        run_lint(root)

def test_missing_path_is_bad_input(tmp_path):
    root = make_tree(tmp_path, {})
    with pytest.raises(LintInputError):
        run_lint(root, paths=["nope.py"])

def test_unknown_rule_is_bad_input(tmp_path):
    root = make_tree(tmp_path, {})
    with pytest.raises(LintInputError):
        run_lint(root, rules=["R99"])


# -- stdin / file-local mode --------------------------------------------------

def test_lint_text_runs_file_local_rules():
    result = lint_text(
        "import jax\n"
        "def _commit_decode(p):\n"
        "    return jax.device_get(p)\n")
    assert [f.rule for f in active(result)] == ["R2"]

def test_lint_text_clean_snippet():
    assert active(lint_text("x = 1\n")) == []

def test_lint_text_unknown_rule_is_bad_input():
    # same 0/1/2 contract as file mode: a typoed --rules must not
    # produce a vacuous clean pass on stdin
    with pytest.raises(LintInputError):
        lint_text("x = 1\n", rules=["R99"])

def test_explicit_paths_see_full_tree_context(tmp_path):
    """Linting a file SELECTION keeps cross-file rules correct: the
    whole tree loads for context (schema for R4, README/code for R5),
    findings filter to the selection — so per-file lint of a clean
    tree is clean, R5 orphan noise from unselected files included."""
    root = make_tree(tmp_path, {
        _SCHEMA: _SCHEMA_SRC,
        f"{PACKAGE}/serve/engine.py": (
            "from {p} import obs\n"
            "obs.serve('admit', request=1, slot=3)\n".format(p=PACKAGE)),
        f"{PACKAGE}/other.py": (
            "import os\nA = os.environ.get('HSTD_DOCUMENTED')\n"),
    }, readme="| `HSTD_DOCUMENTED` | a knob |\n")
    # R4 needs the schema even though only engine.py is selected
    hits = run_lint(root, paths=[f"{PACKAGE}/serve/engine.py"])
    assert [f.rule for f in active(hits)] == ["R4"]
    # R5's readme row is satisfied by the UNSELECTED other.py — no
    # orphan false positive; and nothing anchors in unselected files
    assert all(f.path == f"{PACKAGE}/serve/engine.py"
               for f in active(hits))
    clean = run_lint(root, paths=[f"{PACKAGE}/other.py"])
    assert active(clean) == []

def test_absolute_path_selection_keys_repo_relative(tmp_path):
    """An ABSOLUTE path argument must resolve to the same repo-relative
    key as the relative form — otherwise every path-keyed rule (R2's
    engine file, R4's schema home, R6's paged_kv exemption) silently
    misses the selected file and real violations report clean."""
    root = make_tree(tmp_path, {_ENGINE: """\
        import jax

        def _commit_decode(p):
            return jax.device_get(p)
        """})
    rel = run_lint(root, paths=[_ENGINE], rules=["R2"])
    abs_ = run_lint(root, paths=[os.path.join(root, *_ENGINE.split("/"))],
                    rules=["R2"])
    assert [f.rule for f in active(abs_)] == ["R2"]
    assert render_json(abs_) == render_json(rel)
    with pytest.raises(LintInputError):
        run_lint(root, paths=[os.path.join(os.path.dirname(root),
                                           "outside.py")])

def test_cli_single_file_on_clean_tree_is_clean():
    # the docstring's own example usage: per-file lint of the real
    # tree must not manufacture findings from the partial view
    proc = _cli([f"{PACKAGE}/serve/engine.py", "--format", "json"])
    assert proc.returncode == 0, proc.stdout
    doc = json.loads(proc.stdout)
    assert doc["total"] == 0
    assert doc["suppressed"]          # engine's allow[] sites report


# -- CLI exit codes (the obsctl-diff shape) -----------------------------------

def _cli(args, stdin=None, cwd=_REPO):
    return subprocess.run([sys.executable, _GRAFTLINT, *args],
                          input=stdin, stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True, cwd=cwd)

def test_cli_clean_tree_exits_0_findings_exit_2(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": "x = 1\n"})
    assert _cli(["--root", root]).returncode == 0
    root2 = make_tree(tmp_path / "dirty", {
        f"{PACKAGE}/m.py": "import jax\nf = jax.jit(lambda x: x)\n"})
    proc = _cli(["--root", root2, "--format", "json"])
    assert proc.returncode == 2
    doc = json.loads(proc.stdout)
    assert doc["total"] == 1 and doc["counts"] == {"R3": 1}

def test_cli_bad_input_exits_1(tmp_path):
    root = make_tree(tmp_path, {f"{PACKAGE}/m.py": "def broken(:\n"})
    proc = _cli(["--root", root])
    assert proc.returncode == 1 and "syntax error" in proc.stderr

def test_cli_stdin(tmp_path):
    proc = _cli(["-"], stdin="import jax\n"
                            "def _decode_all(x):\n"
                            "    return jax.device_get(x)\n")
    assert proc.returncode == 2
    assert "<stdin>" in proc.stdout

def test_obsctl_lint_subcommand_stdin_json():
    proc = subprocess.run(
        [sys.executable, _OBSCTL, "lint", "-", "--format", "json"],
        input="x = 1\n", stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=_REPO)
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["total"] == 0


# -- the real tree: tier-1 gates ----------------------------------------------

def test_full_package_lints_clean():
    """THE gate: zero unsuppressed findings over the installed tree,
    and every suppression carries a reason string."""
    result = run_lint(_REPO)
    assert active(result) == [], "\n" + "\n".join(
        f.render() for f in active(result))
    assert result.suppressed, "expected the documented allow[] sites"
    assert all(f.reason and f.reason.strip()
               for f in result.suppressed)

def test_no_jax_zone_static_reachability_primary_gate():
    """R1's static reachability IS the no-jax contract now: the
    import-time closure of obs/, analysis/ and the obsctl/schema CLIs
    contains no jax/flax import — complete over all imports, where the
    old subprocess poison run only covered imported-today paths (one
    subprocess smoke remains as the slow-tier backstop)."""
    project = load_project(_REPO)
    assert check_r1(project) == []
    reached = set(r1_reachability(project))
    # the gate is not vacuous: the zone really spans the jax-less
    # tooling surface, CLIs included
    for must in (f"{PACKAGE}/obs/report.py",
                 f"{PACKAGE}/obs/timeline.py",
                 f"{PACKAGE}/obs/schema.py",
                 f"{PACKAGE}/analysis/lint.py",
                 f"{PACKAGE}/analysis/rules.py",
                 "scripts/obsctl.py",
                 "scripts/check_telemetry_schema.py",
                 "scripts/graftlint.py"):
        assert must in reached, must
    assert f"{PACKAGE}/obs/__init__.py" in r1_zone_roots(project)

def test_rule_catalog_complete():
    assert sorted(RULES) == ["R1", "R2", "R3", "R4", "R5", "R6", "R7"]
    for rule in RULES.values():
        assert rule.title and rule.rationale

def test_linter_itself_runs_without_jax():
    """The poison contract extended over analysis/ (ISSUE 15
    satellite): the full CLI runs with jax import poisoned."""
    code = ("import sys, runpy; sys.modules['jax'] = None; "
            "sys.argv = ['graftlint', '--format', 'json']; "
            "runpy.run_path(%r, run_name='__main__')" % _GRAFTLINT)
    proc = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True)
    assert proc.returncode == 0, proc.stdout
    assert json.loads(proc.stdout)["total"] == 0

def test_bench_lint_stage_emits_zero_count_line():
    """`bench.py --lint` emits the lint_findings count line obsctl
    diff gates (zero-baseline count metric, worse UP)."""
    proc = subprocess.run([sys.executable, "bench.py", "--lint"],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, text=True, cwd=_REPO)
    assert proc.returncode == 0, proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "lint_findings"
    assert line["value"] == 0
    assert line["worse_direction"] == "up"
