"""Task-breadth tests: token-classification (CoNLL-shaped) and extractive
QA (SQuAD-shaped) — alignment correctness and end-to-end learning on the
synthetic offline tier (BASELINE.json breadth configs)."""

import numpy as np
import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_qa,
    synthetic_token_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForQuestionAnswering,
    BertForTokenClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import MeshConfig, build_mesh
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 48


def _cfg(task, **kw):
    base = dict(task=task, dtype="float32", learning_rate=1e-3,
                scale_lr_by_world_size=False, log_every_steps=0, epochs=3)
    base.update(kw)
    return TrainConfig(**base)


def _model_cfg(vocab=512, use_pooler=False):
    return EncoderConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                         num_heads=2, intermediate_size=64,
                         max_position_embeddings=SEQ, use_pooler=use_pooler)


def test_token_cls_label_alignment():
    tok = WordHashTokenizer(vocab_size=512)
    sents = [["alice", "went", "to", "paris"]]
    tags = [[1, 0, 0, 2]]
    ds = ArrayDataset.from_token_classification(tok, sents, tags, max_length=8)
    labels = ds.columns["labels"][0]
    # CLS=-100, then word tags, SEP/PAD=-100
    np.testing.assert_array_equal(labels, [-100, 1, 0, 0, 2, -100, -100, -100])


def test_qa_span_positions():
    tok = WordHashTokenizer(vocab_size=512)
    q = ["which place ?"]
    ctx = ["we went to paris yesterday"]
    start = [ctx[0].index("paris")]
    ds = ArrayDataset.from_qa(tok, q, ctx, start, ["paris"], max_length=16)
    # tokens: CLS which place ? SEP we went to paris ...
    s = int(ds.columns["start_positions"][0])
    e = int(ds.columns["end_positions"][0])
    assert s == e == 8
    assert ds.columns["token_type_ids"][0][s] == 1


def test_qa_span_truncated_falls_back_to_cls():
    tok = WordHashTokenizer(vocab_size=512)
    ctx = " ".join(["word"] * 50) + " paris"
    ds = ArrayDataset.from_qa(tok, ["which place ?"], [ctx],
                              [ctx.index("paris")], ["paris"], max_length=16)
    assert int(ds.columns["start_positions"][0]) == 0


def test_token_cls_learns():
    mesh = build_mesh(MeshConfig())
    cfg = _cfg("token-cls")
    mcfg = _model_cfg()
    model = BertForTokenClassification(mcfg, num_labels=4)
    trainer = Trainer(cfg, model, init_params(model, mcfg), mesh)
    tok = WordHashTokenizer(vocab_size=512)
    sents, tags = synthetic_token_classification(256, seed=0)
    ds = ArrayDataset.from_token_classification(tok, sents, tags, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    assert hist["sparse_categorical_accuracy"][-1] > 0.9
    assert hist["loss"][-1] < hist["loss"][0]

    e_sents, e_tags = synthetic_token_classification(64, seed=5)
    eds = ArrayDataset.from_token_classification(tok, e_sents, e_tags, max_length=SEQ)
    res = trainer.evaluate(ShardedBatcher(eds, 16, mesh, shuffle=False,
                                          drop_remainder=False))
    assert res["eval_accuracy"] > 0.9


def test_qa_learns():
    mesh = build_mesh(MeshConfig())
    cfg = _cfg("qa", epochs=4)
    mcfg = _model_cfg(vocab=1024)
    model = BertForQuestionAnswering(mcfg)
    trainer = Trainer(cfg, model, init_params(model, mcfg), mesh)
    tok = WordHashTokenizer(vocab_size=1024)
    q, c, s, a = synthetic_qa(384, seed=0, ctx_len=(10, 30))
    ds = ArrayDataset.from_qa(tok, q, c, s, a, max_length=SEQ)
    hist = trainer.fit(ShardedBatcher(ds, 16, mesh, shuffle=True, seed=0))
    # span accuracy: argmax start/end both right counts 1.0
    assert hist["sparse_categorical_accuracy"][-1] > 0.6
    assert hist["loss"][-1] < hist["loss"][0] * 0.7


def test_token_cls_eval_reports_micro_f1(devices8):
    """token-cls eval aggregates micro-F1 components inside the jitted
    step; a perfect predictor must score f1=1 and a constant-O predictor
    f1=0 (accuracy can still be high — exactly why F1 is reported)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        token_cls_loss,
    )

    B, S, C = 2, 8, 3
    labels = np.zeros((B, S), np.int32)
    labels[:, :2] = 1            # a few entity tokens, rest O
    batch = {"labels": jnp.asarray(labels),
             "attention_mask": jnp.ones((B, S), jnp.int32),
             "input_ids": jnp.ones((B, S), jnp.int32)}

    def fake_apply(logits):
        def apply_fn(variables, *a, **kw):
            return logits
        return apply_fn

    perfect = jax.nn.one_hot(labels, C) * 10.0
    _, sums = token_cls_loss(fake_apply(jnp.asarray(perfect)), None, batch, {}, False)
    tp, fp, fn = float(sums["f1_tp"]), float(sums["f1_fp"]), float(sums["f1_fn"])
    assert 2 * tp / (2 * tp + fp + fn) == 1.0

    all_o = jax.nn.one_hot(np.zeros((B, S), np.int32), C) * 10.0
    _, sums = token_cls_loss(fake_apply(jnp.asarray(all_o)), None, batch, {}, False)
    assert float(sums["f1_tp"]) == 0.0 and float(sums["f1_fn"]) == 4.0


def test_squad_em_f1():
    from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
        squad_em_f1,
        squad_normalize,
    )

    # official normalization: case, punctuation, articles, whitespace
    assert squad_normalize("The  Eiffel Tower!") == "eiffel tower"
    assert squad_normalize("a dog.") == "dog"
    # punctuation is REMOVED, not replaced: 'U.S.' ≡ 'US' officially
    assert squad_normalize("U.S.") == squad_normalize("US")
    out = squad_em_f1(["U.S."], ["US"])
    assert out["exact_match"] == 100.0 and out["f1"] == 100.0
    out = squad_em_f1(["The Eiffel Tower"], ["eiffel tower"])
    assert out["exact_match"] == 100.0 and out["f1"] == 100.0
    # partial token overlap: F1 rewards it, EM doesn't
    out = squad_em_f1(["eiffel tower of paris"], ["eiffel tower"])
    assert out["exact_match"] == 0.0
    assert 0.0 < out["f1"] < 100.0
    # empty prediction vs non-empty gold
    out = squad_em_f1([""], ["paris"])
    assert out["exact_match"] == 0.0 and out["f1"] == 0.0
    with pytest.raises(ValueError):
        squad_em_f1(["a"], ["a", "b"])


def test_extract_answer_spans_decodes_gold():
    """Feeding one-hot logits at the GOLD span positions through the
    offsets returned by encode_qa must reproduce the answer text — the
    whole decode path (offsets → char span → context slice) round-trips."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import (
        extract_answer_spans,
        squad_em_f1,
    )

    tok = WordHashTokenizer(vocab_size=1024)
    q, c, s, a = synthetic_qa(32, seed=2, ctx_len=(10, 30))
    enc = tok.encode_qa(q, c, s, a, max_length=SEQ, return_offsets=True)
    n, L = enc["input_ids"].shape
    s_log = np.full((n, L), -10.0, np.float32)
    e_log = np.full((n, L), -10.0, np.float32)
    s_log[np.arange(n), enc["start_positions"]] = 10.0
    e_log[np.arange(n), enc["end_positions"]] = 10.0
    preds = extract_answer_spans(s_log, e_log, enc["offset_starts"],
                                 enc["offset_ends"], c)
    out = squad_em_f1(preds, list(a))
    assert out["exact_match"] == 100.0


def test_encode_qa_offsets_slice_to_answer_wordpiece():
    """WordPiece tier: gold span positions + offsets slice the context to
    exactly the labeled answer text."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.wordpiece import (
        WordPieceTokenizer,
    )

    vocab = {w: i for i, w in enumerate(
        ["[PAD]", "[CLS]", "[SEP]", "[UNK]", "[MASK]",
         "we", "went", "to", "par", "##is", "yesterday", "which", "place",
         "?"])}
    tok = WordPieceTokenizer(vocab)
    ctx = "we went to paris yesterday"
    enc = tok.encode_qa(["which place ?"], [ctx], [ctx.index("paris")],
                        ["paris"], max_length=16, return_offsets=True)
    s = int(enc["start_positions"][0])
    e = int(enc["end_positions"][0])
    assert s > 0  # span found
    text = ctx[enc["offset_starts"][0][s]:enc["offset_ends"][0][e]]
    assert text == "paris"
    # offsets are -1 outside context tokens (question/CLS/SEP/pad)
    assert enc["offset_starts"][0][0] == -1
    assert enc["offset_starts"][0][1] == -1


def test_encode_qa_offsets_cover_truncation_boundary():
    """A context token on the LAST context position after truncation can
    still be the labeled gold span — its offset must be recorded, or a
    model predicting the gold span exactly would decode to ''. The
    layout reserves the final [SEP] (HF only_second truncation), so the
    last context slot is max_length-2."""
    tok = WordHashTokenizer(vocab_size=512)
    ctx = " ".join(f"w{i}" for i in range(20))
    # 2-token question → ctx_offset=4; answer placed so its token sits at
    # position max_length-2 (the last context slot before the final SEP)
    L = 12
    answer_idx = L - 2 - 4
    words = ctx.split()
    a_start = ctx.index(words[answer_idx])
    enc = tok.encode_qa(["which one"], [ctx], [a_start], [words[answer_idx]],
                        max_length=L, return_offsets=True)
    s, e = int(enc["start_positions"][0]), int(enc["end_positions"][0])
    assert s == e == L - 2
    assert enc["offset_starts"][0][s] >= 0, "offset missing at boundary"
    assert ctx[enc["offset_starts"][0][s]:enc["offset_ends"][0][e]] == words[answer_idx]
    # the slot after it is the final [SEP], present even under truncation
    assert int(enc["input_ids"][0][L - 1]) == tok.sep_token_id
    # a token truncated past the boundary cannot be labeled
    a2 = ctx.index(words[answer_idx + 1])
    enc2 = tok.encode_qa(["which one"], [ctx], [a2], [words[answer_idx + 1]],
                         max_length=L)
    assert int(enc2["start_positions"][0]) == 0


@pytest.mark.parametrize("doc_stride", [0, 8])
def test_qa_eval_reports_em_f1(tmp_path, devices8, doc_stride):
    """scripts/train.py --task qa --eval_qa_samples N lands
    eval_exact_match / eval_f1 in eval_results.txt (reference analogue:
    the metric emission at train.py:170). With --qa_doc_stride the
    training rows are windowed features and the eval aggregates the
    best-scoring span per example across its windows."""
    import transformers

    from scripts.train import main as train_main

    mdir = str(tmp_path / "cfg")
    transformers.BertConfig(
        vocab_size=4096, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=SEQ).save_pretrained(mdir)
    out = str(tmp_path / "out")
    train_main([
        "--task", "qa", "--dataset", "synthetic", "--from_scratch", "true",
        "--model_name_or_path", mdir, "--epochs", "2",
        "--train_batch_size", "2", "--dtype", "float32",
        "--max_seq_length", str(SEQ), "--max_train_samples", "256",
        "--max_eval_samples", "64", "--eval_qa_samples", "32",
        "--qa_doc_stride", str(doc_stride),
        "--learning_rate", "1e-3", "--scale_lr_by_world_size", "false",
        "--output_data_dir", out, "--model_dir", str(tmp_path / "model"),
    ])
    text = (tmp_path / "out" / "eval_results.txt").read_text()
    kv = dict(line.split(" = ") for line in text.strip().splitlines())
    assert "eval_exact_match" in kv and "eval_f1" in kv
    assert 0.0 <= float(kv["eval_exact_match"]) <= 100.0
    # F1 upper-bounds EM by construction
    assert float(kv["eval_f1"]) >= float(kv["eval_exact_match"])


def test_rouge_l():
    from huggingface_sagemaker_tensorflow_distributed_tpu.utils.metrics import rouge_l

    out = rouge_l(["the cat sat on the mat"], ["the cat sat on the mat"])
    assert out["rougeL_f1"] == 1.0
    out = rouge_l(["a b c d"], ["x y z w"])
    assert out["rougeL_f1"] == 0.0
    out = rouge_l(["the quick fox"], ["the slow fox"])
    assert 0.0 < out["rougeL_f1"] < 1.0
    with pytest.raises(ValueError):
        rouge_l(["a"], ["a", "b"])


def test_label_smoothing_matches_explicit_onehot():
    """The (1-eps)*CE + eps*(lse - mean logits) decomposition must equal
    the explicit smoothed-one-hot cross-entropy, eps=0 must equal the
    plain loss, and eval (train=False) must ignore smoothing."""
    import jax
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.train.trainer import (
        make_smoothed_seq2seq_loss,
        seq2seq_loss,
    )

    B, S, V = 2, 5, 7
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(B, S, V), jnp.float32)
    labels = rng.randint(0, V, (B, S))
    labels[0, -2:] = -100                       # pad positions ignored
    batch = {"input_ids": jnp.zeros((B, S), jnp.int32),
             "attention_mask": jnp.ones((B, S), jnp.int32),
             "decoder_input_ids": jnp.zeros((B, S), jnp.int32),
             "labels": jnp.asarray(labels)}

    def apply_fn(variables, *a, **kw):
        return logits

    eps = 0.1
    loss_fn = make_smoothed_seq2seq_loss(eps)
    smoothed, _ = loss_fn(apply_fn, None, batch, {}, True)

    # explicit reference: q = (1-eps)*onehot + eps/V
    logp = jax.nn.log_softmax(logits, -1)
    safe = np.maximum(labels, 0)
    q = ((1 - eps) * jax.nn.one_hot(safe, V)
         + eps / V * jnp.ones((B, S, V)))
    per_tok = -jnp.sum(q * logp, -1)
    valid = jnp.asarray(labels != -100, jnp.float32)
    want = float(jnp.sum(per_tok * valid) / jnp.sum(valid))
    assert float(smoothed) == pytest.approx(want, rel=1e-5)

    plain, _ = seq2seq_loss(apply_fn, None, batch, {}, True)
    zero, _ = make_smoothed_seq2seq_loss(0.0)(apply_fn, None, batch, {},
                                              True)
    assert float(zero) == pytest.approx(float(plain), rel=1e-6)
    # eval ignores smoothing entirely
    ev, _ = loss_fn(apply_fn, None, batch, {}, False)
    assert float(ev) == pytest.approx(float(plain), rel=1e-6)
    # smoothing strictly increases the training loss on confident logits
    assert float(smoothed) > float(plain)
