"""Config layer tests: typed parsing fixes the reference's stringly-typed
bugs (SURVEY.md §2 behavioral quirks)."""

import pytest

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig, parse_args


def test_defaults_match_reference_contract():
    # reference defaults: epochs=3, train_batch_size=8, eval_batch_size=4,
    # lr=5e-5 (scripts/train.py:39-43)
    cfg = TrainConfig()
    assert cfg.epochs == 3
    assert cfg.train_batch_size == 8
    assert cfg.eval_batch_size == 4
    assert cfg.learning_rate == pytest.approx(5e-5)
    assert cfg.do_train is True and cfg.do_eval is True


def test_learning_rate_is_float_not_str():
    # the reference's --learning_rate was type=str: "5e-5" * 8 = string
    # repetition (scripts/train.py:43,112). Ours parses to float.
    cfg = parse_args(["--learning_rate", "5e-5"])
    assert isinstance(cfg.learning_rate, float)
    assert cfg.learning_rate * 8 == pytest.approx(4e-4)


def test_bool_flags_actually_turn_off():
    # reference: bool("False") is True so --do_train False couldn't disable
    # training (scripts/train.py:44-45). Ours can.
    cfg = parse_args(["--do_train", "False", "--do_eval", "0"])
    assert cfg.do_train is False and cfg.do_eval is False


def test_sm_env_contract(monkeypatch):
    monkeypatch.setenv("SM_OUTPUT_DATA_DIR", "/tmp/sm_out")
    monkeypatch.setenv("SM_MODEL_DIR", "/tmp/sm_model")
    cfg = parse_args([])
    assert cfg.output_data_dir == "/tmp/sm_out"
    assert cfg.model_dir == "/tmp/sm_model"


def test_tpu_env_overrides_sm(monkeypatch):
    monkeypatch.setenv("SM_OUTPUT_DATA_DIR", "/tmp/sm_out")
    monkeypatch.setenv("TPU_OUTPUT_DATA_DIR", "/tmp/tpu_out")
    cfg = parse_args([])
    assert cfg.output_data_dir == "/tmp/tpu_out"


def test_unknown_args_tolerated():
    # parse_known_args parity (scripts/train.py:52)
    cfg = parse_args(["--epochs", "1", "--platform_injected_junk", "x"])
    assert cfg.epochs == 1


def test_validation_errors():
    with pytest.raises(ValueError):
        TrainConfig(task="nope")
    with pytest.raises(ValueError):
        TrainConfig(learning_rate=-1.0)
    with pytest.raises(ValueError):
        TrainConfig(tp=0)


def test_roundtrip():
    cfg = TrainConfig(epochs=5, tp=2)
    assert TrainConfig.from_dict(cfg.to_dict()) == cfg


def test_attention_impl_auto_resolution():
    # auto → flash on TPU, xla on CPU (Pallas would interpret there),
    # ring whenever the mesh has a seq axis; explicit values pass through
    assert TrainConfig().resolve_attention_impl("tpu") == "flash"
    assert TrainConfig().resolve_attention_impl("cpu") == "xla"
    assert TrainConfig(sp=2).resolve_attention_impl("tpu") == "ring"
    # sp>1 forces ring even for explicit xla (per-shard attention over a
    # sharded seq axis is wrong); explicit flash + sp>1 is an error
    assert TrainConfig(sp=2, attention_impl="xla").resolve_attention_impl("tpu") == "ring"
    with pytest.raises(ValueError):
        TrainConfig(sp=2, attention_impl="flash").resolve_attention_impl("tpu")
    assert TrainConfig(attention_impl="xla").resolve_attention_impl("tpu") == "xla"
    with pytest.raises(ValueError):
        TrainConfig(attention_impl="nope")


def test_num_chips_env_parity(monkeypatch):
    # SM_NUM_GPUS-style accelerator-count contract (reference train.py:50)
    monkeypatch.delenv("TPU_NUM_CHIPS", raising=False)
    monkeypatch.delenv("SM_NUM_GPUS", raising=False)
    assert TrainConfig().num_chips is None
    monkeypatch.setenv("SM_NUM_GPUS", "8")
    assert parse_args([]).num_chips == 8
    monkeypatch.setenv("TPU_NUM_CHIPS", "32")
    assert parse_args([]).num_chips == 32
    # an advisory field must tolerate garbage platform values
    monkeypatch.setenv("TPU_NUM_CHIPS", "not-a-number")
    monkeypatch.delenv("SM_NUM_GPUS", raising=False)
    assert TrainConfig().num_chips is None


def test_optimizer_validation():
    import pytest as _pytest

    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig

    with _pytest.raises(ValueError, match="adafactor"):
        TrainConfig(optimizer="adafactor", weight_decay=0.01)
    with _pytest.raises(ValueError, match="adamw"):
        TrainConfig(optimizer="adam", weight_decay=0.01)
    with _pytest.raises(ValueError, match="cosine"):
        TrainConfig(lr_schedule="cosine")          # no warmup
    TrainConfig(optimizer="adam")                  # plain Adam ok
    TrainConfig(lr_schedule="cosine", warmup_ratio=0.1)
