"""Child process for test_mesh32: runs under a forced 32-virtual-CPU-device
backend (cpu_sim_env(32)) and checks 4-axis mesh correctness.

Same seed + same global batches on a 1-device mesh vs the full
dp4 x fsdp2 x tp2 x sp2 mesh (every parallelism axis exercised at once:
data, parameter sharding, tensor heads, ring-attention sequence shards)
must produce the same fp32 loss sequence — the 32-chip analogue of
tests/test_trainer.py::test_dp8_matches_dp1_loss_curve.
"""

import sys

import numpy as np
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
    ArrayDataset,
    ShardedBatcher,
    WordHashTokenizer,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
    synthetic_text_classification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
    BertForSequenceClassification,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
    MeshConfig,
    build_mesh,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

SEQ = 32


def run(mesh_cfg: MeshConfig, devices, attention_impl: str) -> list[float]:
    mesh = build_mesh(mesh_cfg, devices=devices)
    enc = EncoderConfig(vocab_size=512, hidden_size=32, num_layers=2,
                        num_heads=2, intermediate_size=64,
                        max_position_embeddings=SEQ,
                        attention_impl=attention_impl)
    model = BertForSequenceClassification(enc, num_labels=2)
    params = init_params(model, enc, seed=0)
    cfg = TrainConfig(dtype="float32", learning_rate=1e-3,
                      scale_lr_by_world_size=False, log_every_steps=0)
    trainer = Trainer(cfg, model, params, mesh)
    tok = WordHashTokenizer(vocab_size=512)
    texts, labels = synthetic_text_classification(128, seed=0)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=SEQ)
    batcher = ShardedBatcher(ds, 32, mesh, shuffle=True, seed=0)
    losses = []
    for batch in batcher.global_arrays(0):
        trainer.state, metrics = trainer._train_step(trainer.state, batch)
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


def main() -> None:
    devices = jax.devices()
    assert len(devices) == 32 and devices[0].platform == "cpu", (
        f"expected 32 CPU devices, got {len(devices)} {devices[0].platform}")
    ref = run(MeshConfig(), devices[:1], attention_impl="xla")
    full = run(MeshConfig(dp=4, fsdp=2, tp=2, sp=2), devices,
               attention_impl="ring")
    np.testing.assert_allclose(full, ref, atol=1e-5)
    print(f"mesh32 ok: {len(ref)} steps, final loss {ref[-1]:.4f}")


if __name__ == "__main__":
    main()
    sys.exit(0)
