"""Submit a fine-tuning job — reference ``launch.py`` parity, TPU-native.

The reference builds a SageMaker ``HuggingFace`` estimator with a
hyperparameter dict, an instance type, and a distribution knob, then
calls ``fit()`` (reference ``launch.py:13-55``). Here the same shape of
script targets a TPU slice (or the local slice simulator) through the
in-repo launcher: same hyperparameter contract (serialized to
``--key value`` argv), same job-name + artifact-dir semantics, no cloud
SDK in the loop.

Examples:
    # local slice simulator: 2 simulated hosts × 4 CPU "chips"
    python launch.py --slice cpu-8 --num_hosts 2 --epochs 1 \
        --dataset synthetic --from_scratch true

    # print the gcloud command for a real v5e-32 slice
    python launch.py --slice v5e-32
"""

from __future__ import annotations

import argparse
import sys

from huggingface_sagemaker_tensorflow_distributed_tpu.launch import TPUJob


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(allow_abbrev=False)
    parser.add_argument("--slice", default="cpu-8",
                        help="TPU slice spec (v5e-32, v4-8, ...) or cpu-N "
                             "for the local simulator")
    parser.add_argument("--num_hosts", type=int, default=None,
                        help="simulated host count (local backend)")
    parser.add_argument("--entry_point", default="scripts/train.py")
    parser.add_argument("--base_job_name", default="huggingface-tpu")
    parser.add_argument("--job_root", default="/tmp/tpu_jobs")
    # hyperparameters (reference launch.py:13-18 defaults)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--train_batch_size", type=int, default=8)
    parser.add_argument("--eval_batch_size", type=int, default=4)
    parser.add_argument("--model_name_or_path",
                        default="bert-large-uncased-whole-word-masking")
    parser.add_argument("--learning_rate", type=float, default=5e-5)
    ns, extra = parser.parse_known_args(argv)

    hp = {
        "epochs": ns.epochs,
        "train_batch_size": ns.train_batch_size,
        "eval_batch_size": ns.eval_batch_size,
        "model_name_or_path": ns.model_name_or_path,
        "learning_rate": ns.learning_rate,
    }
    # pass-through extras: --key value pairs land in the training config;
    # a bare --flag (next token is another option) means boolean true
    i = 0
    while i < len(extra):
        tok = extra[i]
        if tok.startswith("--"):
            if i + 1 < len(extra) and not extra[i + 1].startswith("--"):
                hp[tok[2:]] = extra[i + 1]
                i += 2
                continue
            hp[tok[2:]] = "true"
        i += 1

    job = TPUJob(entry_point=ns.entry_point, slice_spec=ns.slice,
                 num_hosts=ns.num_hosts, hyperparameters=hp,
                 base_job_name=ns.base_job_name, job_root=ns.job_root)
    handle = job.fit(wait=True)
    print(f"job {handle.job_name} done; artifacts in {handle.job_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
