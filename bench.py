"""Benchmarks on the real jitted training path (same code as
``scripts/train.py``).

Default (no args) — the headline metric, ONE JSON line:
BERT-base fine-tune, seq 512, bf16, Pallas flash attention, per-chip
batch 48 — the reference's default workload shape (BERT-family, IMDb
padded to 512; reference ``launch.py:13-18``, ``scripts/train.py:81-86``)
on synthetic IMDb-shaped data (zero-egress environment). The reference
pins batch 8/worker; per-chip batch is a free throughput knob here, and
48 is the measured v5e sweet spot: a profiler trace showed batch 64
pushing HBM into XLA spill copies + auto-remat (~10% of step time in
pure copies), and the sweep confirms (8→221, 32→247, 40→260, 44→268-273,
48→263-268, 52→269, 56→258, 64→250, 96→231; 128 OOMs on 16G HBM).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the reference's default hardware envelope — BERT-base
fine-tuning at seq 512 / batch 8 / mixed precision on the ml.p3.2xlarge
V100, ≈32 samples/s (public MLPerf-era V100 BERT fine-tune throughput);
vs_baseline = our samples/sec/chip ÷ 32.

Extra modes (each also prints one JSON line per run):
  --model bert-large   the reference's actual default model
                       (bert-large-uncased-whole-word-masking shape:
                       24L/1024H/16 heads; reference ``launch.py:17``),
                       seq 512, per-chip batch 8.
  --buckets            headline workload with length bucketing enabled
                       on a realistic length distribution (vs pad-to-512).
  --mesh               scaling-efficiency instrument: per-step collective
                       vs compute time from a profiler trace.

Results across rounds are recorded in BENCH_EXTRA.md.
"""

from __future__ import annotations

import argparse
import json

V100_BASELINE_SAMPLES_PER_SEC = 32.0
# BERT-large at seq 512 / bs 8 / mixed precision on one V100 runs ≈1/4 of
# BERT-base throughput — public MLPerf-era fine-tune numbers put it ≈8
# samples/s; same caveat as above: a literature anchor, not a measurement.
V100_BERT_LARGE_SAMPLES_PER_SEC = 8.0

BERT_LARGE = dict(hidden_size=1024, num_layers=24, num_heads=16,
                  intermediate_size=4096)


def build_harness(model_kwargs: dict, per_chip_batch: int, seq_len: int = 512,
                  remat: bool = False, bucket_multiple: int = 0,
                  min_len: int = 300, max_len: int = 600, batches: int = 14):
    """(trainer, batcher) for one BERT-family benchmark config — the ONE
    place every bench mode builds its harness, so --mesh/--buckets always
    measure the same configuration the headline does."""
    import jax
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForSequenceClassification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    global_batch = per_chip_batch * n_chips

    mesh = build_mesh(MeshConfig(dp=-1))
    config = TrainConfig(dtype="bfloat16" if on_tpu else "float32",
                         train_batch_size=per_chip_batch,
                         max_seq_length=seq_len, log_every_steps=0,
                         remat=remat, bucket_multiple=bucket_multiple)
    model_cfg = EncoderConfig(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        max_position_embeddings=512,
        attention_impl=config.resolve_attention_impl(
            jax.devices()[0].platform),
        remat=remat,
        **model_kwargs)
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg, seed=0)
    trainer = Trainer(config, model, params, mesh)

    tok = WordHashTokenizer()
    texts, labels = synthetic_text_classification(
        global_batch * batches, seed=0, min_len=min_len, max_len=max_len)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq_len)
    batcher = ShardedBatcher(ds, global_batch, mesh, shuffle=False, seed=0,
                             bucket_sizes=config.bucket_sizes(seq_len))
    return trainer, batcher


def run_finetune(model_kwargs: dict, per_chip_batch: int,
                 epochs: int = 2, warmup_epochs: int = 0, **harness_kwargs):
    """Train-loop throughput for one BERT-family config; returns the fit
    history (the meter excludes the first, compiling, step and runs the
    REAL fit loop: async dispatch, background prefetch, no per-step host
    sync). ``warmup_epochs`` runs an unmeasured fit first so every bucket
    width compiles before the measured pass (the meter only skips the
    first step, which covers a single static shape)."""
    trainer, batcher = build_harness(model_kwargs, per_chip_batch,
                                     **harness_kwargs)
    if warmup_epochs:
        trainer.fit(batcher, epochs=warmup_epochs)
    return trainer.fit(batcher, epochs=epochs)


def emit(metric: str, value: float, baseline: float) -> None:
    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / baseline, 3),
    }))


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def bench_headline() -> None:
    # batch 8 off-TPU keeps the CPU smoke run tractable
    history = run_finetune({}, per_chip_batch=48 if _on_tpu() else 8)
    emit("bert_base_finetune_samples_per_sec_per_chip",
         history["train_samples_per_second_per_chip"],
         V100_BASELINE_SAMPLES_PER_SEC)


def bench_bert_large() -> None:
    # the reference's default workload at its default size: bs 8/worker
    # (reference launch.py:13-18); 340M params + fp32 Adam state fit one
    # 16G chip without encoder remat
    history = run_finetune(BERT_LARGE, per_chip_batch=8 if _on_tpu() else 1)
    emit("bert_large_wwm_finetune_samples_per_sec_per_chip",
         history["train_samples_per_second_per_chip"],
         V100_BERT_LARGE_SAMPLES_PER_SEC)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["bert-base", "bert-large"],
                        default=None)
    parser.add_argument("--buckets", action="store_true")
    parser.add_argument("--mesh", action="store_true")
    args = parser.parse_args()
    picked = [n for n, on in [("--model", args.model is not None),
                              ("--buckets", args.buckets),
                              ("--mesh", args.mesh)] if on]
    if len(picked) > 1:
        parser.error(f"pick one mode, got {' and '.join(picked)}")

    if args.mesh:
        from benchmarks.mesh_bench import bench_mesh
        bench_mesh()
    elif args.buckets:
        from benchmarks.bucket_bench import bench_buckets
        bench_buckets()
    elif args.model == "bert-large":
        bench_bert_large()
    else:
        bench_headline()


if __name__ == "__main__":
    main()
