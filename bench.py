"""Benchmarks on the real jitted training path (same code as
``scripts/train.py``).

Default (no args) — the headline metric, ONE JSON line:
BERT-base fine-tune, seq 512, bf16, Pallas flash attention, per-chip
batch 48 — the reference's default workload shape (BERT-family, IMDb
padded to 512; reference ``launch.py:13-18``, ``scripts/train.py:81-86``)
on synthetic IMDb-shaped data (zero-egress environment). The reference
pins batch 8/worker; per-chip batch is a free throughput knob here, and
48 is the measured v5e sweet spot: a profiler trace showed batch 64
pushing HBM into XLA spill copies + auto-remat (~10% of step time in
pure copies), and the sweep confirms (8→221, 32→247, 40→260, 44→268-273,
48→263-268, 52→269, 56→258, 64→250, 96→231; 128 OOMs on 16G HBM).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the reference's default hardware envelope — BERT-base
fine-tuning at seq 512 / batch 8 / mixed precision on the ml.p3.2xlarge
V100, ≈32 samples/s (public MLPerf-era V100 BERT fine-tune throughput);
vs_baseline = our samples/sec/chip ÷ 32.

The line also carries FLOPs accounting: analytic matmul FLOPs/sample for
the benched model (fwd ≈ 2·N·tokens for the matmuls, train ≈ 3× fwd —
the standard model-FLOPs convention, which excludes remat recompute),
achieved TFLOP/s/chip, and MFU against the chip's bf16 peak.

Outage resilience (the reference's self-measurement contract is the
``train_runtime`` history emission around ``fit``, reference
``scripts/train.py:142,154-165``; ours must not turn into a stack trace
when the accelerator tunnel flaps): the parent process NEVER initializes
a JAX backend. It probes backend reachability in a short-timeout
subprocess with bounded retries, then runs the measured bench in a
supervised child with a hard timeout, forwarding the child's JSON line.
Any permanent failure — unreachable backend, child crash, child hang —
emits ONE structured JSON line (``"error": ...``) and exits 0 so the
driver always records a parseable artifact.

Extra modes (each also prints one JSON line per run):
  --model bert-large   the reference's actual default model
                       (bert-large-uncased-whole-word-masking shape:
                       24L/1024H/16 heads; reference ``launch.py:17``),
                       seq 512, per-chip batch 8.
  --buckets            headline workload with length bucketing enabled
                       on a realistic length distribution (vs pad-to-512).
  --mesh               scaling-efficiency instrument: per-step collective
                       vs compute time from a profiler trace.
  --generate           decode throughput: tokens/s/chip for GPT-2
                       prefill+scan and BART cached greedy + beam.
  --causal-lm          GPT-2 124M training throughput, fused
                       vocab-CE loss vs full-logits baseline.
  --mlm                BERT-base WWM pretraining throughput, sparse-
                       gather fused vocab-CE vs full-logits baseline.
  --lora               BERT-large + LoRA r=8: the frozen base carries no
                       Adam m/v or grad tree, buying per-chip batch 32
                       (full fine-tuning's HBM sweet spot is 8-16).
  --banded             banded-flash microbench: sliding-window vs full
                       causal fwd+bwd at seq 8192 (the O(S*window)
                       tile-skip claim, measured).
  --llama-train        TinyLlama-1.1B causal-LM training on one chip
                       (bf16 Adam + remat dots + fused vocab-CE +
                       flash), samples/s + MFU.
  --serve              continuous-batching serving engine (serve/:
                       paged KV + iteration-level scheduling) vs
                       static-batch generate_causal on a mixed-length
                       request trace (speedup, TTFT p50/p99, KV-pool
                       utilization, compile-flatness check), plus the
                       width-bucketed gather line: bucketed vs
                       full-width decode tokens/sec on a short-context
                       trace (>=1.3x CPU gate, identical outputs,
                       compiles <= #buckets), the speculative-decode
                       line (>=1.5x CPU gate), the prefix-cache
                       line: TTFT p50 with copy-on-write prefix
                       caching on vs off on a repeated-prefix trace
                       (>=2x CPU gate, identical outputs, block
                       conservation), the paged-kernel line:
                       int8 vs fp KV pools on a decode-dominated
                       trace (>=1.2x CPU gate, per-side exactness,
                       per-step pool bytes <=0.6x asserted), and the
                       tensor-parallel capacity line: TP=2 vs TP=1 on
                       the same per-device KV byte budget (>=2x
                       admission depth, <=0.55x per-device pool
                       bytes/token, token identity — all
                       deterministic gates).

Every metric line additionally carries a ``memory`` watermark field on
accelerator backends (peak_bytes_in_use vs bytes_limit, ROADMAP "Memory
watermarks") so HBM-spill regressions surface next to the throughput
they cost, plus an ``anomalies`` count from the run's anomaly detector
(``obs/anomaly.py``; zero on healthy runs). MFU rides on every training
line — on TPU from the peak table, elsewhere only under an explicit
``HSTD_PEAK_TFLOPS`` override. A measured body whose training loss went
non-finite exits ``ANOMALY_RC`` (3) AFTER printing its lines — the one
deliberate exception to the rc-0 contract, so CI catches silent
divergence (infra failures still exit 0 with structured error lines).

Results across rounds are recorded in BENCH_EXTRA.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

V100_BASELINE_SAMPLES_PER_SEC = 32.0
# BERT-large at seq 512 / bs 8 / mixed precision on one V100 runs ≈1/4 of
# BERT-base throughput — public MLPerf-era fine-tune numbers put it ≈8
# samples/s; same caveat as above: a literature anchor, not a measurement.
V100_BERT_LARGE_SAMPLES_PER_SEC = 8.0

BERT_LARGE = dict(hidden_size=1024, num_layers=24, num_heads=16,
                  intermediate_size=4096)


def chip_peak_tflops(device_kind: str) -> float | None:
    """Peak bf16 TFLOP/s for the chip — one source of truth in
    ``obs/flops.py`` (device_kind table + ``HSTD_PEAK_TFLOPS`` env
    override for chips the table doesn't know, CPU included)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.flops import (
        peak_tflops,
    )

    return peak_tflops(device_kind)


def train_flops_per_sample(seq_len: int, hidden_size: int = 768,
                           num_layers: int = 12,
                           intermediate_size: int = 3072) -> float:
    """Analytic matmul FLOPs for ONE training sample (fwd+bwd) of a
    BERT-family encoder. Delegates to the ONE FLOPs convention in
    ``obs/flops.py`` (3× forward; remat recompute excluded; embedding
    lookups / layernorms / softmax excluded, ~2% at these shapes) so
    bench-line MFU and trainer-history MFU can never drift."""
    import types

    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.flops import (
        train_flops_per_token,
    )

    cfg = types.SimpleNamespace(hidden_size=hidden_size,
                                num_layers=num_layers,
                                intermediate_size=intermediate_size,
                                vocab_size=0)
    return seq_len * train_flops_per_token(cfg, "seq-cls", seq_len)


def build_harness(model_kwargs: dict, per_chip_batch: int, seq_len: int = 512,
                  remat: bool = False, remat_policy: str = "full",
                  bucket_multiple: int = 0,
                  min_len: int = 300, max_len: int = 600, batches: int = 14,
                  opt_state_bf16: bool = False, lora_rank: int = 0,
                  lora_targets: str = "attention"):
    """(trainer, batcher) for one BERT-family benchmark config — the ONE
    place every bench mode builds its harness, so --mesh/--buckets always
    measure the same configuration the headline does."""
    import jax
    import jax.numpy as jnp

    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForSequenceClassification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    global_batch = per_chip_batch * n_chips

    mesh = build_mesh(MeshConfig(dp=-1))
    config = TrainConfig(dtype="bfloat16" if on_tpu else "float32",
                         train_batch_size=per_chip_batch,
                         max_seq_length=seq_len, log_every_steps=0,
                         remat=remat, bucket_multiple=bucket_multiple,
                         optimizer_state_dtype="bfloat16" if opt_state_bf16
                         else "float32", lora_rank=lora_rank,
                         lora_targets=lora_targets)
    model_cfg = EncoderConfig(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        max_position_embeddings=512,
        attention_impl=config.resolve_attention_impl(
            jax.devices()[0].platform),
        remat=remat, remat_policy=remat_policy,
        **model_kwargs)
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg, seed=0)
    trainer = Trainer(config, model, params, mesh)

    tok = WordHashTokenizer()
    texts, labels = synthetic_text_classification(
        global_batch * batches, seed=0, min_len=min_len, max_len=max_len)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq_len)
    batcher = ShardedBatcher(ds, global_batch, mesh, shuffle=False, seed=0,
                             bucket_sizes=config.bucket_sizes(seq_len))
    return trainer, batcher


def run_finetune(model_kwargs: dict, per_chip_batch: int,
                 epochs: int = 2, warmup_epochs: int = 0, **harness_kwargs):
    """Train-loop throughput for one BERT-family config; returns the fit
    history (the meter excludes the first, compiling, step and runs the
    REAL fit loop: async dispatch, background prefetch, no per-step host
    sync). ``warmup_epochs`` runs an unmeasured fit first so every bucket
    width compiles before the measured pass (the meter only skips the
    first step, which covers a single static shape)."""
    trainer, batcher = build_harness(model_kwargs, per_chip_batch,
                                     **harness_kwargs)
    if warmup_epochs:
        trainer.fit(batcher, epochs=warmup_epochs)
    return trainer.fit(batcher, epochs=epochs)


def _flops_detail(samples_per_sec_per_chip: float,
                  flops_per_sample: float) -> dict:
    """TFLOP/s/chip + MFU fields for an emit line. MFU is null when the
    chip's peak is unknown; on CPU the ``HSTD_PEAK_TFLOPS`` override is
    the only way to get one (the obsctl acceptance path uses it)."""
    import jax

    achieved = samples_per_sec_per_chip * flops_per_sample / 1e12
    peak = chip_peak_tflops(jax.devices()[0].device_kind)
    return {
        "model_tflops_per_sample": round(flops_per_sample / 1e12, 4),
        "achieved_tflops_per_chip": round(achieved, 4),
        "chip_peak_tflops": peak,
        "mfu": round(achieved / peak, 6) if peak else None,
    }


def _flops_reportable() -> bool:
    """Should a metric line carry FLOPs/MFU fields? Always on TPU;
    elsewhere only under an explicit ``HSTD_PEAK_TFLOPS`` (a guessed
    CPU peak would make MFU noise, not a metric)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.obs.flops import (
        env_peak_tflops,
    )

    return _on_tpu() or env_peak_tflops() is not None


def memory_watermark() -> dict | None:
    """Peak-vs-limit device-memory watermark across local devices
    (ROADMAP "Memory watermarks") — the figure that catches HBM-spill
    regressions like the batch-64 spill story without a profiler trace.
    None on CPU backends / before jax initializes (the supervisor
    parent never initializes a backend, so it must never call this
    successfully by accident)."""
    if "jax" not in sys.modules:
        return None
    jax = sys.modules["jax"]
    try:
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend gone / not initialized
        return None
    peaks = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — CPU backends raise
            stats = {}
        if stats.get("peak_bytes_in_use"):
            peaks.append((int(stats["peak_bytes_in_use"]),
                          int(stats.get("bytes_limit") or 0)))
    if not peaks:
        return None
    peak = max(p for p, _ in peaks)
    limit = max((lim for _, lim in peaks if lim), default=0)
    out = {"peak_bytes_in_use": peak}
    if limit:
        out["bytes_limit"] = limit
        out["peak_frac"] = round(peak / limit, 3)
    return out


def anomaly_field() -> dict:
    """The ``anomalies`` field every metric line carries: total count +
    per-kind breakdown from the live detector (zero/empty on healthy
    runs — which is what CI greps for)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs

    counts = obs.anomaly_counts()
    return {"anomalies": sum(counts.values()), **(
        {"anomaly_kinds": counts} if counts else {})}


def emit(metric: str, value: float, baseline: float,
         flops_per_sample: float | None = None, **extra) -> None:
    line = {
        "metric": metric,
        "value": round(value, 3),
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / baseline, 3),
    }
    if flops_per_sample is not None and _flops_reportable():
        line.update(_flops_detail(value, flops_per_sample))
    line.update(anomaly_field())
    mem = memory_watermark()
    if mem is not None:
        # every stage line carries the watermark: a spill regression
        # shows as peak_frac -> 1.0 next to the throughput it costs
        line["memory"] = mem
        print(f"[bench] memory watermark: peak {mem['peak_bytes_in_use']}"
              + (f" / limit {mem['bytes_limit']}"
                 f" ({mem['peak_frac']:.1%})" if "bytes_limit" in mem
                 else ""), file=sys.stderr)
    line.update(extra)
    print(json.dumps(line))


def _on_tpu() -> bool:
    import jax
    return jax.devices()[0].platform == "tpu"


def bench_headline(per_chip_batch: int | None = None,
                   opt_state_bf16: bool = False,
                   remat_policy: str | None = None) -> None:
    # batch 8 off-TPU keeps the CPU smoke run tractable
    if per_chip_batch is None:
        per_chip_batch = 48 if _on_tpu() else 8
    history = run_finetune({}, per_chip_batch=per_chip_batch,
                           opt_state_bf16=opt_state_bf16,
                           remat=remat_policy is not None,
                           remat_policy=remat_policy or "full")
    emit("bert_base_finetune_samples_per_sec_per_chip",
         history["train_samples_per_second_per_chip"],
         V100_BASELINE_SAMPLES_PER_SEC,
         flops_per_sample=train_flops_per_sample(512),
         detail={"per_chip_batch": per_chip_batch,
                 "optimizer_state_dtype":
                     "bfloat16" if opt_state_bf16 else "float32",
                 "remat_policy": remat_policy or "off"})


def _bert_large_flops_per_sample() -> float:
    """One source of truth for the BERT-large full-train FLOPs figure —
    both bert-large modes must report MFU under the same convention."""
    return train_flops_per_sample(512, **{
        k: v for k, v in BERT_LARGE.items() if k != "num_heads"})


def bench_lora() -> None:
    """BERT-large + LoRA r=8 (attention targets, trainable head): the
    base model's fp32 Adam m/v (2x 1.36G) and backbone grad tree vanish,
    so per-chip batch 32 — past full fine-tuning's HBM sweet spot of
    8-16 — runs without spills. Same measurement contract as the
    bert-large mode, so the samples/s and vs_baseline compare directly
    (baseline: the reference's full fine-tune on V100)."""
    batch = 32 if _on_tpu() else 1
    targets = "attention"
    history = run_finetune(BERT_LARGE, per_chip_batch=batch,
                           lora_rank=8, lora_targets=targets)
    # FLOPs convention: full fine-tune is ~3x forward (fwd + dX + dW);
    # with the backbone's dW matmuls dead-code-eliminated (stop-gradient
    # base, models/lora.py) the hardware executes ~2x forward, so MFU
    # must be computed against 2/3 of the full-train FLOPs — the 3x
    # figure would overstate utilization by ~1.5x
    full_flops = _bert_large_flops_per_sample()
    emit("bert_large_lora_r8_samples_per_sec_per_chip",
         history["train_samples_per_second_per_chip"],
         V100_BERT_LARGE_SAMPLES_PER_SEC,
         flops_per_sample=full_flops * 2.0 / 3.0,
         detail={"per_chip_batch": batch, "lora_rank": 8,
                 "lora_targets": targets,
                 "flops_convention": "fwd+dx only (no backbone dW)"})


def bench_bert_large() -> None:
    # the reference's default workload at its default size: bs 8/worker
    # (reference launch.py:13-18); 340M params + fp32 Adam state fit one
    # 16G chip without encoder remat
    history = run_finetune(BERT_LARGE, per_chip_batch=8 if _on_tpu() else 1)
    emit("bert_large_wwm_finetune_samples_per_sec_per_chip",
         history["train_samples_per_second_per_chip"],
         V100_BERT_LARGE_SAMPLES_PER_SEC,
         flops_per_sample=_bert_large_flops_per_sample())


# ---------------------------------------------------------------------------
# Outage-resilient supervisor (parent process; never initializes JAX)
# ---------------------------------------------------------------------------

def _default_budget() -> float | None:
    """Overall deadline for one bench invocation, settable without
    touching the driver's command line (``BENCH_BUDGET_SECONDS``). None
    preserves the unbounded-patience behavior (probe retries sized for
    tunnel flaps + 30 min child timeout)."""
    raw = os.environ.get("BENCH_BUDGET_SECONDS", "").strip()
    try:
        return float(raw) if raw else None
    except ValueError:
        return None


PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
# The tunnel flaps on a scale of hours, not minutes (observed r2-r4):
# 15 attempts with exponential backoff (5s doubling, capped 60s) plus
# 120s probe timeouts gives ~41 min of total patience in the worst
# (every-probe-hangs) case while still returning within seconds once the
# backend answers. Total-patience arithmetic: 15*120s probes + 675s of
# waits ≈ 2475s.
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "15"))
PROBE_RETRY_WAIT_S = int(os.environ.get("BENCH_PROBE_RETRY_WAIT", "5"))
PROBE_RETRY_CAP_S = int(os.environ.get("BENCH_PROBE_RETRY_CAP", "60"))
CHILD_TIMEOUT_S = int(os.environ.get("BENCH_TIMEOUT", "1800"))
PARITY_TIMEOUT_S = int(os.environ.get("BENCH_PARITY_TIMEOUT", "600"))
# exit code reserved for "measured fine but the run diverged" (NaN-loss
# anomaly): the child returns it, the supervisor propagates it
ANOMALY_RC = 3

_PROBE_CODE = (
    "import json, jax; d = jax.devices(); "
    "print(json.dumps({'platform': d[0].platform, 'n': len(d), "
    "'device_kind': d[0].device_kind}))"
)


def probe_backend(deadline: float | None = None) -> dict:
    """Initialize the JAX backend in a short-timeout subprocess; return
    ``{'ok': True, 'platform': ...}`` or ``{'ok': False, 'attempts': [...]}``.
    A hung accelerator tunnel hangs the CHILD, not this process.
    ``deadline`` (monotonic seconds) caps total probe patience — under a
    ``--budget-seconds`` run the probe must leave the measured body its
    share of the budget instead of spending ~41 min on retries."""
    attempts = []
    for i in range(PROBE_ATTEMPTS):
        per_probe = PROBE_TIMEOUT_S
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 1:
                attempts.append({"attempt": i + 1,
                                 "outcome": "budget_exhausted"})
                break
            per_probe = max(1, min(PROBE_TIMEOUT_S, int(remaining)))
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE], cwd=_REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                timeout=per_probe)
        except subprocess.TimeoutExpired:
            attempts.append({"attempt": i + 1,
                             "outcome": f"timeout>{per_probe}s"})
        else:
            if proc.returncode == 0:
                try:
                    info = json.loads(proc.stdout.strip().splitlines()[-1])
                except (ValueError, IndexError):
                    attempts.append({"attempt": i + 1,
                                     "outcome": "unparseable probe output"})
                else:
                    info.update(ok=True, attempts=attempts)
                    return info
            else:
                attempts.append({"attempt": i + 1,
                                 "outcome": f"rc={proc.returncode}",
                                 "stderr_tail": proc.stderr[-300:]})
        if i + 1 < PROBE_ATTEMPTS:
            wait = min(PROBE_RETRY_CAP_S, PROBE_RETRY_WAIT_S * 2 ** i)
            if deadline is not None:
                wait = min(wait, max(deadline - time.monotonic(), 0))
                if wait <= 0:
                    continue  # next iteration records budget_exhausted
            time.sleep(wait)
    return {"ok": False, "attempts": attempts}


def run_kernel_parity() -> dict:
    """Run the ~2-min compiled-kernel-parity subset in a supervised
    subprocess and return a compact summary for the headline JSON line,
    so ONE tunnel window banks throughput + kernel evidence in the same
    driver-captured artifact (VERDICT r4 #2). Never raises; a parity
    failure/timeout is reported in the field, not fatal to the headline."""
    argv = [sys.executable,
            os.path.join(_REPO_ROOT, "benchmarks", "tpu_kernel_parity.py"),
            "--subset"]
    try:
        proc = subprocess.run(argv, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=PARITY_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout>{PARITY_TIMEOUT_S}s"}
    lines = proc.stdout.splitlines()
    passed = sum(1 for ln in lines if ln.startswith("PASS "))
    failed = [ln.split(":", 1)[0][5:] for ln in lines if ln.startswith("FAIL ")]
    summary = {"pass": passed, "fail": len(failed), "subset": True,
               "rc": proc.returncode}
    if failed:
        summary["failed"] = failed
    if proc.returncode == 2:
        summary["error"] = "no_evidence_not_tpu"
    elif proc.returncode != 0 and not failed:
        summary["error"] = "crashed"
        summary["tail"] = proc.stdout[-300:]
    return summary


def bench_lint() -> None:
    """The ``--lint`` stage: run graftlint over the tree and emit one
    ``lint_findings`` count line. Zero-baseline count semantics (shared
    with compiles/anomalies): the healthy value is 0, ANY unsuppressed
    finding is a regression, worse direction UP — which is exactly how
    ``obsctl diff`` gates the matching report scalar. Runs in-process
    (no jax, no supervised child: the linter is stdlib-only by rule
    R1), and mirrors the count into telemetry (``lint/findings``) when
    a sink is configured so ``obsctl report`` carries it."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs
    from huggingface_sagemaker_tensorflow_distributed_tpu.analysis.lint import (
        LintInputError,
        run_lint,
    )

    root = os.path.dirname(os.path.abspath(__file__))
    try:
        result = run_lint(root)
    except LintInputError as e:
        emit_error(["lint_findings"], "lint_bad_input",
                   {"message": str(e)})
        return
    n = len(result.active)
    if obs.has_sink():
        obs.scalar("lint/findings", n)
        obs.flush()
    print(json.dumps({
        "metric": "lint_findings", "value": n, "unit": "findings",
        "vs_baseline": None, "worse_direction": "up",
        "suppressed": len(result.suppressed),
        "per_rule": result.counts(),
        "detail": {"finding": [f.render() for f in result.active[:20]]}
        if n else {},
    }))


def emit_error(metrics: list[str], error: str, detail: dict) -> None:
    """The structured-failure contract: one parseable JSON line per
    metric the mode would have produced, rc 0."""
    for metric in metrics:
        print(json.dumps({"metric": metric, "value": None, "unit": None,
                          "vs_baseline": None, "error": error,
                          "detail": detail}))


def _mode_metrics(args: argparse.Namespace) -> list[str]:
    """Exactly the metric names the mode emits on success, so error and
    success lines for one mode correlate by name."""
    if args.mesh:
        return ["train_step_collective_fraction"]
    if args.buckets:
        return ["bert_base_bucketed_samples_per_sec_per_chip"]
    if args.generate:
        return [f"generate_{m}_tokens_per_sec_per_chip"
                for m in ("gpt2_greedy", "gpt2_greedy_int8",
                          "llama_greedy", "llama_greedy_int8",
                          "llama_greedy_b1", "llama_self_spec_b1",
                          "bart_greedy", "bart_beam4")]
    if args.causal_lm:
        return ["gpt2_finetune_fused_ce_samples_per_sec_per_chip"]
    if args.mlm:
        return ["bert_base_mlm_fused_ce_samples_per_sec_per_chip"]
    if args.banded:
        return ["flash_banded_fwd_bwd_ms"]
    # getattr: test harnesses build Namespaces predating this flag
    if getattr(args, "data", False):
        return ["data_pipeline_microbench"]
    if getattr(args, "serve", False):
        return ["serve_continuous_vs_static_speedup",
                "serve_bucketed_gather_decode_speedup",
                "serve_speculative_decode_speedup",
                "serve_prefix_cache_ttft_speedup",
                "serve_paged_kernel_decode_speedup",
                "serve_overlap_decode_speedup",
                "serve_tp_shard_capacity",
                "serve_router_scaleout",
                "serve_open_loop_goodput"]
    if args.llama_train:
        return ["llama_1b_train_samples_per_sec_per_chip"]
    if args.mixtral_train:
        return ["mixtral_moe_train_samples_per_sec_per_chip"]
    if args.lora:
        return ["bert_large_lora_r8_samples_per_sec_per_chip"]
    if args.model == "bert-large":
        return ["bert_large_wwm_finetune_samples_per_sec_per_chip"]
    return ["bert_base_finetune_samples_per_sec_per_chip"]


def emit_provisional(metrics: list[str], stage: str, **extra) -> None:
    """One parseable JSON line marking progress. THE fix for the
    BENCH r05 empty-tail artifact: if the driver's own timeout kills this
    process at ANY point after startup, the last stdout line is already
    valid JSON naming the stage that was running — never an empty tail
    with ``parsed: null``."""
    line = {"metric": metrics[0], "value": None, "unit": None,
            "vs_baseline": None, "provisional": True, "stage": stage}
    line.update(extra)
    print(json.dumps(line), flush=True)


def _forward_partial(metrics: list[str], partial: str, error: str,
                     detail: dict) -> None:
    """Forward whatever COMPLETE JSON lines a dead child managed to
    print (partial results beat no results), then the error line."""
    for ln in partial.splitlines():
        try:
            json.loads(ln)
        except ValueError:
            continue
        print(ln)
    emit_error(metrics, error, detail)


def supervise(args: argparse.Namespace) -> None:
    """Probe the backend, then run the measured bench in a supervised
    child, forwarding its output; emit a structured error line (rc 0) on
    unreachable backend / child crash / child hang. With a budget
    (``--budget-seconds`` / ``BENCH_BUDGET_SECONDS``) every stage gets a
    deadline and a timeout degrades to partial output, not an empty tail."""
    metrics = _mode_metrics(args)
    budget = args.budget_seconds
    t_start = time.monotonic()
    deadline = t_start + budget if budget is not None else None
    # the measured child streams telemetry (events.jsonl + trace.json):
    # a run that dies mid-compile still leaves heartbeat/compile events
    child_env = dict(os.environ)
    child_env.setdefault("HSTD_TELEMETRY_DIR",
                         os.path.join(os.getcwd(), "telemetry"))
    emit_provisional(metrics, "probing",
                     budget_s=budget, all_metrics=metrics)
    info = probe_backend(deadline=deadline)
    if not info.get("ok"):
        emit_error(metrics, "backend_unreachable", info)
        return
    print(f"[bench] backend ok: {info.get('platform')} x{info.get('n')} "
          f"({info.get('device_kind')})", file=sys.stderr)
    emit_provisional(metrics, "measuring", backend=info)

    if (getattr(args, "serve", False) and info.get("platform") == "cpu"
            and "xla_force_host_platform_device_count"
            not in child_env.get("XLA_FLAGS", "")):
        # the serve_tp_shard_capacity line shards an engine over 2
        # devices; a CPU host exposes 1 by default, so force a 2-device
        # host platform in the measured child (same mechanism the test
        # conftest uses — harmless to the single-device lines, which
        # keep placing everything on device 0)
        child_env["XLA_FLAGS"] = (
            child_env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()

    child_argv = [sys.executable, os.path.abspath(__file__),
                  *sys.argv[1:], "--_child"]
    child_timeout = CHILD_TIMEOUT_S
    if deadline is not None:
        # +10s grace: the child's own in-process alarm fires first and
        # emits partial JSON + flushes telemetry; this outer timeout only
        # catches a child wedged in native code where signals can't land
        remaining = max(deadline - time.monotonic(), 5)
        child_timeout = remaining + 10
        child_env["_BENCH_CHILD_BUDGET"] = str(round(remaining, 1))
    try:
        proc = subprocess.run(
            child_argv, cwd=_REPO_ROOT, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, timeout=child_timeout,
            env=child_env)
    except subprocess.TimeoutExpired as e:
        partial = e.stdout or b""
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        _forward_partial(metrics, partial, "bench_timeout",
                         {"timeout_s": round(child_timeout, 1),
                          "backend": info,
                          "partial_stdout": partial[-500:]})
        return
    if proc.returncode == ANOMALY_RC:
        # NaN-loss contract: the child measured and emitted real lines
        # (each carrying the anomalies field) but the run diverged —
        # forward the lines verbatim and PROPAGATE the nonzero exit so
        # CI catches silent divergence. Infra failures below keep the
        # rc-0 structured-error contract; divergence is a result, not
        # an infra failure.
        sys.stdout.write(proc.stdout)
        sys.stdout.flush()
        print("[bench] NaN-loss anomaly: exiting nonzero", file=sys.stderr)
        sys.exit(ANOMALY_RC)
    if proc.returncode != 0:
        _forward_partial(metrics, proc.stdout, "bench_failed",
                         {"rc": proc.returncode, "backend": info,
                          "stdout_tail": proc.stdout[-500:]})
        return
    parity_affordable = (deadline is None
                         or deadline - time.monotonic() > PARITY_TIMEOUT_S)
    if (metrics == ["bert_base_finetune_samples_per_sec_per_chip"]
            and args.batch is None and not args.opt_state_bf16
            and args.remat_policy is None and parity_affordable):
        # default (driver) invocation only: append compiled-kernel-parity
        # evidence to the same line the driver records; the --batch /
        # --opt-state-bf16 sweep variants skip it so a tunnel-window
        # sweep doesn't pay ~2 min of parity per step. Parse the
        # headline BEFORE spending parity time: if the line is
        # unparseable the parity field has nowhere to land anyway.
        out_lines = proc.stdout.strip().splitlines()
        try:
            headline = json.loads(out_lines[-1])
        except (ValueError, IndexError):
            sys.stdout.write(proc.stdout)
        else:
            print("[bench] running kernel-parity subset", file=sys.stderr)
            headline["kernel_parity"] = run_kernel_parity()
            for ln in out_lines[:-1]:
                print(ln)
            print(json.dumps(headline))
        sys.stdout.flush()
        return
    sys.stdout.write(proc.stdout)
    sys.stdout.flush()


def _setup_child_telemetry() -> None:
    """Instrument the measured child: file-backed telemetry, compile
    tracker, and a fast heartbeat (10s default instead of 60: bench
    bodies are minutes long, and the heartbeat is what leaves evidence
    on disk when the run is killed mid-compile)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs

    out = (os.environ.get(obs.ENV_DIR, "").strip()
           or os.path.join(os.getcwd(), "telemetry"))
    obs.configure(out_dir=out)
    if not obs.has_sink():
        return
    obs.compile_tracker()
    hb = obs.heartbeat(interval=obs.heartbeat_env_interval(default=10.0))
    hb.start()
    hb.watch_current_thread()
    import atexit

    atexit.register(obs.shutdown)


def _install_child_budget(args: argparse.Namespace) -> None:
    """SIGALRM/SIGTERM → partial-result JSON + telemetry flush + exit 0.
    The alarm leads the supervisor's kill by design; if the process is
    wedged in native code where Python signals can't run, the heartbeat
    thread has been flushing trace.json all along and the supervisor
    forwards whatever stdout exists."""
    budget = os.environ.get("_BENCH_CHILD_BUDGET", "").strip()
    try:
        budget_s = float(budget) if budget else args.budget_seconds
    except ValueError:
        budget_s = args.budget_seconds
    if budget_s is None:
        return
    import signal

    metrics = _mode_metrics(args)

    def _bail(signum, frame):
        try:
            from huggingface_sagemaker_tensorflow_distributed_tpu import obs
            obs.flush()
        except Exception:  # noqa: BLE001 — partial emission must not die
            pass
        # leading newline: the alarm may land mid-print of a metric
        # line; starting fresh keeps the final stdout line parseable
        # (the whole point of the partial-result contract)
        sys.stdout.write("\n")
        emit_error(metrics, "budget_exceeded",
                   {"budget_s": budget_s, "signal": int(signum),
                    "partial": True})
        sys.stdout.flush()
        os._exit(0)

    signal.signal(signal.SIGTERM, _bail)
    if hasattr(signal, "SIGALRM"):
        signal.signal(signal.SIGALRM, _bail)
        signal.alarm(max(int(budget_s) - 5, 1))


def _check_divergence_exit() -> None:
    """NaN-loss gate (CI contract): a measured body whose training loss
    went non-finite exits ``ANOMALY_RC`` AFTER its metric lines are on
    stdout — silent divergence must not look like a healthy bench."""
    from huggingface_sagemaker_tensorflow_distributed_tpu import obs

    counts = obs.anomaly_counts()
    if counts.get("nan_loss") or counts.get("nan_grad"):
        print(f"[bench] divergence anomalies detected: {counts} — "
              "exiting nonzero", file=sys.stderr)
        try:
            obs.flush()
        except Exception:  # noqa: BLE001
            pass
        sys.stdout.flush()
        sys.exit(ANOMALY_RC)


def _run_child(args: argparse.Namespace) -> None:
    _setup_child_telemetry()
    _install_child_budget(args)
    if args.mesh:
        from benchmarks.mesh_bench import bench_mesh
        bench_mesh()
    elif args.buckets:
        from benchmarks.bucket_bench import bench_buckets
        bench_buckets()
    elif args.generate:
        from benchmarks.generate_bench import bench_generate
        bench_generate()
    elif args.causal_lm:
        from benchmarks.causal_lm_bench import bench_causal_lm
        bench_causal_lm()
    elif args.mlm:
        from benchmarks.mlm_bench import bench_mlm
        bench_mlm()
    elif args.banded:
        from benchmarks.banded_bench import bench_banded
        bench_banded()
    elif getattr(args, "data", False):
        from benchmarks.data_bench import bench_data
        bench_data()
    elif getattr(args, "serve", False):
        from benchmarks.serve_bench import bench_serve
        bench_serve()
    elif args.llama_train:
        from benchmarks.llama_train_bench import bench_llama_train
        bench_llama_train()
    elif args.mixtral_train:
        from benchmarks.mixtral_train_bench import bench_mixtral_train
        bench_mixtral_train()
    elif args.lora:
        bench_lora()
    elif args.model == "bert-large":
        bench_bert_large()
    else:
        bench_headline(per_chip_batch=args.batch,
                       opt_state_bf16=args.opt_state_bf16,
                       remat_policy=args.remat_policy)
    _check_divergence_exit()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", choices=["bert-base", "bert-large"],
                        default=None)
    parser.add_argument("--buckets", action="store_true")
    parser.add_argument("--mesh", action="store_true")
    parser.add_argument("--generate", action="store_true")
    parser.add_argument("--causal-lm", action="store_true", dest="causal_lm")
    parser.add_argument("--mlm", action="store_true")
    parser.add_argument("--lora", action="store_true",
                        help="BERT-large + LoRA r=8: adapter-only "
                             "optimizer state buys batch 32 on one chip")
    parser.add_argument("--banded", action="store_true",
                        help="banded-flash microbench (sliding window vs "
                             "full causal at seq 8192)")
    parser.add_argument("--data", action="store_true",
                        help="input-pipeline microbench: prefetch-depth "
                             "autotune consumer-wait reduction + pad-waste "
                             "bucketing-vs-packing (CPU-friendly)")
    parser.add_argument("--serve", action="store_true",
                        help="continuous-batching serving bench: mixed-"
                             "length request trace through serve/engine "
                             "(paged KV + iteration-level scheduling) vs "
                             "static-batch generate_causal (TTFT "
                             "p50/p99, aggregate tokens/sec, KV-pool "
                             "utilization, compile flatness) + the "
                             "bucketed-gather decode speedup on a "
                             "short-context trace + the speculative "
                             "draft/verify decode speedup on a high-"
                             "acceptance trace + the tensor-parallel "
                             "shard-capacity line (TP=2 vs TP=1 on "
                             "the same per-device KV byte budget) + "
                             "the multi-replica router scale-out line "
                             "(2 engine replicas vs 1: placement-"
                             "policy token identity, 2x fleet "
                             "admission depth, affinity-vs-round-"
                             "robin cache hit rate, load imbalance) + "
                             "the open-loop goodput line (Poisson "
                             "arrival schedule on a virtual clock: "
                             "SLO attainment at underload/overload "
                             "rates, queue-dominant miss attribution, "
                             "wall-clock capacity knee reported)")
    parser.add_argument("--lint", action="store_true",
                        help="graftlint static-analysis stage: emit a "
                             "lint_findings count line (0 = clean; "
                             "count metric, worse direction UP, "
                             "zero-baseline regression rule shared "
                             "with compiles/anomalies). Runs "
                             "in-process and jax-less")
    parser.add_argument("--llama-train", action="store_true",
                        dest="llama_train",
                        help="TinyLlama-1.1B training throughput "
                             "(bf16 Adam + remat dots + fused CE)")
    parser.add_argument("--mixtral-train", action="store_true",
                        dest="mixtral_train",
                        help="sparse-MoE (Mixtral-style, 8 experts "
                             "alternating) training throughput, routed-"
                             "FLOPs MFU convention")
    parser.add_argument("--batch", type=int, default=None,
                        help="per-chip batch override (headline mode)")
    parser.add_argument("--opt-state-bf16", action="store_true",
                        dest="opt_state_bf16",
                        help="bf16 Adam m/v storage (halved optimizer HBM; "
                             "headline mode)")
    parser.add_argument("--remat-policy", dest="remat_policy", default=None,
                        choices=["full", "dots", "dots_no_batch"],
                        help="enable encoder remat with this checkpoint "
                             "policy (headline mode; default: remat off)")
    parser.add_argument("--budget-seconds", dest="budget_seconds",
                        type=float, default=_default_budget(),
                        help="overall deadline for this invocation: the "
                             "probe, measured child, and parity subset "
                             "share it, and on expiry the run degrades "
                             "to partial-result JSON (rc 0) instead of "
                             "an empty tail (default: "
                             "BENCH_BUDGET_SECONDS env or unbounded)")
    parser.add_argument("--_child", action="store_true",
                        help=argparse.SUPPRESS)  # internal: run measured body
    args = parser.parse_args()
    picked = [n for n, on in [("--model", args.model is not None),
                              ("--buckets", args.buckets),
                              ("--mesh", args.mesh),
                              ("--generate", args.generate),
                              ("--causal-lm", args.causal_lm),
                              ("--mlm", args.mlm),
                              ("--lora", args.lora),
                              ("--banded", args.banded),
                              ("--data", args.data),
                              ("--serve", args.serve),
                              ("--lint", args.lint),
                              ("--llama-train", args.llama_train),
                              ("--mixtral-train", args.mixtral_train)] if on]
    if len(picked) > 1:
        parser.error(f"pick one mode, got {' and '.join(picked)}")
    if (args.batch is not None or args.opt_state_bf16
            or args.remat_policy) and picked:
        # headline-only knobs: other modes hardcode their configuration,
        # so dropping these silently would mislabel the measurement
        parser.error("--batch/--opt-state-bf16/--remat-policy apply to "
                     f"the headline mode only, not {picked[0]}")

    if args.lint:
        # no supervised child: the stage is stdlib-only and sub-second,
        # and the probe/budget machinery exists for jax workloads
        bench_lint()
    elif getattr(args, "_child"):
        _run_child(args)
    else:
        supervise(args)


if __name__ == "__main__":
    main()
