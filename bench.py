"""Headline benchmark: BERT-base fine-tune samples/sec/chip.

Runs the real jitted training step (same code path as ``scripts/train.py``)
on the available TPU chip(s): BERT-base, seq 512, bf16 compute, Pallas
flash attention, per-chip batch 64 — the reference's default workload
shape (BERT-family, IMDb padded to 512; reference ``launch.py:13-18``,
``scripts/train.py:81-86``) on synthetic IMDb-shaped data (zero-egress
environment). The reference pins batch 8/worker; per-chip batch is a
free throughput knob here, and 64 is the measured v5e sweet spot
(8→221, 32→247, 64→251, 96→231 samples/s/chip; 128 OOMs on 16G HBM).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the reference's default hardware envelope — BERT-base
fine-tuning at seq 512 / batch 8 / mixed precision on the ml.p3.2xlarge
V100, ≈32 samples/s (public MLPerf-era V100 BERT fine-tune throughput);
vs_baseline = our samples/sec/chip ÷ 32.
"""

from __future__ import annotations

import json

V100_BASELINE_SAMPLES_PER_SEC = 32.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from huggingface_sagemaker_tensorflow_distributed_tpu.config import TrainConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.data import (
        ArrayDataset,
        ShardedBatcher,
        WordHashTokenizer,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.data.sources import (
        synthetic_text_classification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import init_params
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bert import (
        BertForSequenceClassification,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel import (
        MeshConfig,
        build_mesh,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.train import Trainer

    n_chips = len(jax.devices())
    on_tpu = jax.devices()[0].platform == "tpu"
    seq_len = 512
    per_chip_batch = 64 if on_tpu else 8
    global_batch = per_chip_batch * n_chips

    mesh = build_mesh(MeshConfig(dp=-1))
    config = TrainConfig(dtype="bfloat16" if on_tpu else "float32",
                         train_batch_size=per_chip_batch,
                         max_seq_length=seq_len, log_every_steps=0)
    model_cfg = EncoderConfig(
        dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        max_position_embeddings=512,  # BERT-base
        attention_impl=config.resolve_attention_impl(
            jax.devices()[0].platform))
    model = BertForSequenceClassification(model_cfg, num_labels=2)
    params = init_params(model, model_cfg, seed=0)
    trainer = Trainer(config, model, params, mesh)

    tok = WordHashTokenizer()
    n_examples = global_batch * 14
    texts, labels = synthetic_text_classification(n_examples, seed=0,
                                                  min_len=300, max_len=600)
    ds = ArrayDataset.from_texts(tok, texts, labels, max_length=seq_len)
    batcher = ShardedBatcher(ds, global_batch, mesh, shuffle=False, seed=0)

    # measure through the REAL fit loop (async dispatch, background
    # prefetch, no per-step host sync): the same path scripts/train.py
    # runs, minus logging — the meter excludes the first (compile) step
    history = trainer.fit(batcher, epochs=2)
    value = round(history["train_samples_per_second_per_chip"], 3)
    print(json.dumps({
        "metric": "bert_base_finetune_samples_per_sec_per_chip",
        "value": value,
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / V100_BASELINE_SAMPLES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
