"""Typed configuration: hyperparameters, environment contract, CLI.

Capability parity with the reference's three-tier config layer
(launcher dict → platform-serialized CLI strings → argparse with env
defaults; reference ``launch.py:13-18`` and ``scripts/train.py:36-52``),
rebuilt as ONE typed dataclass with validated parsing. This fixes by
construction the reference's stringly-typed bugs:

- ``--learning_rate`` declared ``type=str`` (reference
  ``scripts/train.py:43``) so ``lr * world_size`` performs string
  repetition when the flag is passed → here it is a float.
- ``--do_train``/``--do_eval`` declared ``type=bool`` (reference
  ``scripts/train.py:44-45``) so ``--do_train False`` is truthy → here
  booleans parse "true/false/1/0" properly.

Environment contract: the reference consumes SageMaker's ``SM_OUTPUT_DATA_DIR``,
``SM_MODEL_DIR``, ``SM_NUM_GPUS`` (``scripts/train.py:48-50``). We honour the
same variables for drop-in compatibility and add TPU-native equivalents
(``TPU_OUTPUT_DATA_DIR``, ``TPU_MODEL_DIR``) plus multi-host coordination
variables (``TPU_COORDINATOR_ADDRESS``, ``TPU_NUM_PROCESSES``,
``TPU_PROCESS_ID``) consumed by ``parallel.distributed``.

Unknown CLI args are tolerated (``parse_known_args``), matching the
reference's tolerance of platform-injected extras (``scripts/train.py:52``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from dataclasses import dataclass, field, fields
from typing import Optional


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "y"):
        return True
    if s in ("false", "0", "no", "n", ""):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


# task heads that stay fully trainable under LoRA by default (PEFT
# ``modules_to_save`` analogue); single source of truth — models/lora.py
# re-exports this as ``HEAD_REGEX_DEFAULT``
LORA_HEAD_REGEX_DEFAULT = r"(classifier|qa_outputs|pooler)"


def _env(*names: str, default: Optional[str] = None) -> Optional[str]:
    for name in names:
        if name in os.environ:
            return os.environ[name]
    return default


@dataclass
class TrainConfig:
    """All knobs for a fine-tuning job.

    Field names follow the reference's hyperparameter contract
    (``launch.py:13-18``: epochs, train_batch_size, eval_batch_size,
    model_name_or_path) so launcher dicts are drop-in compatible.
    """

    # --- model / task ---
    model_name_or_path: str = "bert-base-uncased"
    task: str = "seq-cls"          # seq-cls | token-cls | qa | seq2seq |
                                   # causal-lm | mlm | rtd
    num_labels: int = 2
    max_seq_length: int = 512      # reference pads to tokenizer.model_max_length=512 (train.py:81)
    max_target_length: int = 64    # seq2seq decoder length (summaries are short)
    # T5 pretraining: corrupt spans of the input text instead of a
    # source/target dataset (task stays seq2seq; any text source works)
    span_corruption: bool = False
    # seq2seq eval extra: greedy-generate this many eval examples and
    # report ROUGE-L alongside loss/accuracy (0 = off; generation is a
    # separate pass, so this scales eval cost with the sample count)
    eval_rouge_samples: int = 0
    # qa eval extra: decode predicted answer TEXTS for this many eval
    # examples and report SQuAD exact-match/F1 alongside span accuracy
    # (0 = off; one extra forward pass over the sampled examples)
    eval_qa_samples: int = 0
    # fused-MLM static gather capacity as a fraction of each shard's
    # tokens; must exceed the dataset's masking rate (default 0.15 HF
    # rate → 0.25 cap). Positions beyond the cap are dropped from loss
    # AND count (surfaced as the ce_dropped metric) — raise this when
    # pretraining with a higher mlm_probability
    fused_mlm_mask_cap: float = 0.25
    # pin MLM masks to the seed draw for every epoch (pre-r4 behavior;
    # ablation knob — default re-draws per epoch like HF's collator)
    mlm_static_masking: bool = False
    # causal-lm pretraining: pack documents EOS-joined into completely
    # full rows (zero pad waste — every MXU cycle on real tokens)
    packed_sequences: bool = False
    # token packing WITH per-example boundaries (data/pipeline.py::
    # pack_examples): short examples share rows behind segment ids +
    # restarting positions, attention stays block-diagonal per example
    # (cross-contamination-safe) and loss/metrics match unpacked exactly
    # — the pad-waste fix for fine-tuning corpora where packed_sequences'
    # cross-document attention is not acceptable. causal-lm and mlm.
    segment_packing: bool = False
    from_scratch: bool = False     # random init instead of pretrained weights

    # --- data ---
    dataset: str = "imdb"          # imdb | sst2 | conll2003 | squad | cnn_dailymail | synthetic
    dataset_path: Optional[str] = None   # local dataset dir (offline mode)
    # stream the corpus from disk instead of materializing it densely in
    # host RAM (mlm / causal-lm / seq-cls; fixes the reference's
    # materialize-everything quirk at scripts/train.py:80-83). Train-side
    # only; eval sets stay materialized (they're small and need ROUGE/EM
    # decoding access)
    streaming: bool = False
    max_train_samples: Optional[int] = None
    max_eval_samples: Optional[int] = None

    # --- optimization (reference defaults: train.py:39-43) ---
    epochs: int = 3
    train_batch_size: int = 8      # per-worker, as in the reference (launch.py:15)
    eval_batch_size: int = 4
    learning_rate: float = 5e-5
    scale_lr_by_world_size: bool = True   # reference semantics: lr × hvd.size() (train.py:112)
    # adamw default (adam = exact reference parity, coupled, no decay);
    # adafactor = T5's sublinear-memory pretraining optimizer (no
    # weight_decay); lamb = large-batch (pod-scale) BERT
    optimizer: str = "adamw"       # adamw | adam | adafactor | lamb
    # bf16 storage for Adam's m/v buffers (fp32 compute each step):
    # halves optimizer HBM — batch-size headroom at the 16G ceiling.
    # adam/adamw only (adafactor is already sublinear; lamb unsupported)
    optimizer_state_dtype: str = "float32"   # float32 | bfloat16
    lr_schedule: str = "linear"    # linear | cosine (with warmup_ratio > 0)
    warmup_ratio: float = 0.0
    weight_decay: float = 0.0
    max_grad_norm: float = 0.0     # 0 disables clipping (reference has none)
    # uniform label smoothing for seq2seq fine-tuning (T5/BART
    # convention, HF --label_smoothing_factor; train-time only — eval
    # loss stays plain CE). Composes with --fused_vocab_ce: the kernel
    # carries a running logit-sum next to its online-softmax stats.
    label_smoothing: float = 0.0
    # micro-batches averaged per optimizer update (1 = off): grows the
    # effective batch beyond HBM limits (e.g. BERT-large past bs 8/chip)
    gradient_accumulation_steps: int = 1
    steps_per_epoch: Optional[int] = None
    seed: int = 42
    # dropout-key PRNG. "rbg" uses the TPU's hardware RNG instruction —
    # threefry key-schedule math otherwise fuses into the weight-gradient
    # matmuls and throttles the MXU (~25% step time on BERT-base).
    # "threefry" remains for bit-exact cross-platform reproducibility.
    rng_impl: str = "rbg"

    # --- precision ---
    dtype: str = "bfloat16"        # compute dtype on TPU; tests override to float32
    param_dtype: str = "float32"

    # --- parallelism mesh (reference supports DP only; see SURVEY.md §2) ---
    dp: int = -1                   # -1: use all remaining devices on the data axis
    fsdp: int = 1
    ep: int = 1                    # expert parallel (MoE expert sharding)
    pp: int = 1                    # pipeline parallel (GPipe over stacked layers)
    tp: int = 1
    sp: int = 1                    # sequence/context parallel (ring attention)
    # outer data-parallel axis across slices connected by DCN rather
    # than ICI (multi-slice); blocks group whole slices/processes
    dcn_dp: int = 1
    # microbatches per pipeline round-trip (0 → = pp); more microbatches
    # shrink the fill/drain bubble: overhead ~ (pp-1)/(M+pp-1)
    pipeline_microbatches: int = 0

    # --- Mixture-of-Experts (models/moe.py; beyond-parity — the
    #     reference has no MoE). 0 = dense FFN everywhere. MoE weights
    #     are always fresh-initialized (HF BERT-family checkpoints have
    #     no experts); use with --from_scratch or for upcycling. ---
    num_experts: int = 0
    expert_top_k: int = 2
    moe_every: int = 2

    # --- kernels / memory ---
    # auto: flash (Pallas) on TPU, xla elsewhere, ring when sp > 1.
    # Measured on one v5e chip (BERT-base, seq 512, bf16): flash wins at
    # per-chip batch >= 16 and never loses, so it is the TPU default.
    attention_impl: str = "auto"   # auto | xla | flash (pallas) | ring
    remat: bool = False            # rematerialize encoder layers (FLOPs for HBM)
    # what remat saves at layer boundaries: "full" recomputes everything,
    # "dots" saves matmul outputs and recomputes only elementwise ops,
    # "dots_no_batch" also drops batch-dim matmul results (models/layers.py)
    remat_policy: str = "full"     # full | dots | dots_no_batch
    # Fused LM-head + CE (ops/pallas_vocab_ce.py): the [B,S,V] logits
    # never materialize in HBM. causal-lm only; opt-in (numerics match
    # the unfused path to fp32 roundoff, tests/test_vocab_ce.py).
    fused_vocab_ce: bool = False

    # --- QA doc-stride (HF run_qa semantics): contexts longer than the
    #     room left by the question become overlapping windows instead of
    #     being truncated — at training (independent rows) AND at the
    #     --eval_qa_samples EM/F1 eval (best-scoring span across each
    #     example's windows). 0 = truncate (reference-era behavior);
    #     HF's conventional value is 128. ---
    qa_doc_stride: int = 0

    # --- LoRA parameter-efficient fine-tuning (models/lora.py;
    #     beyond-parity — the reference trains every weight,
    #     train.py:117). rank 0 = off. With rank r > 0 the base model is
    #     frozen (no Adam state: the fp32 m/v mirrors that dominate HBM
    #     at the 16G ceiling vanish) and only A·B factors on the
    #     targeted kernels train; export merges them back into the
    #     checkpoint and also writes an adapter.safetensors sidecar. ---
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: str = "attention"   # attention | mlp | all | custom regex
    # fresh task heads stay fully trainable (PEFT modules_to_save
    # analogue) — freezing a random-init classifier would make the task
    # unlearnable; "" freezes them too (adapter-only, e.g. causal-lm
    # where the LM head is the tied embedding). The default lives HERE
    # (models/lora.py re-exports it as HEAD_REGEX_DEFAULT — config must
    # stay import-light, so the dependency points this way)
    lora_train_heads: str = LORA_HEAD_REGEX_DEFAULT

    # --- length bucketing (tf.data bucket_by_sequence_length capability;
    #     the reference pads everything to 512, train.py:80-83). 0 = off;
    #     N > 0 buckets token widths at multiples of N (e.g. 128 →
    #     128/256/384/512), one XLA compilation per bucket actually seen.
    #     Must stay a multiple of any ``sp`` sharding of the seq axis. ---
    bucket_multiple: int = 0

    # --- control flags (reference train.py:44-45, typed correctly here) ---
    do_train: bool = True
    do_eval: bool = True
    # per-epoch eval during fit (Keras validation_data shape): eval
    # metrics land in the training history as eval_loss/eval_accuracy
    eval_each_epoch: bool = False
    # HF load_best_model_at_end: snapshot the best epoch's params (by
    # --best_metric) to host and export THOSE instead of the final ones;
    # implies per-epoch eval
    keep_best: bool = False
    best_metric: str = "eval_loss"    # eval_loss | eval_accuracy
    # stop when --best_metric hasn't improved for N consecutive epochs
    # (0 = off; implies per-epoch eval). Composes with --keep_best: the
    # exported model is the best epoch's, not the stopping epoch's.
    early_stopping_patience: int = 0

    # --- checkpoint / resume (reference commented these out, train.py:136-137) ---
    checkpoint_dir: Optional[str] = None
    checkpoint_every_steps: int = 0      # 0: per-epoch only
    resume: bool = True                   # resume from latest checkpoint if present
    keep_checkpoints: int = 3
    async_checkpointing: bool = True      # overlap checkpoint writes with steps

    # --- replica-divergence detection (SURVEY.md §5.2): verify at every
    #     checkpoint boundary that parameter replicas across the data/seq
    #     mesh axes still agree (the consistency Horovod's broadcast only
    #     establishes at start, reference train.py:127-134). ---
    check_divergence: bool = True
    divergence_tol: float = 1e-6          # relative; replicas should be bit-equal

    # --- output contract (reference train.py:48-50) ---
    # accelerator-count env parity (reference SM_NUM_GPUS, train.py:50):
    # informational — the real device count comes from jax.devices();
    # scripts/train.py warns when the platform-declared count disagrees.
    num_chips: Optional[int] = field(
        default_factory=lambda: (
            int(v) if (v := _env("TPU_NUM_CHIPS", "SM_NUM_GPUS",
                                 default="")).isdigit() else None)
    )
    output_data_dir: str = field(
        default_factory=lambda: _env("TPU_OUTPUT_DATA_DIR", "SM_OUTPUT_DATA_DIR", default="/tmp/output")
    )
    model_dir: str = field(
        default_factory=lambda: _env("TPU_MODEL_DIR", "SM_MODEL_DIR", default="/tmp/model")
    )

    # --- compilation ---
    # persistent XLA compilation cache: recompiles across runs (and across
    # bucket widths, restarts, resumes) become disk hits. Empty string
    # disables. ~3x faster warm startup measured on TPU.
    # HSTD_COMPILE_CACHE_DIR is the documented env knob (the launcher
    # sets it per job root so every host of a job shares one cache);
    # TPU_COMPILATION_CACHE_DIR kept as the legacy spelling.
    compilation_cache_dir: str = field(
        default_factory=lambda: _env(
            "HSTD_COMPILE_CACHE_DIR", "TPU_COMPILATION_CACHE_DIR",
            default=os.path.join(os.path.expanduser("~"), ".cache", "hstd-xla"))
    )

    # --- observability ---
    log_every_steps: int = 10
    profile: bool = False          # capture a jax.profiler trace of a few steps
    profile_dir: str = "/tmp/profile"
    log_all_hosts: bool = False

    def __post_init__(self):
        if self.task not in ("seq-cls", "token-cls", "qa", "seq2seq",
                             "causal-lm", "mlm", "rtd"):
            raise ValueError(f"unknown task {self.task!r}")
        if self.dtype not in ("bfloat16", "float32", "float16"):
            raise ValueError(f"unknown dtype {self.dtype!r}")
        if self.rng_impl == "threefry":   # JAX's registry name for it
            self.rng_impl = "threefry2x32"
        if self.rng_impl not in ("rbg", "threefry2x32"):
            raise ValueError(f"unknown rng_impl {self.rng_impl!r}")
        if self.epochs < 0 or self.train_batch_size <= 0 or self.eval_batch_size <= 0:
            raise ValueError("epochs must be >= 0 and batch sizes positive")
        if self.gradient_accumulation_steps < 1:
            raise ValueError("gradient_accumulation_steps must be >= 1")
        if self.learning_rate <= 0:
            raise ValueError("learning_rate must be positive")
        if self.optimizer not in ("adamw", "adam", "adafactor", "lamb"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.optimizer_state_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown optimizer_state_dtype {self.optimizer_state_dtype!r}")
        if (self.optimizer_state_dtype == "bfloat16"
                and self.optimizer not in ("adam", "adamw")):
            raise ValueError(
                "optimizer_state_dtype='bfloat16' supports adam/adamw only "
                "(adafactor is already sublinear-memory; lamb's trust "
                "ratio is untested with quantized moments)")
        if self.packed_sequences and self.task != "causal-lm":
            raise ValueError(
                "packed_sequences is a causal-lm pretraining layout "
                "(EOS-joined documents chunked into full rows); other "
                "tasks need per-example boundaries")
        if self.packed_sequences and self.streaming:
            raise ValueError(
                "packed_sequences does not combine with --streaming "
                "(the streaming tier tokenizes rows independently; "
                "packing needs the whole token stream) — pick one")
        if self.segment_packing and self.task not in ("causal-lm", "mlm"):
            raise ValueError(
                "segment_packing packs token-level examples behind "
                "segment ids (causal-lm / mlm); per-example-label tasks "
                f"cannot pack (got task={self.task!r})")
        if self.segment_packing and self.packed_sequences:
            raise ValueError(
                "segment_packing and packed_sequences are alternative "
                "packing layouts (per-example boundaries vs EOS-joined "
                "stream) — pick one")
        if self.segment_packing and self.streaming:
            raise ValueError(
                "segment_packing does not combine with --streaming "
                "(packing re-groups rows at build time; the streaming "
                "tier tokenizes per batch) — pick one")
        if self.segment_packing and self.bucket_multiple:
            raise ValueError(
                "segment_packing already eliminates pad waste; "
                "bucket_multiple would re-fragment packed rows — pick one")
        if self.streaming and self.span_corruption:
            raise ValueError(
                "--streaming does not implement span corruption (the "
                "streaming seq2seq tier encodes supervised source/target "
                "rows); drop --streaming for span-corruption pretraining")
        if self.optimizer == "adafactor" and self.weight_decay > 0:
            raise ValueError(
                "weight_decay with adafactor is not supported: optax "
                "applies it per-update after lr scaling (~1/lr stronger "
                "than AdamW's decoupled decay); use adamw or lamb")
        if self.optimizer == "adam" and self.weight_decay > 0:
            raise ValueError(
                "optimizer='adam' is plain coupled Adam (reference "
                "parity) and ignores weight_decay; use adamw")
        if self.lr_schedule not in ("linear", "cosine"):
            raise ValueError(f"unknown lr_schedule {self.lr_schedule!r}")
        if self.lr_schedule == "cosine" and self.warmup_ratio <= 0:
            raise ValueError(
                "lr_schedule='cosine' needs warmup_ratio > 0 (schedules "
                "only engage with a warmup+decay window; without it the "
                "lr is constant and the flag would be silently ignored)")
        for ax in ("fsdp", "ep", "pp", "tp", "sp", "dcn_dp"):
            if getattr(self, ax) <= 0:
                raise ValueError(f"mesh axis {ax} must be positive")
        if self.pipeline_microbatches < 0:
            raise ValueError("pipeline_microbatches must be >= 0")
        if self.pp > 1 and self.num_experts:
            raise ValueError("pp > 1 cannot combine with num_experts (MoE)")
        if self.num_experts < 0 or self.expert_top_k < 1 or self.moe_every < 1:
            raise ValueError("num_experts >= 0, expert_top_k >= 1, moe_every >= 1")
        if self.ep > 1 and self.num_experts == 0:
            raise ValueError("ep > 1 requires num_experts > 0 (MoE model)")
        if not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError("label_smoothing must be in [0, 1)")
        if self.label_smoothing > 0 and self.task != "seq2seq":
            raise ValueError(
                "label_smoothing is implemented for task='seq2seq' (the "
                "T5/BART fine-tuning convention); other tasks would "
                "silently ignore it")
        if self.best_metric not in ("eval_loss", "eval_accuracy"):
            raise ValueError(
                f"unknown best_metric {self.best_metric!r} "
                "(eval_loss | eval_accuracy)")
        if self.early_stopping_patience < 0:
            raise ValueError("early_stopping_patience must be >= 0")
        if self.early_stopping_patience > 0:
            self.eval_each_epoch = True
        if self.keep_best and not self.do_eval:
            raise ValueError("keep_best needs do_eval=true (it selects "
                             "by eval metric)")
        if self.early_stopping_patience > 0 and not self.do_eval:
            raise ValueError("early_stopping_patience needs do_eval=true "
                             "(it watches an eval metric)")
        if self.keep_best:
            self.eval_each_epoch = True
        if self.remat_policy not in ("full", "dots", "dots_no_batch"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        if self.qa_doc_stride < 0:
            raise ValueError("qa_doc_stride must be >= 0 (0 disables)")
        if 0 < self.max_seq_length - 3 <= self.qa_doc_stride:
            # stride is the OVERLAP between windows: when it meets or
            # exceeds the best-case window room (empty question), every
            # example degenerates to 1-token steps — up to one feature
            # per context token, a quiet memory/time blowup
            raise ValueError(
                f"qa_doc_stride={self.qa_doc_stride} >= "
                f"max_seq_length-3={self.max_seq_length - 3} (the maximum "
                "context window room): windows would step 1 token at a "
                "time; lower --qa_doc_stride or raise --max_seq_length")
        if self.lora_rank < 0:
            raise ValueError("lora_rank must be >= 0 (0 disables LoRA)")
        if self.lora_rank > 0 and self.lora_alpha <= 0:
            raise ValueError("lora_alpha must be positive")
        # lora_rank > 0 composes with gradient accumulation: the trainer
        # wraps multi_transform AROUND the MultiSteps'd optimizer, so the
        # accumulator only ever sees the trainable (adapter+head) subtree
        # — MaskedNode placeholders carry no leaves and accumulate
        # nothing (parity-tested in tests/test_lora.py)
        if self.num_experts and self.num_experts % self.ep:
            raise ValueError(
                f"num_experts={self.num_experts} must divide over ep={self.ep}")
        if self.num_experts and self.expert_top_k > self.num_experts:
            raise ValueError("expert_top_k cannot exceed num_experts")
        if self.bucket_multiple < 0:
            raise ValueError("bucket_multiple must be >= 0")
        if self.bucket_multiple and self.sp > 1 and self.bucket_multiple % self.sp:
            raise ValueError("bucket_multiple must divide evenly over sp shards")
        if self.attention_impl not in ("auto", "xla", "flash", "ring"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        if self.attention_impl == "flash" and self.sp > 1:
            raise ValueError(
                "attention_impl='flash' cannot run over a sequence-sharded "
                "axis (sp>1); use 'ring' or 'auto'")

    def resolve_attention_impl(self, platform: str) -> str:
        """Single source of truth for the attention kernel choice.

        A seq mesh axis (sp > 1) forces ring attention — xla/flash compute
        per-shard attention over a sharded seq axis, which is wrong
        (flash+sp is already rejected at construction). ``auto`` then
        picks flash (Pallas) on real TPU and xla elsewhere (on CPU the
        Pallas kernels would run in slow interpret mode)."""
        if self.sp > 1:
            return "ring"
        if self.attention_impl != "auto":
            return self.attention_impl
        return "flash" if platform == "tpu" else "xla"

    def bucket_sizes(self, max_len: int) -> Optional[list[int]]:
        """The length-bucket width schedule ``bucket_multiple`` implies:
        multiples of it up to ``max_len`` (validated sp-divisible in
        ``__post_init__``). None when bucketing is off. Shared by
        ``scripts/train.py`` and ``bench.py --buckets``."""
        if not self.bucket_multiple:
            return None
        return list(range(self.bucket_multiple, max_len + 1,
                          self.bucket_multiple))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "TrainConfig":
        known = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def _add_field_arg(parser: argparse.ArgumentParser, f: dataclasses.Field) -> None:
    name = "--" + f.name
    if f.type in ("bool", bool):
        parser.add_argument(name, type=_parse_bool, default=None)
    elif f.type in ("int", int):
        parser.add_argument(name, type=int, default=None)
    elif f.type in ("float", float):
        parser.add_argument(name, type=float, default=None)
    elif f.type in ("Optional[int]",):
        parser.add_argument(name, type=int, default=None)
    else:
        parser.add_argument(name, type=str, default=None)


def parse_args(argv: Optional[list[str]] = None) -> TrainConfig:
    """Build a TrainConfig from CLI args layered over env/defaults.

    Hyperparameters arrive as ``--key value`` strings exactly as the
    SageMaker platform serializes them (reference ``launch.py:51`` →
    ``scripts/train.py:39-46``); every value is validated and coerced to
    its typed field. Unknown args are ignored.
    """
    parser = argparse.ArgumentParser(allow_abbrev=False)
    for f in fields(TrainConfig):
        _add_field_arg(parser, f)
    ns, _unknown = parser.parse_known_args(argv)
    overrides = {k: v for k, v in vars(ns).items() if v is not None}
    base = TrainConfig()
    merged = {**base.to_dict(), **overrides}
    return TrainConfig.from_dict(merged)
