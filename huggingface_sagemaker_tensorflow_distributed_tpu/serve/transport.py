"""Cross-engine KV block-set transport (ISSUE 18).

ONE primitive — :func:`migrate_request` — moves a live request between
two :class:`~.engine.ServeEngine` instances with zero re-prefill: the
request's block set leaves the source pools through
:func:`~.paged_kv.extract_blocks` (full LOGICAL blocks on host — value
pools, int8 scale planes, and draft pools ride together, and a
tensor-parallel source's shards are already assembled by the
``device_get``), the scheduler-side :class:`~.scheduler.Request`
transplants with its generated tail, sampled seed, SLO riders and
timeline stamps intact, and the destination re-admits it through the
swapped-request path (:meth:`~.scheduler.Scheduler._reserve_swapped`):
allocate exactly the set's blocks from the DESTINATION pool, scatter
before any dispatch reads the table, resume in DECODE. Because the
host payload is engine-geometry-free, inserting into a destination
with a different tensor-parallel degree re-shards the KV heads axis
as a side effect of the destination's own committed pool shardings —
no new pool math, which is the point of the BlockSet layout.

Token exactness falls out of two existing invariants: the generated
tokens never leave ``req.output`` (the decode feed is ``output[-1]``
on whichever engine runs it), and token ``n``'s sampling key is
``fold_in(PRNGKey(seed), n)`` — a pure function of (seed, n), so a
moved sampled stream is bitwise the unmoved one.

The Router cashes this in three ways (ISSUE 18): disaggregated
prefill/decode fleets (``Router(roles=...)``), live migration of
RESIDENT requests off a draining replica, and length-aware placement
over heterogeneous (mixed-TP) fleets.
"""

from __future__ import annotations

import time
from typing import Optional

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    extract_blocks,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    DECODE,
    WAITING,
)

__all__ = ["TransportError", "migrate_request", "can_accept",
           "pool_signature"]


class TransportError(RuntimeError):
    """A request cannot move: not resident on the source, incompatible
    pool geometry, or a destination too small to ever hold it."""


def pool_signature(engine) -> tuple:
    """The engine's LOGICAL pool geometry: ``(block_size, per-pool
    (block shape, dtype), draft ditto)``. Shapes are global (a sharded
    pool reports its unsharded shape), so two engines at different
    tensor-parallel degrees over the same model compare EQUAL — the
    transportability contract: equal signatures mean a :class:`~.
    paged_kv.BlockSet` extracted from one scatters bitwise into the
    other."""
    def sig(pools):
        return tuple((tuple(int(d) for d in p.shape[1:]), str(p.dtype))
                     for p in pools)
    draft = sig(engine._d_pools) if engine.speculative else None
    return (int(engine.blocks.block_size), sig(engine._pools), draft)


def can_accept(dst, req, live: bool = False) -> bool:
    """True when ``dst`` could EVER hold ``req``: the submit-time
    worst-case block need (padded prompt, full generation + decode
    lookahead, preemption-folded re-prefill) against the destination's
    own chunk grid, model length, and whole pool — the same formula
    :meth:`~.scheduler.Scheduler.submit` validates, re-run because a
    heterogeneous destination's geometry may be smaller than the
    engine the request was originally admitted to.

    With ``live=True`` (ISSUE 20, admission-aware placement) the
    probe additionally requires the worst case to fit the pool's
    CURRENT headroom (:meth:`~.paged_kv.BlockManager.can_allocate` —
    free + evictable cached blocks), so a router can skip a
    destination that is full RIGHT NOW for a peer with room. Purely a
    read: no refcount, LRU, or allocation state moves either way."""
    s = dst.sched
    total = len(req.prompt) + req.max_new_tokens
    if total + s.decode_lookahead - 1 > s.max_model_len:
        return False
    worst = max(s.padded_prompt_len(req),
                total + s.decode_lookahead - 1,
                -(-(total - 1) // s.prefill_chunk) * s.prefill_chunk)
    need = s.blocks.blocks_for(worst)
    if need > s.blocks.num_blocks - 1:
        return False
    return s.blocks.can_allocate(need) if live else True


def migrate_request(src, dst, rid: int, prefetched=None,
                    extract_s: float = 0.0) -> Optional[dict]:
    """Move resident request ``rid`` from ``src`` to ``dst``.

    A DECODE resident moves HOT: its context's block set is extracted
    to host, the source's blocks are released, and the request enters
    the destination's queue at the FRONT (it already held residency —
    a migration must not re-queue it behind unadmitted work) carrying
    the set as its ``swap_set``; the destination's next admission
    allocates from its own pool, scatters, and resumes decode on the
    committed tail. A mid-PREFILL resident (nothing generated yet)
    moves COLD — no payload, the destination re-runs its prefill —
    which keeps drains latency-bounded without shipping half-written
    block spans.

    The source's in-flight pipeline is landed first (the preemption
    rule: migration acts on COMMITTED state only); committing may
    finish the request, in which case there is nothing to move and
    ``None`` is returned. Otherwise returns ``{"rid", "bytes",
    "context_len", "cold"}``. Raises :class:`TransportError` when the
    request is not resident on ``src``, the engines' pool geometries
    differ, or ``dst`` could never hold the request.

    ``prefetched`` (ISSUE 20, the PR 18 drain follow-up) is a
    :class:`~.paged_kv.BlockSet` the caller already extracted for
    this request as part of a batched cohort pull
    (:func:`~.paged_kv.extract_block_sets` — one ``device_get`` per
    victim cohort instead of one per request), with ``extract_s`` its
    amortized share of the cohort's extraction seconds. It is used
    only when it still matches the slot's committed context (the
    caller must have landed the source pipeline before prefetching);
    otherwise the per-request extraction runs as before — semantics,
    migration count, and tokens are identical either way.
    """
    if src is dst:
        raise TransportError(
            f"request {rid}: source and destination are the same engine")
    if pool_signature(src) != pool_signature(dst):
        raise TransportError(
            f"request {rid}: engine pool geometries differ "
            f"({pool_signature(src)} vs {pool_signature(dst)})")
    if rid in src.finished:
        return None
    slot = next((s for s in src.sched.slots
                 if s.request is not None and s.request.rid == rid), None)
    if slot is None:
        raise TransportError(
            f"request {rid} is not resident on the source engine")
    req = slot.request
    if not can_accept(dst, req):
        raise TransportError(
            f"request {rid} can never fit the destination engine "
            f"(max_model_len {dst.sched.max_model_len}, pool "
            f"{dst.blocks.num_blocks - 1} blocks)")
    # land any in-flight dispatch before touching the slot (the same
    # committed-state rule preemption follows) — the commit may FINISH
    # the request, which makes the migration a no-op
    with src._mesh_ctx():
        if src._pending is not None:
            src._flush("migrate")
        if src._pending_spec is not None:
            pending, src._pending_spec = src._pending_spec, None
            src._commit_spec(pending)
    if rid in src.finished:
        return None
    # the destination's re-admission closes this as the request's
    # migration-hold interval (the timeline's "preempted" phase — a
    # migrated request is off-accelerator either way). The same stamp
    # opens the transport-hop clock the destination's restore apply
    # closes (ISSUE 19).
    req.preempt_t = time.perf_counter()
    req.migrate_out_t = req.preempt_t
    cold = req.state != DECODE
    if cold:
        nbytes, ctx = 0, 0
        req.migrate_extract_s = 0.0
    else:
        n = src.blocks.blocks_for(slot.context_len)
        if prefetched is not None and prefetched.n_blocks == n:
            req.swap_set = prefetched
            req.migrate_extract_s = float(extract_s)
        else:
            t0 = time.perf_counter()
            with src._mesh_ctx():
                req.swap_set = extract_blocks(
                    src._pools, slot.table[:n],
                    d_pools=src._d_pools if src.speculative else None)
            req.migrate_extract_s = time.perf_counter() - t0
        req.swap_context = slot.context_len
        nbytes, ctx = req.swap_set.nbytes, slot.context_len
    src.blocks.release(slot.table)
    slot.clear()
    src._keys.pop(rid, None)
    req.state = WAITING
    req.hop += 1
    src.migrations_out += 1
    dst.adopt_resident(req, from_replica=src.replica)
    if cold:
        # a cold move lands no destination-side restore, so the
        # migrate event is emitted here; a HOT move's event comes from
        # the destination's restore apply, which knows restore_s
        kw = {}
        if src.replica is not None:
            kw["from_replica"] = src.replica
        if dst.replica is not None:
            kw["to_replica"] = dst.replica
        if req.trace_id:
            kw["trace_id"] = req.trace_id
            kw["hop"] = req.hop
        obs.serve("migrate", request=rid, migration_bytes=0,
                  restore_s=0.0, **kw)
    return {"rid": rid, "bytes": nbytes, "context_len": ctx,
            "cold": cold}
