"""``serve``: continuous-batching inference engine (ISSUE 3 + the
ISSUE 5 decode fast path: width-bucketed KV gather, batched prefill,
per-slot seeded sampling).

- :mod:`~.paged_kv` — block-pool KV allocation + gather read-waste
  accounting (host-side policy).
- :mod:`~.scheduler` — iteration-level admission/preemption over fixed
  decode slots, per-iteration max-context + tokens-per-dispatch
  prefill budget.
- :mod:`~.engine` — the jitted prefill/decode step functions (compiled
  per gather bucket) and the driving loop (``scripts/serve.py`` is the
  CLI; ``bench.py --serve`` the measurement).
- :mod:`~.router` — N engine replicas behind one facade (ISSUE 14):
  round-robin / least-loaded / prefix-affinity / length-aware
  placement, replica drain/restart with requeue-to-siblings and live
  resident migration, disaggregated prefill/decode roles (ISSUE 18).
- :mod:`~.transport` — cross-engine KV block-set migration (ISSUE 18):
  one primitive moves a live request between engines with zero
  re-prefill, token-exactly.
- :mod:`~.policy` — goodput-aware admission control (ISSUE 20):
  pluggable scheduler ordering (fifo | slo), per-tenant token-bucket
  rate limits, structured rejections. Host-side by contract
  (graftlint R7).
"""

from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (  # noqa: F401
    BlockManager,
    PoolExhausted,
    prefix_chain_keys,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.policy import (  # noqa: F401
    POLICIES,
    RateLimited,
    SloPolicy,
    TokenBucket,
    parse_aging_s,
    parse_policy,
    parse_rate_limit,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (  # noqa: F401
    Request,
    Scheduler,
)


def __getattr__(name):
    # ServeEngine pulls in jax; keep `import ...serve` cheap for
    # host-only consumers (scheduler/block-manager tests)
    if name in ("ServeEngine", "EngineStats", "CachePlan",
                "build_cache_plan", "parse_gather_buckets",
                "parse_prefix_cache", "parse_tp"):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve import (
            engine,
        )
        return getattr(engine, name)
    if name in ("Router", "parse_replicas", "parse_placement",
                "parse_roles"):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve import (
            router,
        )
        return getattr(router, name)
    if name in ("TransportError", "migrate_request", "can_accept",
                "pool_signature"):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve import (
            transport,
        )
        return getattr(transport, name)
    raise AttributeError(name)
