"""``serve``: continuous-batching inference engine (ISSUE 3).

- :mod:`~.paged_kv` — block-pool KV allocation (host-side policy).
- :mod:`~.scheduler` — iteration-level admission/preemption over fixed
  decode slots.
- :mod:`~.engine` — the jitted prefill/decode step functions and the
  driving loop (``scripts/serve.py`` is the CLI; ``bench.py --serve``
  the measurement).
"""

from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (  # noqa: F401
    BlockManager,
    PoolExhausted,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (  # noqa: F401
    Request,
    Scheduler,
)


def __getattr__(name):
    # ServeEngine pulls in jax; keep `import ...serve` cheap for
    # host-only consumers (scheduler/block-manager tests)
    if name in ("ServeEngine", "EngineStats", "CachePlan",
                "build_cache_plan"):
        from huggingface_sagemaker_tensorflow_distributed_tpu.serve import (
            engine,
        )
        return getattr(engine, name)
    raise AttributeError(name)
