"""The serving engine: continuous batching over a paged KV cache with
prefill/decode disaggregation, a width-bucketed decode fast path, and
optional speculative decoding.

Architecture (ISSUE 3 tentpole + ISSUE 5 fast path + ISSUE 6
speculation; vLLM + Orca + Sarathi + Leviathan lineage):

- **Paged KV** — one preallocated pool per KV leaf of the model's flax
  ``"cache"`` collection, ``[num_blocks, block_size, heads, head_dim]``.
  Persistent memory scales with blocks actually held (= tokens
  resident), not ``slots × max_model_len``. The jitted steps rebuild
  the model's cache pytree from the pools via
  ``ops.attention.gather_paged_kv`` (block-table gather), run the
  UNMODIFIED model decode path (same ``write_kv_cache`` protocol
  ``models/generate.py`` drives), then scatter the newly-written K/V
  back into the pools. No model code changes: paging is an addressing
  layer around the existing cache contract.
- **Width-bucketed gather** — the decode step is compiled at a small
  ladder of context-width buckets (``HSTD_SERVE_GATHER_BUCKETS`` /
  ``gather_buckets``; default quarter-width + full width) and each
  iteration runs the smallest bucket covering the scheduler's
  per-iteration max resident context
  (``Scheduler.max_decode_context``). When most contexts are short the
  step's KV read traffic (and the attention mask/logits width behind
  it) shrinks from ``max_model_len`` to the bucket — the read-waste
  elimination of PagedAttention's motivating analysis. Growth is
  immediate (correctness), shrinking has hysteresis so bucket churn is
  bounded; every switch is telemetered (``bucket_switch`` serve event
  + ``serve/gather_bucket`` series), and each bucket compiles exactly
  once (the bench asserts steady-state decode compiles ≤ #buckets).
- **Iteration-level scheduling** — a fixed set of ``num_slots`` decode
  slots (static shapes, so after one warmup compile of each step
  function NOTHING retraces); requests admit/evict between decode
  steps (``serve/scheduler.py``).
- **Batched chunked prefill** — prompt ingestion packs up to
  ``prefill_batch`` prefilling slots' chunks into ONE fixed-shape
  dispatch (one row per slot; each row attends only the KV its own
  block table gathers, so cross-request isolation is structural — the
  property token-packing buys with ``make_segment_mask``, bought here
  by the paged addressing itself, and test-gated either way). The
  scheduler's adaptive budget is denominated in tokens-per-dispatch
  (Sarathi-style): a full decode batch admits one chunk's tokens per
  iteration (bounding the decode stall a long prompt can inject), and
  every idle decode slot buys one more chunk, packed into as few
  dispatches as possible — which is what cuts TTFT under bursty
  arrivals.
- **Copy-on-write prefix caching** (``prefix_cache``) — full
  block-aligned prompt-prefix chunks are indexed by a rolling hash
  chain (:class:`~.paged_kv.BlockManager`), so requests sharing a
  templated system prompt map their prefix onto SHARED refcounted KV
  blocks: prefill for the cached span is skipped entirely (a
  block-table write), admission charges only private blocks, and
  zero-ref cached blocks persist in an LRU until pool pressure evicts
  them. Writes into still-shared blocks privatize first via a
  device-side block copy (COW) — output stays token-exact vs cold
  start.
- **Fused paged-attention kernel** (``kernel='pallas'``) — the decode
  step's gather→dense-attend HBM round trip collapses into ONE fused
  read: the model's paged decode branch scatters each slot's new K/V
  straight into the pools and attends via the Pallas kernel
  (``ops/pallas_paged_attention.py``), which walks the block tables
  inside the attention read — no ``[S, H, width, D]`` intermediate.
  Rides the same bucket ladder (one compile per bucket); interpret
  mode off-TPU, so CPU runs are correct but slow (tests), and the
  default stays ``kernel='xla'`` (the gather reference path).
- **int8 KV pools** (``kv_cache_dtype='int8'``) — pools store K/V as
  symmetric per-(position, head) int8 with fp32 scales riding parallel
  scale pools (written by the model's own ``kv_quantize`` protocol at
  scatter time, dequantized on read — in-tile under the kernel), which
  halves KV bytes per decode step end to end. Output is token-exact vs
  ``generate_causal`` on the SAME int8-cache config (quantization is
  deterministic, so recompute preemption and prefix sharing reproduce
  bitwise-identical pools); ``kv_pool_bytes`` sizes the pool by a
  memory budget, so int8 admits ~2x the requests of fp on equal bytes.
- **Speculative decoding** (``speculate_k``/``draft``) — per iteration
  a draft model (its own paged pools over the SAME block tables)
  proposes ``k`` tokens per running slot, then ONE width-(k+1) target
  verify — structurally just a wider bucketed decode, so it composes
  with the gather ladder — scores every window; the accepted prefix +
  bonus token commit, and rejected tokens roll back by an O(1)
  ``context_lens`` rewind (stale K/V hides behind the context-derived
  masks). Acceptance-rate × (k+1) decode tokens land per step with the
  output distribution unchanged (greedy: token-exact; sampled:
  Leviathan rejection acceptance).

- **Dispatch-ahead loop** (``overlap``, ISSUE 12) — the decode loop
  pipelines one iteration deep: dispatch N feeds N−1's un-fetched
  DEVICE tokens, ``device_get`` is deferred exactly one iteration,
  and the whole host side of the loop (commit, stamps, admission,
  bucket pick, block math, prefill staging) runs concurrently with
  the in-flight device step — the Orca/vLLM-style answer to host
  latency on the critical path. Token-value-dependent decisions are
  re-derived one step late (budget finishes from counts, EOS by
  discarding the wasted in-flight token) or drain the pipeline
  (preemption/KV pressure; ``overlap_flushes``); emitted tokens are
  identical to the serial loop's, which ``overlap='off'`` restores
  byte-for-byte. A LONE stream (decode occupancy 1, empty queue)
  auto-flushes to the serial schedule — there is no concurrent host
  work to hide, so the deferred fetch would only delay every token's
  delivery by one iteration (ISSUE 13 follow-up).
- **Tensor parallelism** (``mesh`` / ``HSTD_SERVE_TP``, ISSUE 13) —
  one engine serves a model bigger than a chip: params place
  Megatron-style over a ``tensor``-axis mesh
  (``parallel/sharding.py::param_shardings``) and every KV pool
  shards its HEADS axis (``kv_pool_sharding``; ``num_kv_heads % tp``
  rejected loudly, GQA included), so each device holds ``1/tp`` of
  every pool while block tables/context lens/token feeds stay
  replicated — the scheduler, BlockManager, prefix cache and overlap
  pipeline are untouched and output is token-identical to the
  single-device engine. The KV byte budget re-denominates PER DEVICE
  (``BlockManager.token_bytes`` = shard bytes/token), so the same
  per-chip ``kv_pool_bytes`` admits ~tp× the concurrent requests —
  the measurable capacity win even on CPU meshes.

Decoding is greedy by default and token-for-token identical to
per-request ``generate_causal`` — the exactness gate
``tests/test_serve.py`` pins, including with bucketing enabled and
under preemption. Per-request ``temperature``/``top_k``/``top_p``
sampling rides the same dispatches via per-slot PRNG keys (the
filtering semantics of ``models/generate.py``'s ``_filter_top_p`` et
al., vectorized per row): the n-th token's key is
``fold_in(PRNGKey(seed), n)``, a pure function of (request seed, token
index), so sampled streams are bitwise-reproducible under a fixed seed
even across recompute preemption — the seeded-determinism gate.

Telemetry: ``serve`` events (``obs/schema.py``) for request lifecycle
(submit/admit/first_token/finish/preempt, submit carrying ``sampled``)
plus ``bucket_switch`` events, spans around every prefill and decode
dispatch, and pool-utilization/read-waste metrics. With ``timeline``
on (``HSTD_SERVE_TIMELINE``, default on — ISSUE 10) the engine
additionally stamps each request's phase transitions host-side and
emits a compact ``request_timeline`` event at finish/preempt-requeue
(queue / prefill / decode / preempted / overhead decomposition that
sums to e2e, plus a coalesced per-dispatch segment list: per-chunk
prefill incl. cached-prefix skip, per-iteration decode runs keyed by
gather bucket, speculative window acceptance, COW copies, admission
-block attribution) and a per-iteration ``iteration_ledger`` event
(phase mix, bucket, slots, tokens, pool pressure) — the inputs
``obsctl timeline|slo|tail`` reconstruct. All stamps are host-side
``perf_counter`` reads: the accounting mints zero compiled variants,
and ``timeline='off'`` is byte-identical to the pre-tracing stream.
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, NamedTuple, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.models.generate import (
    _speculative_accept,
    sample_per_slot,
    self_draft,
    speculative_accept_greedy,
    warp_logits_per_slot,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    gather_paged_kv,
    scatter_paged_kv,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    BlockManager,
    extract_blocks,
    insert_blocks,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    DECODE,
    Request,
    Scheduler,
)

ENV_GATHER_BUCKETS = "HSTD_SERVE_GATHER_BUCKETS"
ENV_SPECULATE_K = "HSTD_SERVE_SPECULATE_K"
ENV_DRAFT_LAYERS = "HSTD_SERVE_DRAFT_LAYERS"
ENV_PREFIX_CACHE = "HSTD_SERVE_PREFIX_CACHE"
ENV_KERNEL = "HSTD_SERVE_KERNEL"
ENV_KV_DTYPE = "HSTD_SERVE_KV_DTYPE"
ENV_TIMELINE = "HSTD_SERVE_TIMELINE"
ENV_OVERLAP = "HSTD_SERVE_OVERLAP"
ENV_TP = "HSTD_SERVE_TP"
ENV_SWAP = "HSTD_SERVE_SWAP"
ENV_SWAP_BYTES = "HSTD_SERVE_SWAP_BYTES"


def parse_tp(spec) -> int:
    """The tensor-parallel degree knob (ISSUE 13): a positive int, the
    number of devices one engine shards its params + KV pools over.
    None reads ``HSTD_SERVE_TP`` (default 1 = the single-device
    engine). Rejects non-integers and non-positive values here; the
    divisibility contracts (device count, kv heads) are enforced where
    the mesh and pool shardings are built — with the offending figure
    named."""
    if spec is None:
        spec = os.environ.get(ENV_TP, "1") or "1"
    try:
        tp = int(str(spec).strip() or "1")
    except ValueError:
        raise ValueError(f"unparseable {ENV_TP} value {spec!r}: "
                         "expected a positive integer")
    if tp < 1:
        raise ValueError(f"{ENV_TP} must be >= 1, got {tp}")
    return tp


def parse_kernel(spec: Union[str, None]) -> str:
    """The decode-kernel knob: ``xla`` (gather + dense attention — the
    reference path, CPU-native) or ``pallas`` (the fused paged-decode
    kernel, ``ops/pallas_paged_attention.py`` — interpret-mode off
    TPU). None reads ``HSTD_SERVE_KERNEL``, default ``xla``."""
    if spec is None:
        spec = os.environ.get(ENV_KERNEL, "xla")
    s = str(spec).strip().lower() or "xla"
    if s not in ("xla", "pallas"):
        raise ValueError(f"unparseable {ENV_KERNEL} value {spec!r}: "
                         "expected xla | pallas")
    return s


def parse_kv_dtype(spec: Union[str, None], model_default: str) -> str:
    """The pool-storage knob: ``fp`` or ``int8`` (int8 halves KV bytes
    per decode step; scales ride parallel fp32 pools). None reads
    ``HSTD_SERVE_KV_DTYPE``, falling back to the model config's own
    ``kv_cache_dtype``."""
    if spec is None:
        spec = os.environ.get(ENV_KV_DTYPE) or None
    if spec is None:
        return model_default
    s = str(spec).strip().lower()
    if s not in ("fp", "int8"):
        raise ValueError(f"unparseable {ENV_KV_DTYPE} value {spec!r}: "
                         "expected fp | int8")
    return s


def _parse_on_off(spec: Union[str, bool, None], env_var: str,
                  default: str = "on") -> bool:
    """Shared on/off knob parser: None reads ``env_var`` (falling back
    to ``default``); accepts bool or the CLI/env spellings
    on/off/1/0/true/false."""
    if spec is None:
        spec = os.environ.get(env_var, default)
    if isinstance(spec, bool):
        return spec
    s = str(spec).strip().lower()
    if s in ("on", "1", "true", "yes", ""):
        return True
    if s in ("off", "0", "false", "no"):
        return False
    raise ValueError(f"unparseable {env_var} value {spec!r}: "
                     "expected on/off")


def parse_prefix_cache(spec: Union[str, bool, None]) -> bool:
    """The ``prefix_cache`` knob: None reads ``HSTD_SERVE_PREFIX_CACHE``
    (default ON — templated traffic is the common case)."""
    return _parse_on_off(spec, ENV_PREFIX_CACHE)


def parse_timeline(spec: Union[str, bool, None]) -> bool:
    """The ``timeline`` knob (ISSUE 10): per-request lifecycle tracing
    — phase stamps, ``request_timeline`` events at finish/preempt, and
    the per-iteration ``iteration_ledger`` event. None reads
    ``HSTD_SERVE_TIMELINE`` (default ON — the stamps are host-side
    ``perf_counter`` reads, so the serving hot path mints zero new
    compiled variants either way); ``off`` makes the engine's telemetry
    byte-identical to the pre-tracing stream."""
    return _parse_on_off(spec, ENV_TIMELINE)


def parse_overlap(spec: Union[str, bool, None]) -> bool:
    """The ``overlap`` knob (ISSUE 12): dispatch-ahead decode — host
    scheduling runs concurrently with the in-flight device iteration,
    ``jax.device_get`` deferred by exactly one iteration. None reads
    ``HSTD_SERVE_OVERLAP`` (default ON — emitted tokens are identical
    either way); ``off`` restores the strictly serial
    schedule→dispatch→fetch→commit loop byte-for-byte, telemetry
    included."""
    return _parse_on_off(spec, ENV_OVERLAP)


def parse_swap(spec: Union[str, None]) -> str:
    """The KV spill-tier policy knob (ISSUE 17). ``off`` (the default)
    disables the host tier entirely — telemetry byte-identical to the
    pre-swap engine. ``never`` activates the tier for prefix DEMOTION
    only (preemption stays vLLM-recompute). ``always`` swaps every
    preemption victim to host (budget permitting); ``auto`` picks swap
    vs recompute per victim from the bytes-moved vs tokens-recomputed
    estimate. None reads ``HSTD_SERVE_SWAP``."""
    if spec is None:
        spec = os.environ.get(ENV_SWAP, "off")
    s = str(spec).strip().lower() or "off"
    if s not in ("auto", "always", "never", "off"):
        raise ValueError(f"unparseable {ENV_SWAP} value {spec!r}: "
                         "expected auto | always | never | off")
    return s


def parse_swap_bytes(spec: Union[str, int, None]) -> Optional[int]:
    """The host-tier byte budget (ISSUE 17): a non-negative int capping
    demoted payloads + swap reservations together, or None for
    unbounded. None reads ``HSTD_SERVE_SWAP_BYTES`` (empty/``0`` =
    unbounded — "no budget" is the safe default on a host whose RAM
    dwarfs the KV pool)."""
    if spec is None:
        spec = os.environ.get(ENV_SWAP_BYTES) or None
    if spec is None:
        return None
    try:
        n = int(str(spec).strip() or "0")
    except ValueError:
        raise ValueError(f"unparseable {ENV_SWAP_BYTES} value {spec!r}: "
                         "expected a byte count (0/empty = unbounded)")
    if n < 0:
        raise ValueError(f"{ENV_SWAP_BYTES} must be >= 0, got {n}")
    return n or None


def parse_gather_buckets(spec: Union[str, Sequence[int], None],
                         max_model_len: int, block_size: int) -> list[int]:
    """The decode gather-width ladder from a knob value.

    ``spec`` is the comma-separated ``HSTD_SERVE_GATHER_BUCKETS`` form
    (``"512,2048"``), a sequence of ints, or None/``"auto"`` for the
    default ladder (quarter width + full width). ``"full"``/``"off"``
    disables bucketing (full-width gather only). Widths are rounded UP
    to a block multiple and clipped to ``max_model_len``, which is
    itself always present (the fallback bucket every admissible context
    fits). Returns the sorted ascending ladder."""
    if spec is None or (isinstance(spec, str)
                        and spec.strip().lower() in ("", "auto")):
        widths = [max_model_len // 4]
    elif isinstance(spec, str):
        s = spec.strip().lower()
        if s in ("full", "off", "0"):
            widths = []
        else:
            try:
                widths = [int(x) for x in spec.split(",") if x.strip()]
            except ValueError:
                raise ValueError(
                    f"unparseable {ENV_GATHER_BUCKETS} value {spec!r}: "
                    "expected comma-separated widths, 'auto', or 'full'")
    else:
        widths = [int(x) for x in spec]
    out = set()
    for w in widths:
        if w <= 0:
            continue
        out.add(min(max_model_len, -(-w // block_size) * block_size))
    out.add(max_model_len)
    return sorted(out)


class CachePlan(NamedTuple):
    """Static (hashable — it rides jit static_argnames) description of
    the model's flax cache pytree: the treedef plus, per flattened leaf,
    what it is — ``("kv", pool_index)`` for cached_key/cached_value
    (and, under ``kv_cache_dtype='int8'``, the ``cached_*_scale``
    fp32 scale planes, which ride parallel scale POOLS through the
    same gather/scatter/COW machinery), ``("index",)`` for the per-row
    write indices, ``("scalar",)`` for model-level counters (unused
    under explicit position_ids). ``paths`` holds each leaf's key path
    so the PAGED cache (kernel mode) can be built as a nested dict with
    a ``block_tables`` sibling injected per attention scope — and the
    mutated pools re-extracted by NAME, immune to the flatten-order
    shift the extra leaf causes.

    ``kv_shardings`` (ISSUE 13) is one ``NamedSharding`` per KV POOL
    (pool-index order, empty for a single-device plan): each pool's
    heads axis over the mesh's ``tensor`` axis. It is how the in/out
    shardings reach the jitted step families — the engine places the
    pools with these at init (jit derives its in-shardings from the
    committed operands) and the steps re-pin their pool OUTPUTS to the
    same shardings, so the pools-chain can never drift off the mesh
    mid-serve. ``NamedSharding`` hashes by (mesh, spec), so a TP plan
    and a single-device plan over the same model are distinct static
    keys — each compiles its own executables, one per bucket, exactly
    like two engines over different models would."""

    treedef: Any
    kinds: tuple
    paths: tuple
    kv_shardings: tuple = ()


def _constrain_pools(pools, plan: CachePlan):
    """Re-pin mutated pools to the plan's shardings (no-op for a
    single-device plan): the out-sharding half of the TP contract —
    scatter/gather propagation already keeps the heads axis sharded,
    but pinning makes it a stated invariant rather than an inference."""
    if not plan.kv_shardings:
        return pools
    return [lax.with_sharding_constraint(p, s)
            for p, s in zip(pools, plan.kv_shardings)]


# (model, max_ctx, mesh) -> (plan, pool_shapes): the cache structure is
# a function of the model config + width (+ the serving mesh, which
# only adds shardings), so engine rebuilds (bench's measured pass,
# server restarts) skip the eval_shape re-trace
_PLAN_CACHE: dict = {}


def build_cache_plan(model, params, max_ctx: int,
                     mesh=None) -> tuple[CachePlan, list]:
    """(plan, pool_shapes): traverse the cache collection's SHAPE (via
    ``jax.eval_shape`` — nothing is allocated) for a batch-1 decode at
    width ``max_ctx`` and classify every leaf. ``pool_shapes`` is one
    ``(heads, head_dim, dtype)`` per KV leaf in flatten order.

    With ``mesh`` (a tensor-parallel serving mesh, ISSUE 13) the plan
    additionally carries one ``NamedSharding`` per pool — heads over
    the ``tensor`` axis — and REJECTS loudly any pool whose kv-head
    count does not divide the tensor degree (GQA included: the check is
    on each cache leaf's own head count, which for GQA models is
    ``num_kv_heads``)."""
    key = (model, max_ctx, mesh)
    cached = _PLAN_CACHE.get(key)
    if cached is not None:
        return cached

    def init_cache(p):
        _, variables = model.apply(
            {"params": p}, jnp.ones((1, max_ctx), jnp.int32), decode=True,
            deterministic=True, mutable=["cache"])
        return variables["cache"]

    shapes = jax.eval_shape(init_cache, params)
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    kinds, pool_shapes, paths = [], [], []
    for path, leaf in flat:
        names = tuple(p.key if hasattr(p, "key") else str(p)
                      for p in path)
        name = names[-1]
        if name in ("cached_key", "cached_value",
                    "cached_key_scale", "cached_value_scale"):
            b, h, s, d = leaf.shape
            if s != max_ctx:
                raise ValueError(
                    f"cache leaf {name} has kv width {s}, expected "
                    f"{max_ctx} — non-slot-indexed cache layouts "
                    "(e.g. T5 encoder-decoder) are not serveable here")
            kinds.append(("kv", len(pool_shapes)))
            pool_shapes.append((h, d, leaf.dtype))
        elif name == "cache_index":
            kinds.append(("index",))
        elif name == "position_index":
            kinds.append(("scalar",))
        else:
            raise ValueError(
                f"unsupported cache leaf {name!r}: the serve engine "
                "speaks the cached_key/cached_value (+ int8 scale) "
                "protocol only")
        paths.append(names)
    kv_shardings: tuple = ()
    if mesh is not None and mesh.shape.get("tensor", 1) > 1:
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
            kv_pool_sharding,
        )

        kv_shardings = tuple(kv_pool_sharding(mesh, h)
                             for h, _d, _dt in pool_shapes)
    result = (CachePlan(treedef, tuple(kinds), tuple(paths),
                        kv_shardings), pool_shapes)
    _PLAN_CACHE[key] = result
    return result


def _assemble_cache(plan: CachePlan, pools, block_tables, context_lens,
                    width: Optional[int] = None):
    """The model-facing cache pytree: contiguous per-slot KV gathered
    from the pools (restricted to the static ``width`` bucket when
    given), write indices set to each slot's context length."""
    leaves = []
    for kind in plan.kinds:
        if kind[0] == "kv":
            leaves.append(gather_paged_kv(pools[kind[1]], block_tables,
                                          width=width))
        elif kind[0] == "index":
            leaves.append(context_lens.astype(jnp.int32))
        else:
            leaves.append(jnp.zeros((), jnp.int32))
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _decode_step(model, params, pools, tokens, block_tables, context_lens,
                 active, temps, top_ks, top_ps, keys, folds,
                 plan: CachePlan, width: int, sampled: bool):
    """One decode iteration over ALL slots (static [S] shapes): feed
    each slot's last token against a ``width``-bucket gathered cache,
    write its K/V at ``context_len`` (scattered back to the pools;
    inactive slots write the reserved null block 0), return the next
    token per slot — greedy argmax, or the per-slot seeded sample for
    rows with ``temperature > 0`` when the (static) ``sampled`` mode is
    on. Callers guarantee ``context_len + 1 <= width`` for every active
    slot."""
    cache = _assemble_cache(plan, pools, block_tables, context_lens,
                            width=width)
    # kv-buffer validity includes the slot being written this step —
    # exactly generate_causal's decode-step mask, at bucket width
    valid = (jnp.arange(width)[None, :]
             <= context_lens[:, None]).astype(jnp.int32)
    logits, mut = model.apply(
        {"params": params, "cache": cache}, tokens[:, None], valid,
        position_ids=context_lens[:, None], decode=True,
        deterministic=True, mutable=["cache"])
    last = logits[:, -1, :].astype(jnp.float32)
    if sampled:
        next_tok = sample_per_slot(last, temps, top_ks, top_ps, keys, folds)
    else:
        next_tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    # scatter the step's writes back; inactive slots route to the null
    # block so the scatter itself needs no masking
    safe_tables = jnp.where(active[:, None], block_tables, 0)
    pos = jnp.where(active, context_lens, 0)
    mut_leaves = jax.tree_util.tree_leaves(mut["cache"])
    new_pools = list(pools)
    for leaf, kind in zip(mut_leaves, plan.kinds):
        if kind[0] != "kv":
            continue
        written = jnp.take_along_axis(
            leaf, pos[:, None, None, None], axis=2)[:, :, 0, :]  # [S, H, D]
        new_pools[kind[1]] = scatter_paged_kv(
            new_pools[kind[1]], safe_tables, pos, written)
    return next_tok, _constrain_pools(new_pools, plan)


def _paged_cache(plan: CachePlan, pools, block_tables, context_lens):
    """The model-facing PAGED cache pytree (kernel mode): every KV leaf
    is its whole block pool (no gather — the fused kernel walks the
    tables in-attention), write indices are the context lengths, and a
    ``block_tables`` leaf rides next to each attention scope's
    ``cache_index`` (the marker the model's paged decode branch keys
    on). Built as a nested dict from the plan's recorded paths — the
    treedef can't be reused because of the injected sibling."""
    root: dict = {}
    for path, kind in zip(plan.paths, plan.kinds):
        node = root
        for key in path[:-1]:
            node = node.setdefault(key, {})
        if kind[0] == "kv":
            node[path[-1]] = pools[kind[1]]
        elif kind[0] == "index":
            node[path[-1]] = context_lens.astype(jnp.int32)
            node["block_tables"] = block_tables
        else:
            node[path[-1]] = jnp.zeros((), jnp.int32)
    return root


def _paged_decode_step(model, params, pools, tokens, block_tables,
                       context_lens, active, temps, top_ks, top_ps, keys,
                       folds, plan: CachePlan, width: int, sampled: bool):
    """One FUSED decode iteration over all slots (kernel mode): the
    model's paged decode branch scatters each slot's new K/V straight
    into the pools and attends via the Pallas paged kernel — no dense
    [S, H, width, D] intermediate is ever materialized. ``width``
    restricts the block-table walk to the iteration's gather bucket
    (same ladder, same compile-per-bucket contract as the XLA path);
    inactive slots route writes to null block 0 at context 0."""
    bs = pools[0].shape[1]
    tables = block_tables[:, :width // bs]
    safe_tables = jnp.where(active[:, None], tables, 0)
    ctx = jnp.where(active, context_lens, 0)
    cache = _paged_cache(plan, pools, safe_tables, ctx)
    logits, mut = model.apply(
        {"params": params, "cache": cache}, tokens[:, None], None,
        position_ids=ctx[:, None], decode=True, deterministic=True,
        mutable=["cache"])
    last = logits[:, -1, :].astype(jnp.float32)
    if sampled:
        next_tok = sample_per_slot(last, temps, top_ks, top_ps, keys, folds)
    else:
        next_tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    # the model scattered into the pools in place (cache mutation);
    # re-extract them BY PATH — the block_tables sibling shifts the
    # flatten order, so positional zip against plan.kinds would skew
    flat, _ = jax.tree_util.tree_flatten_with_path(mut["cache"])
    by_path = {tuple(p.key if hasattr(p, "key") else str(p)
                     for p in path): leaf for path, leaf in flat}
    new_pools = list(pools)
    for path, kind in zip(plan.paths, plan.kinds):
        if kind[0] == "kv":
            new_pools[kind[1]] = by_path[path]
    return next_tok, new_pools


def _prefill_chunk(model, params, pools, chunks, block_tables, start, rel,
                   temps, top_ks, top_ps, keys, folds, plan: CachePlan,
                   sampled: bool):
    """One BATCHED prefill dispatch: up to G prefilling slots' chunks as
    G independent rows (static [G, C] shape; unused rows carry pad
    tokens against the null block table). Each row writes its chunk's
    K/V into its own blocks starting at ``start[g]`` and returns the
    token after prompt position ``rel[g]`` (chunk-relative index of the
    last REAL prompt token; meaningful on a final chunk only — other
    rows return a discarded value). Isolation between the packed
    requests is structural: row g's attention reads exactly the KV its
    own block table gathers, so no mask can leak another request's
    context into it."""
    G, C = chunks.shape
    bs = pools[0].shape[1]
    max_ctx = block_tables.shape[1] * bs
    cache = _assemble_cache(plan, pools, block_tables, start)
    # chunk slots are marked valid; the model's step mask
    # (key_slot <= cache_index + q_index) imposes causality within the
    # chunk, and pad-tail keys sit AFTER every real query so they are
    # never attended. Pad-tail writes land in block space the scheduler
    # trims back after the final chunk.
    valid = (jnp.arange(max_ctx)[None, :]
             < start[:, None] + C).astype(jnp.int32)
    pos_ids = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    logits, mut = model.apply(
        {"params": params, "cache": cache}, chunks, valid,
        position_ids=pos_ids, decode=True, deterministic=True,
        mutable=["cache"])
    sel = jnp.take_along_axis(
        logits.astype(jnp.float32),
        jnp.clip(rel, 0, C - 1)[:, None, None], axis=1)[:, 0]  # [G, V]
    if sampled:
        next_tok = sample_per_slot(sel, temps, top_ks, top_ps, keys, folds)
    else:
        next_tok = jnp.argmax(sel, axis=-1).astype(jnp.int32)   # [G]
    positions = (start[:, None]
                 + jnp.arange(C, dtype=jnp.int32)[None, :]).reshape(-1)
    tables_tok = jnp.repeat(block_tables, C, axis=0)       # [G*C, nb]
    mut_leaves = jax.tree_util.tree_leaves(mut["cache"])
    new_pools = list(pools)
    for leaf, kind in zip(mut_leaves, plan.kinds):
        if kind[0] != "kv":
            continue
        h, d = leaf.shape[1], leaf.shape[3]
        written = jax.vmap(
            lambda row, s: lax.dynamic_slice(row, (0, s, 0), (h, C, d))
        )(leaf, start)                                      # [G, H, C, D]
        written = written.transpose(0, 2, 1, 3).reshape(G * C, h, d)
        new_pools[kind[1]] = scatter_paged_kv(
            new_pools[kind[1]], tables_tok, positions, written)
    return next_tok, _constrain_pools(new_pools, plan)


@functools.lru_cache(maxsize=2)
def _decode_step_jit(donate: bool):
    """Process-wide jitted decode step (one per donation mode).
    ``model``/``plan``/``width``/``sampled`` are static — each gather
    bucket (and each sampling mode actually used) compiles exactly
    once; pools are donated on accelerator backends so the scatter
    updates them in place (CPU has no donation and would warn every
    call)."""
    return jax.jit(_decode_step, static_argnums=(0, 12, 13, 14),
                   donate_argnums=(2,) if donate else ())


@functools.lru_cache(maxsize=2)
def _prefill_chunk_jit(donate: bool):
    return jax.jit(_prefill_chunk, static_argnums=(0, 12, 13),
                   donate_argnums=(2,) if donate else ())


@functools.lru_cache(maxsize=2)
def _paged_decode_step_jit(donate: bool):
    """Process-wide jitted FUSED decode step (kernel mode) — same
    static/donation contract as :func:`_decode_step_jit`: one compile
    per (model, plan, bucket, sampled)."""
    return jax.jit(_paged_decode_step, static_argnums=(0, 12, 13, 14),
                   donate_argnums=(2,) if donate else ())


def _copy_block(pools, src, dst):
    """Copy-on-write device op: duplicate physical block ``src`` into
    ``dst`` across every pool of one model's KV address space. Scalar
    src/dst are traced, so ONE compile covers every COW a pool
    geometry ever performs (fixed shape — the compile-flatness gates
    stay honest on the cache-hit path). Under a tensor-parallel mesh
    the copy is shard-local by construction: the pools are sharded on
    their heads axis and the copy addresses only the (replicated)
    block axis, so each device duplicates its own head slice — output
    sharding propagates from the pool operand, no collective, and the
    one-compile contract holds per sharding like any other step."""
    return [p.at[dst].set(p[src]) for p in pools]


@functools.lru_cache(maxsize=2)
def _copy_block_jit(donate: bool):
    # graftlint: allow[R3] no static key by design: pools are traced arrays and src/dst are traced scalars, so ONE compile covers every COW a pool geometry performs
    return jax.jit(_copy_block, donate_argnums=(0,) if donate else ())


class _PendingDecode(NamedTuple):
    """One in-flight PLAIN decode dispatch (dispatch-ahead pipeline,
    ISSUE 12): the un-fetched device next-token array, the (slot,
    request) pairs that rode it (captured at dispatch — a rider's slot
    may be reassigned by the time a wasted token is discarded), the
    bucket it ran at, and the dispatch-enqueue cost/stamp. The fetch is
    deferred to the NEXT engine iteration: everything the host does in
    between runs concurrently with this dispatch's device compute."""

    nxt: Any
    riders: tuple
    bucket: int
    dispatch_s: float
    t_dispatch: float


class _PendingSpec(NamedTuple):
    """One in-flight SPECULATIVE window (dispatch-ahead, ISSUE 12).
    Unlike the plain pipeline, a window's commit must complete before
    the next window dispatches (the next window's input token and
    context advance are data-dependent on the acceptance counts), so
    the overlap window covers the NEXT iteration's admission, prefill
    dispatches, and telemetry — not the next decode dispatch."""

    drafts: Any
    n_acc: Any
    bonus: Any
    riders: tuple
    bucket: int
    dispatch_s: float
    t_dispatch: float


def _scatter_window(pools, plan: CachePlan, cache_leaves, block_tables,
                    context_lens, active, k: int):
    """Scatter a just-computed (k+1)-token window's K/V — written by a
    model apply into an assembled (contiguous, bucket-width) cache at
    slots ``context_lens .. context_lens + k`` per row — back into the
    paged pools. Inactive rows route to the reserved null block 0 so
    the write path needs no masking (the plain decode step's
    convention, widened to the window)."""
    S = context_lens.shape[0]
    safe_tables = jnp.where(active[:, None], block_tables, 0)
    safe_start = jnp.where(active, context_lens, 0)
    flat_pos = (safe_start[:, None]
                + jnp.arange(k + 1, dtype=jnp.int32)[None]).reshape(-1)
    tables_tok = jnp.repeat(safe_tables, k + 1, axis=0)   # [S*(k+1), nb]
    new_pools = list(pools)
    for leaf, kind in zip(cache_leaves, plan.kinds):
        if kind[0] != "kv":
            continue
        h, d = leaf.shape[1], leaf.shape[3]
        written = jax.vmap(
            lambda row, s: lax.dynamic_slice(row, (0, s, 0), (h, k + 1, d))
        )(leaf, safe_start)                               # [S, H, k+1, D]
        written = written.transpose(0, 2, 1, 3).reshape(S * (k + 1), h, d)
        new_pools[kind[1]] = scatter_paged_kv(
            new_pools[kind[1]], tables_tok, flat_pos, written)
    return _constrain_pools(new_pools, plan)


def _spec_decode_step(model, params, draft_model, draft_params, t_pools,
                      d_pools, tokens, block_tables, context_lens, active,
                      temps, top_ks, top_ps, keys, folds, t_plan: CachePlan,
                      d_plan: CachePlan, width: int, k: int, sampled: bool):
    """One SPECULATIVE decode iteration over all slots (static [S]
    shapes): the draft proposes ``k`` tokens per slot autoregressively
    against its own paged pools, then ONE width-(k+1) verify dispatch of
    the target scores every window position — structurally just a wider
    bucketed decode, so it rides the same ``width`` gather ladder. Per
    row the accepted prefix + bonus token come back for the host to
    commit; rejected draft tokens leave only stale K/V past the
    committed context, which the host rewinds in O(1) by NOT advancing
    ``context_lens`` over them (validity masks are context-derived, so
    stale slots are invisible and the next window overwrites them).

    ``tokens`` is each slot's newest COMMITTED token (its K/V lands at
    ``context_lens`` during the verify, exactly like the plain step);
    ``folds`` is the window's starting request-global token index — the
    per-row PRNG key for the whole window derives from (request seed,
    window start) alone, which is what keeps sampled speculative
    streams bitwise-reproducible across recompute preemption (windows
    re-start at the same committed index, so the same keys re-derive).
    Greedy rows accept by longest argmax-matching prefix
    (:func:`~..models.generate.speculative_accept_greedy` — token-exact
    vs ``generate_causal``); sampled rows use Leviathan rejection
    acceptance on the per-slot WARPED distributions, so the emitted
    marginal is the target's.

    Returns ``(drafts [S, k], n_acc [S], bonus [S], t_pools, d_pools)``.
    Callers guarantee ``context_lens + k + 1 <= width`` per active
    slot."""
    S = tokens.shape[0]
    pos_grid = jnp.arange(width)[None, :]
    win_pos = (context_lens[:, None]
               + jnp.arange(k + 1, dtype=jnp.int32)[None])   # [S, k+1]
    if sampled:
        # window key = f(request seed, window start): split into the
        # draft-proposal stream and the acceptance stream
        wkeys = jax.vmap(jax.random.fold_in)(keys, folds)
        pair = jax.vmap(lambda kk: jax.random.split(kk, 2))(wkeys)
        draft_keys, accept_keys = pair[:, 0], pair[:, 1]
    else:
        draft_keys = keys

    # -- draft: k+1 single-token steps over ONE pre-assembled bucket
    #    cache (the step writes stay inside the carried pytree — no
    #    per-step pool gather/scatter; the final carry holds the whole
    #    window's K/V, scattered back once below). Step k's output is
    #    discarded: it only exists so the final carry contains
    #    d_{k-1}'s K/V, which the NEXT window's draft needs resident
    #    when the full window is accepted.
    d_cache = _assemble_cache(d_plan, d_pools, block_tables, context_lens,
                              width=width)

    def dstep(carry, t):
        tok, cache = carry
        valid = (pos_grid <= (context_lens + t)[:, None]).astype(jnp.int32)
        lg, mut = draft_model.apply(
            {"params": draft_params, "cache": cache}, tok[:, None], valid,
            position_ids=(context_lens + t)[:, None], decode=True,
            deterministic=True, mutable=["cache"])
        lg = lg[:, -1, :].astype(jnp.float32)
        if sampled:
            nxt = sample_per_slot(lg, temps, top_ks, top_ps, draft_keys,
                                  jnp.full((S,), t, jnp.int32))
            qp = jax.nn.softmax(
                warp_logits_per_slot(lg, temps, top_ks, top_ps), axis=-1)
            return (nxt, mut["cache"]), (nxt, qp)
        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        return (nxt, mut["cache"]), nxt

    (_, d_final), ys = lax.scan(dstep, (tokens, d_cache),
                                jnp.arange(k + 1))
    if sampled:
        drafts = ys[0][:k].T                                 # [S, k]
        q_probs = jnp.swapaxes(ys[1], 0, 1)[:, :k]           # [S, k, V]
    else:
        drafts = ys[:k].T
    new_d_pools = _scatter_window(d_pools, d_plan,
                                  jax.tree_util.tree_leaves(d_final),
                                  block_tables, context_lens, active, k)

    # -- verify: ONE (k+1)-wide target pass scores the whole window and
    #    writes its K/V (accepted slots become resident; rejected ones
    #    are the stale tail the host's context rewind hides)
    verify_in = jnp.concatenate([tokens[:, None], drafts], axis=1)
    t_cache = _assemble_cache(t_plan, t_pools, block_tables, context_lens,
                              width=width)
    valid = (pos_grid <= (context_lens + k)[:, None]).astype(jnp.int32)
    logits, mut = model.apply(
        {"params": params, "cache": t_cache}, verify_in, valid,
        position_ids=win_pos, decode=True, deterministic=True,
        mutable=["cache"])
    lg = logits.astype(jnp.float32)                          # [S, k+1, V]
    t_pred = jnp.argmax(lg, axis=-1).astype(jnp.int32)
    n_acc, bonus = speculative_accept_greedy(t_pred, drafts)
    if sampled:
        p_probs = jax.nn.softmax(jax.vmap(
            lambda x: warp_logits_per_slot(x, temps, top_ks, top_ps),
            in_axes=1, out_axes=1)(lg), axis=-1)
        n_acc_s, nxt_s = jax.vmap(_speculative_accept)(
            p_probs, q_probs, drafts, accept_keys)
        on = temps > 0
        n_acc = jnp.where(on, n_acc_s, n_acc)
        bonus = jnp.where(on, nxt_s, bonus)
    new_t_pools = _scatter_window(t_pools, t_plan,
                                  jax.tree_util.tree_leaves(mut["cache"]),
                                  block_tables, context_lens, active, k)
    return drafts, n_acc, bonus, new_t_pools, new_d_pools


@functools.lru_cache(maxsize=2)
def _spec_step_jit(donate: bool):
    """Process-wide jitted speculative step (one per donation mode):
    ``model``/``draft_model``/plans/``width``/``k``/``sampled`` are
    static, so each gather bucket (per sampling mode actually used)
    compiles exactly once and a rebuilt engine over the same
    model/geometry reuses the executables."""
    return jax.jit(_spec_decode_step,
                   static_argnums=(0, 2, 15, 16, 17, 18, 19),
                   donate_argnums=(4, 5) if donate else ())


class EngineStats(NamedTuple):
    decode_steps: int
    prefill_chunks: int
    prefill_dispatches: int
    tokens_generated: int
    decode_tokens: int
    decode_time_s: float
    preemptions: int
    bucket_switches: int
    kv_peak_utilization: float
    kv_utilization: float
    gather_waste_peak: float
    gather_waste_mean: float
    draft_proposed: int = 0
    draft_accepted: int = 0
    acceptance_rate: Optional[float] = None
    spec_windows: int = 0
    verify_waste_peak: float = 0.0
    verify_waste_mean: float = 0.0
    # prefix caching (ISSUE 8)
    prefix_cache: bool = False
    prefix_cached_tokens: int = 0
    cache_hit_rate: Optional[float] = None
    blocks_shared_peak: int = 0
    blocks_saved_peak: int = 0
    cow_copies: int = 0
    prefix_evictions: int = 0
    shared_read_frac: float = 0.0
    peak_resident_requests: int = 0
    # paged-attention kernel + int8 pools (ISSUE 9)
    kernel: str = "xla"
    kv_dtype: str = "fp"
    kv_bytes_read: int = 0
    kv_token_bytes: int = 0
    # dispatch-ahead pipeline (ISSUE 12)
    overlap: bool = False
    overlap_flushes: int = 0
    # tensor-parallel serving (ISSUE 13): the mesh degree and the KV
    # pool's per-device footprint (num_blocks × per-device block
    # bytes — kv_token_bytes above is already per-device under TP)
    tp: int = 1
    kv_pool_bytes_per_device: int = 0
    # host-RAM KV spill tier (ISSUE 17): swap-mode preemption +
    # prefix demotion. All zero/"off" when the tier is disabled.
    swap_policy: str = "off"
    swap_outs: int = 0
    swap_ins: int = 0
    swap_bytes: int = 0
    restore_s: float = 0.0
    recompute_tokens_avoided: int = 0
    host_tier_hits: int = 0
    host_tier_hit_rate: Optional[float] = None
    # cross-engine KV transport (ISSUE 18): migration traffic through
    # this engine — all zero unless migrate_request touched it
    migrations_in: int = 0
    migrations_out: int = 0
    migration_bytes: int = 0


class ServeEngine:
    """Continuous-batching engine for the decoder-only families that
    follow the slot-indexed KV-cache protocol (GPT-2, dense Llama).

    ``num_blocks`` includes the reserved null block: allocatable KV is
    ``(num_blocks - 1) * block_size`` tokens, shared by every request —
    size it for the expected CONCURRENT context, not
    ``num_slots × max_model_len``.

    ``gather_buckets`` is the decode gather-width ladder (None reads
    ``HSTD_SERVE_GATHER_BUCKETS``, default quarter + full width; pass
    ``[max_model_len]`` or ``"full"`` to force full-width gather).
    ``prefill_batch`` caps how many prefilling slots' chunks one
    prefill dispatch packs (clamped to ``num_slots``).

    ``speculate_k > 0`` turns on SPECULATIVE decode (None reads
    ``HSTD_SERVE_SPECULATE_K``, default off): per iteration a draft
    model proposes ``k`` tokens per running slot and one width-(k+1)
    verify dispatch of the target scores them all — acceptance-rate ×
    (k+1) tokens land per decode step without changing the output
    (greedy stays token-exact vs ``generate_causal``; sampled rows keep
    the Leviathan rejection acceptance, so the emitted distribution is
    the target's). ``draft`` selects the proposer: a
    ``(draft_model, draft_params)`` tuple, an int = build a layer-skip
    self-draft from the target's own first N layers
    (``models.generate.self_draft`` — no second checkpoint), or None =
    ``HSTD_SERVE_DRAFT_LAYERS`` falling back to a quarter of the
    target's layers. Requests additionally reserve the verify window:
    ``prompt + max_new_tokens + speculate_k`` must fit
    ``max_model_len``.

    ``prefix_cache`` (None reads ``HSTD_SERVE_PREFIX_CACHE``, default
    on) turns on copy-on-write prefix caching: full block-aligned
    prompt-prefix chunks are indexed by a rolling hash chain, identical
    prefixes across requests map onto SHARED read-only KV blocks
    (refcounted, charged to the pool once), and prefill for a cache hit
    starts at the first uncached chunk — TTFT for templated traffic
    collapses toward the tail's prefill plus a block-table write, and
    effective KV capacity multiplies by the dedup factor. Blocks of
    finished requests stay cached (zero-ref LRU) until pool pressure
    evicts them, oldest first. Output is token-exact vs a cold start:
    cached KV is bitwise what this request's own prefill would have
    produced, and a scatter into a still-shared block (the chunk-grid
    overlap at admission) is privatized by a device-side block copy
    first (:func:`_copy_block`). ``prefix_cache='off'`` is
    byte-for-byte the refcount-free engine's behavior — same tokens,
    same compile count.

    ``kernel`` (None reads ``HSTD_SERVE_KERNEL``, default ``xla``)
    selects the decode-attention path: ``xla`` gathers a dense view
    then attends (reference, CPU-native), ``pallas`` runs the fused
    paged-decode kernel — gather folded into the attention read, int8
    dequant in-tile, sliding-window band tiles skipped. Speculative
    engines keep draft/verify on the assembled path either way (the
    kernel is single-token). ``kv_cache_dtype`` (None reads
    ``HSTD_SERVE_KV_DTYPE``, default = the model config's own value)
    selects pool storage; ``int8`` rebuilds the serving module around
    ``kv_cache_dtype='int8'`` (params untouched) and the exactness
    contract moves to ``generate_causal`` on that same config.
    ``kv_pool_bytes`` sizes ``num_blocks`` from a KV memory budget
    (``1 + budget // block_bytes``) instead of a block count.

    ``timeline`` (None reads ``HSTD_SERVE_TIMELINE``, default on)
    turns on per-request lifecycle tracing: ``request_timeline`` +
    ``iteration_ledger`` telemetry events from host-side phase stamps
    (zero new compiled variants; ``off`` restores the pre-tracing
    telemetry byte-for-byte).

    ``overlap`` (None reads ``HSTD_SERVE_OVERLAP``, default on) makes
    the decode loop DISPATCH-AHEAD (ISSUE 12): iteration N is
    dispatched before iteration N−1's tokens are fetched, and all the
    host work of the loop — committing N−1's tokens, phase stamps,
    admission, bucket pick, block math, prefill staging — runs
    concurrently with N's device compute; ``jax.device_get`` is
    deferred by exactly one iteration. The token feed for dispatch N
    is N−1's un-fetched DEVICE output (merged with host-known tokens
    for fresh-from-prefill slots by one warmed fixed-shape select —
    the decode step itself compiles zero new variants per bucket).
    Host decisions that depend on N−1's token values are re-derived
    one step late without changing emitted tokens: a budget finish is
    predicted from counts and excluded from dispatch N up front; an
    EOS finish is discovered at commit, and the wasted in-flight token
    is discarded (its stale K/V write is ordered before any
    reallocation of the released blocks by the pool-chain data
    dependency, so it can never clobber a later owner). Preemption /
    KV-pressure DRAINS the pipeline first (``overlap_flushes``
    latches every drain), so the recompute path always runs on
    committed state. A speculative engine commits each window before
    the next dispatch (acceptance counts are data-dependent) and
    overlaps the next iteration's admission/prefill/telemetry
    instead. ``overlap='off'`` restores the serial loop byte-for-byte
    in telemetry.

    ``mesh`` (ISSUE 13) makes the engine TENSOR-PARALLEL — one engine
    serving a model bigger than a chip. Pass a ``jax.sharding.Mesh``
    with a ``tensor`` axis, an int degree (a ``dp=1 × tp`` mesh over
    the first ``tp`` devices is built via
    ``parallel.mesh.tensor_parallel_mesh``), or None to read
    ``HSTD_SERVE_TP`` (default 1 = single-device). Params are placed
    with ``parallel.sharding.param_shardings`` (Megatron layout) and
    every per-layer KV pool — int8 scale pools included — shards its
    HEADS axis over ``tensor`` (``[num_blocks, block_size, H, D]``
    shards on H cleanly; ``num_kv_heads % tp == 0`` is required and
    rejected loudly otherwise, GQA included). Block tables, context
    lens and token feeds stay replicated, so the host-side scheduler,
    BlockManager, prefix cache, dispatch-ahead pipeline and timeline
    stamps are untouched — the TP engine emits token-identical output
    to the single-device engine. The KV byte budget re-denominates PER
    DEVICE: ``BlockManager.token_bytes`` becomes each shard's bytes
    per resident token (``1/tp`` of the model's), so
    ``kv_pool_bytes`` — a per-device figure — buys a TP=2 engine ~2x
    the blocks, and through the scheduler's block-denominated
    admission math, ~2x the concurrently-resident requests on the
    same per-chip memory. Compile expectations are unchanged: one
    step compile per bucket per engine (a TP plan is its own static
    key; sharding mints no extra variants within it).
    ``kernel='pallas'`` does not compose with ``mesh`` (the fused
    kernel would need a shard_map port) and is rejected loudly.

    ``swap`` (ISSUE 17, None reads ``HSTD_SERVE_SWAP``, default
    ``off``) turns on the host-RAM KV spill tier. Preemption victims
    are EXTRACTED to host (:func:`extract_blocks` — value pools and
    int8 scale pools atomically) instead of recomputed: on re-admit
    the blocks scatter back (:func:`insert_blocks`) and the request
    resumes DECODE with its output intact — no re-prefill, token
    emission bitwise what the uninterrupted run produces (the sampled
    fold indices are a pure function of output length, which swap
    never rewinds). ``auto`` picks swap vs recompute per victim by
    comparing bytes moved (2 × blocks × host block bytes) against the
    weight traffic re-prefill would stream (param bytes × prefill
    dispatches); ``always``/``never`` force the choice; ``never``
    still keeps the tier for PREFIX DEMOTION — zero-ref cached blocks
    write back to host before true eviction and revive on match, so
    the effective prefix cache is RAM-sized. ``swap_bytes`` (None
    reads ``HSTD_SERVE_SWAP_BYTES``) caps demoted payloads + swap
    reservations together; a victim that cannot reserve falls back to
    recompute. Extraction/insertion are per-block jitted
    gather/scatters over TRACED indices — zero new step variants, and
    both directions are precompiled at :meth:`warmup`. ``off`` keeps
    the engine (and its telemetry) byte-identical to the pre-tier
    build."""

    #: consecutive iterations a smaller bucket must suffice before the
    #: engine shrinks to it — bounds bucket churn when the max resident
    #: context oscillates around a bucket boundary
    SHRINK_PATIENCE = 4

    def __init__(self, model, params, *, num_slots: int = 8,
                 block_size: int = 16, num_blocks: int = 129,
                 prefill_chunk: int = 16,
                 max_model_len: Optional[int] = None,
                 gather_buckets: Union[str, Sequence[int], None] = None,
                 prefill_batch: int = 4,
                 speculate_k: Optional[int] = None,
                 draft=None,
                 prefix_cache: Union[str, bool, None] = None,
                 kernel: Union[str, None] = None,
                 kv_cache_dtype: Union[str, None] = None,
                 kv_pool_bytes: Optional[int] = None,
                 timeline: Union[str, bool, None] = None,
                 overlap: Union[str, bool, None] = None,
                 mesh=None,
                 swap: Union[str, None] = None,
                 swap_bytes: Union[str, int, None] = None,
                 policy: Union[str, None] = None,
                 aging_s: Union[str, float, None] = None):
        cfg = model.config
        if getattr(cfg, "num_experts", 0):
            raise ValueError(
                "ServeEngine does not support MoE models: expert "
                "capacity depends on the apply's sequence length, so "
                "chunked prefill could drop token->expert assignments "
                "the one-shot path never drops")
        if getattr(cfg, "pipeline_stages", 0):
            raise ValueError("ServeEngine needs the dense stack "
                             "(pipeline_stages=0)")
        self.kernel = parse_kernel(kernel)
        # tensor-parallel mesh resolution (ISSUE 13): an explicit Mesh,
        # an int degree, or the HSTD_SERVE_TP env default
        from jax.sharding import Mesh as _Mesh

        if isinstance(mesh, _Mesh):
            self.mesh = mesh
            self.tp = int(mesh.shape.get("tensor", 1))
            if self.tp < 2:
                # a mesh without a >1 tensor axis is the single-device
                # engine with extra steps — treat it as one
                self.mesh = None
                self.tp = 1
        else:
            self.tp = parse_tp(mesh)
            if self.tp > 1:
                from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
                    tensor_parallel_mesh,
                )

                self.mesh = tensor_parallel_mesh(self.tp)
            else:
                self.mesh = None
        if self.mesh is not None and self.kernel == "pallas":
            raise ValueError(
                "kernel='pallas' does not compose with a tensor-parallel "
                "mesh: the fused paged kernel reads whole pools and "
                "would need a shard_map port — serve TP with the xla "
                "gather path (the kernel is a per-chip bandwidth "
                "optimization; TP is a capacity one)")
        self.kv_cache_dtype = parse_kv_dtype(
            kv_cache_dtype, getattr(cfg, "kv_cache_dtype", "fp"))
        if self.kv_cache_dtype != getattr(cfg, "kv_cache_dtype", "fp"):
            # the knob overrides the model's own cache storage: rebuild
            # the serving module around the adjusted config (params are
            # untouched — KV quantization is activation-side)
            if not hasattr(cfg, "kv_cache_dtype"):
                raise ValueError(
                    f"kv_cache_dtype={self.kv_cache_dtype!r} requested "
                    f"but {type(model).__name__} has no int8 KV cache "
                    "protocol")
            import dataclasses
            cfg = dataclasses.replace(cfg,
                                      kv_cache_dtype=self.kv_cache_dtype)
            model = type(model)(cfg)
        if self.mesh is not None:
            # place the params once, Megatron layout: qkv/FFN-in
            # column-parallel, attn-out/FFN-out row-parallel — the
            # committed shardings are what drive every jitted step's
            # SPMD partitioning (jit derives in-shardings from them)
            from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
                param_shardings,
            )

            params = jax.device_put(params,
                                    param_shardings(params, self.mesh))
        self.model, self.params = model, params
        self.eos_token_id = int(cfg.eos_token_id)
        self.pad_token_id = min(int(cfg.pad_token_id), cfg.vocab_size - 1)
        if max_model_len is None:
            max_model_len = (cfg.max_position_embeddings
                             // block_size) * block_size
        self.max_model_len = int(max_model_len)
        max_pos = getattr(cfg, "max_position_embeddings", None)
        if max_pos is not None and self.max_model_len > max_pos:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the "
                f"model's max_position_embeddings {max_pos}")
        self.num_slots = int(num_slots)
        if speculate_k is None:
            speculate_k = int(os.environ.get(ENV_SPECULATE_K, "0") or 0)
        self.speculate_k = int(speculate_k)
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, "
                             f"got {self.speculate_k}")
        self.prefix_cache = parse_prefix_cache(prefix_cache)
        self.timeline = parse_timeline(timeline)
        self.overlap = parse_overlap(overlap)
        plan, pool_shapes = build_cache_plan(model, params,
                                             self.max_model_len,
                                             mesh=self.mesh)
        self._plan = plan
        # bytes one resident token costs across every pool (int8 KV +
        # its fp32 scale plane included) — the figure that sizes a
        # byte-budgeted pool and denominates kv_bytes_read telemetry.
        # Under a tensor-parallel mesh this re-denominates PER DEVICE
        # (each shard holds H/tp heads of every pool — exact, the plan
        # already validated divisibility): kv_pool_bytes is a per-chip
        # budget, so a TP=2 engine on the same per-chip figure holds
        # ~2x the blocks and admits ~2x the concurrent requests — the
        # capacity win sharding buys
        token_bytes = sum(h * d * np.dtype(dtype).itemsize
                          for h, d, dtype in pool_shapes) // self.tp
        if kv_pool_bytes is not None:
            # size the pool by a KV MEMORY budget instead of a block
            # count: int8 pools (~half the bytes/token) get ~2x the
            # blocks — and through the scheduler's block-denominated
            # admission math, ~2x the resident requests — for the same
            # budget. The budget covers the TARGET pools; a speculative
            # draft's pools ride on top (its layer share).
            block_bytes = block_size * max(token_bytes, 1)
            num_blocks = max(2, 1 + int(kv_pool_bytes) // block_bytes)
        self.blocks = BlockManager(num_blocks, block_size,
                                   token_bytes=token_bytes)
        self.sched = Scheduler(num_slots, self.blocks, prefill_chunk,
                               self.max_model_len,
                               decode_lookahead=self.speculate_k + 1,
                               prefix_cache=self.prefix_cache,
                               policy=policy, aging_s=aging_s)
        # admission policy (ISSUE 20): parsed once by the scheduler;
        # "fifo" keeps every event stream byte-identical to the
        # pre-policy engine (all policy riders gate on != "fifo")
        self.policy = self.sched.policy
        self.max_blocks_per_seq = self.max_model_len // block_size
        if gather_buckets is None:
            gather_buckets = os.environ.get(ENV_GATHER_BUCKETS)
        self.gather_buckets = parse_gather_buckets(
            gather_buckets, self.max_model_len, block_size)
        if self.speculate_k:
            if self.speculate_k + 1 > self.max_model_len:
                raise ValueError(
                    f"speculate_k {self.speculate_k} verify window does "
                    f"not fit max_model_len {self.max_model_len}")
            # buckets too narrow for even an empty-context window can
            # never be selected — drop them so warmup compiles only
            # dispatchable variants (full width always remains)
            self.gather_buckets = [b for b in self.gather_buckets
                                   if b >= self.speculate_k + 1]
        self.prefill_batch = max(1, min(int(prefill_batch), self.num_slots))

        # place every pool heads-sharded over the mesh: the committed
        # shardings ARE the jitted steps' pool in-shardings, and
        # _constrain_pools pins the outputs to the same, so the
        # pools-chain stays on the mesh end to end. Sharded pools are
        # materialized from HOST zeros — device_put splits a numpy
        # array into per-device shards directly, whereas a jnp.zeros
        # would first allocate the FULL pool on one device, which is
        # exactly the footprint a bigger-than-a-chip model cannot fit
        self._pools = self._init_pools(num_blocks, block_size,
                                       pool_shapes, plan)
        # speculative mode: the draft model's paged pools ride the SAME
        # block tables/allocator as the target's — one allocation
        # domain, two KV address spaces (per-block bytes grow by the
        # draft's layer share; the draft's context is the target's)
        self.draft_model = self.draft_params = None
        if self.speculate_k:
            if isinstance(draft, tuple):
                self.draft_model, self.draft_params = draft
            else:
                layers = draft
                if layers is None:
                    layers = int(os.environ.get(ENV_DRAFT_LAYERS, "0")
                                 or 0) or max(1, cfg.num_layers // 4)
                self.draft_model, self.draft_params = self_draft(
                    model, params, int(layers))
            if self.draft_model.config.vocab_size != cfg.vocab_size:
                raise ValueError(
                    "draft and target must share a vocabulary (got "
                    f"{self.draft_model.config.vocab_size} vs "
                    f"{cfg.vocab_size})")
            if self.mesh is not None:
                # the draft inherits the target's parallelism: its
                # params (a layer subset or a second checkpoint) place
                # by the same Megatron rules, its pools shard on the
                # same heads axis over the same mesh
                from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
                    param_shardings,
                )

                self.draft_params = jax.device_put(
                    self.draft_params,
                    param_shardings(self.draft_params, self.mesh))
            d_plan, d_pool_shapes = build_cache_plan(
                self.draft_model, self.draft_params, self.max_model_len,
                mesh=self.mesh)
            self._d_plan = d_plan
            self._d_pools = self._init_pools(num_blocks, block_size,
                                             d_pool_shapes, d_plan)
        # the jitted step functions are MODULE-level and keyed on
        # (model, plan, width, sampled) static args: a second engine
        # over the same model/geometry — the bench's measured pass, a
        # restarted server — reuses the compiled executables instead of
        # retracing
        donate = jax.default_backend() != "cpu"
        self._donate = donate
        # multi-replica serving (ISSUE 14): the router sets this to the
        # replica index when the engine is one of N; every per-request
        # lifecycle event + the SLO report then carry `replica`, which
        # is what `obsctl slo` groups tail attribution by. None (the
        # default, and the single-replica router's choice) adds NOTHING
        # to the telemetry stream — the byte-identity contract.
        self.replica: Optional[int] = None
        self._decode_fn = (_paged_decode_step_jit(donate)
                           if self.kernel == "pallas"
                           else _decode_step_jit(donate))
        self._prefill_fn = _prefill_chunk_jit(donate)
        self._spec_fn = _spec_step_jit(donate)
        self._copy_fn = _copy_block_jit(donate)
        self.finished: dict[int, Request] = {}
        self._keys: dict[int, np.ndarray] = {}   # rid -> base PRNG key
        self.decode_steps = 0
        self.prefill_chunks = 0
        self.prefill_dispatches = 0
        self.tokens_generated = 0
        self.decode_tokens = 0
        self.decode_time_s = 0.0
        self.iterations = 0
        self.peak_waiting = 0
        self.bucket_switches = 0
        self.draft_proposed = 0
        self.draft_accepted = 0
        self.kv_bytes_read = 0      # pool bytes decode dispatches read
        self.spec_windows = 0       # active (slot, iteration) pairs
        self.peak_resident = 0      # max concurrently-occupied slots
        # open-loop SLO accounting (ISSUE 16): attainment counters over
        # finished requests that carried targets, plus the per-group
        # split and the peak count of arrival-stamped requests seen
        # waiting at any ledger instant. The _has_* flags gate every new
        # report/ledger field so a closed-loop run's stream stays
        # byte-identical to the pre-open-loop engine's.
        self._slo_total = 0
        self._slo_met = 0
        self._group_slo: dict[str, list] = {}   # group -> [met, total]
        self._arrival_backlog_peak = 0
        self._has_arrivals = False
        self._has_slo = False
        # admission-policy accounting (ISSUE 20): deadline verdicts
        # over finished requests that carried one, and per-priority-
        # class SLO attainment. _has_priorities flips on the first
        # nonzero-priority submit; all riders stay absent otherwise.
        self._deadline_total = 0
        self._deadline_miss = 0
        self._priority_slo: dict[int, list] = {}  # class -> [met, total]
        self._has_priorities = False
        self._bucket = self.gather_buckets[0]
        self._shrink_streak = 0
        self._warmed_modes: set = set()
        # dispatch-ahead pipeline state (ISSUE 12): the one in-flight
        # decode dispatch (plain) or speculative window, and how many
        # times the pipeline was force-drained (preemption/KV pressure
        # must act on committed state)
        self._pending: Optional[_PendingDecode] = None
        self._pending_spec: Optional[_PendingSpec] = None
        self.overlap_flushes = 0
        # lifecycle tracing (ISSUE 10): per-iteration dispatch-time
        # accumulators the iteration_ledger event reads (reset each
        # step; populated only with `timeline` on)
        self._iter_prefill_s = 0.0
        self._iter_decode_s = 0.0
        self._iter_decode_slots = 0
        # host-RAM KV spill tier (ISSUE 17). `off` leaves every hook
        # uninstalled — scheduler, BlockManager and telemetry behave
        # byte-identically to the pre-tier engine. Otherwise the
        # scheduler's preemption path gets the swap hook and (with the
        # prefix cache on) the BlockManager gets the spill/demotion
        # hook, both closing over the live pools.
        self.swap = parse_swap(swap)
        self.swap_bytes = parse_swap_bytes(swap_bytes)
        self.swap_ins = 0
        self.swap_outs = 0
        self.swap_bytes_moved = 0
        self.restore_s = 0.0
        self.recompute_tokens_avoided = 0
        # cross-engine transport (ISSUE 18): counters stay zero —
        # and every rider stays absent — unless migrate_request runs,
        # the byte-identity contract for single-engine traffic.
        # _migrated_in maps an adopted resident's rid to its source
        # replica index until the restore applies, which is how
        # _apply_restores tells a migration arrival (migration
        # accounting, `migrate` event) from a swap-tier re-admission
        # (host-budget release, `swap_in` event).
        self.migrations_in = 0
        self.migrations_out = 0
        self.migration_bytes = 0
        self.migration_restore_s = 0.0
        self._migrated_in: dict = {}
        # fleet tracing (ISSUE 19): per-hop transport seconds observed
        # at this engine's restore applies (migrate-out stamp →
        # scatter-complete), the sample list behind the router's
        # transport_hop_s_p99 rider. _migrate_hold marks rids whose
        # NEXT admission closes a migration hold — the stamp tags that
        # preempted segment `via: "migrate"` so the stitcher can split
        # cross-engine admission wait out of same-engine preemption.
        self.transport_hop_s: list = []
        self._migrate_hold: set = set()
        # role-designated prefill replica (ISSUE 18): the Router flips
        # this on disaggregated fleets; _step then suppresses the
        # decode phase entirely and finished prefills park in DECODE
        # state until the router migrates them to a decode replica
        self.prefill_only = False
        if self.swap != "off":
            # host bytes one block costs across every pool, UNSHARDED
            # (device_get assembles the full logical block regardless
            # of tp), draft pools included — the figure behind both
            # the budget charge and the auto estimate's bytes-moved
            # side. The recompute side streams the params once per
            # prefill dispatch, so the crossover is
            #   2 * blocks * host_block_bytes
            #     vs param_bytes * ceil(context / prefill_chunk)
            self._host_block_bytes = block_size * sum(
                h * d * np.dtype(dtype).itemsize
                for h, d, dtype in pool_shapes)
            if self.speculate_k:
                self._host_block_bytes += block_size * sum(
                    h * d * np.dtype(dtype).itemsize
                    for h, d, dtype in d_pool_shapes)
            self._param_bytes = sum(
                x.size * x.dtype.itemsize
                for x in jax.tree_util.tree_leaves(self.params))
            self.sched.swap_hook = self._swap_out
            if self.prefix_cache:
                self.blocks.set_spill(self._spill_block,
                                      host_budget=self.swap_bytes)

    @staticmethod
    def _init_pools(num_blocks: int, block_size: int, pool_shapes,
                    plan: CachePlan) -> list:
        """Zeroed KV pools, placed per the plan. Sharded pools go
        through ``jax.device_put(host_zeros, sharding)`` so each
        device only ever materializes its own ``1/tp`` shard — a
        ``jnp.zeros`` would transiently allocate the WHOLE pool on the
        default device first, OOMing init in precisely the
        bigger-than-a-chip regime TP serves."""
        if not plan.kv_shardings:
            return [jnp.zeros((num_blocks, block_size, h, d), dtype)
                    for h, d, dtype in pool_shapes]
        return [jax.device_put(
                    np.zeros((num_blocks, block_size, h, d),
                             np.dtype(dtype)), s)
                for (h, d, dtype), s in zip(pool_shapes,
                                            plan.kv_shardings)]

    # -- public API ----------------------------------------------------------

    def _replica_kw(self) -> dict:
        """``{"replica": i}`` when this engine is replica i of a router
        fleet, ``{}`` otherwise — the single spot that keeps a
        router-less (or replicas=1) engine's telemetry byte-identical
        to the pre-router stream."""
        return {} if self.replica is None else {"replica": self.replica}

    def _trace_kw(self, req: Request) -> dict:
        """``{"trace_id": ..., "hop": ...}`` when the request carries a
        router-minted trace context (ISSUE 19), ``{}`` otherwise — the
        absent-when-default twin of :meth:`_replica_kw`: untraced runs
        emit byte-identical events to the pre-tracing stream."""
        if not req.trace_id:
            return {}
        return {"trace_id": req.trace_id, "hop": req.hop}

    def take_waiting(self) -> list[Request]:
        """Drain hook (ISSUE 14): remove and return every WAITING
        request (the scheduler's :meth:`~.scheduler.Scheduler.
        take_waiting`), dropping their engine-side sampled-key entries
        — the adopting replica re-derives them (:meth:`adopt`), and a
        stale entry here would leak per-request state past the
        request's departure. Resident requests finish on this engine."""
        moved = self.sched.take_waiting()
        for req in moved:
            self._keys.pop(req.rid, None)
        return moved

    def adopt(self, req: Request) -> None:
        """Requeue hook (ISSUE 14): enqueue an EXISTING request — a
        sibling replica's drain victim — keeping its identity, folded
        prompt, and submit stamp. The sampled PRNG key re-derives from
        the request's own seed (token n's key is ``fold_in(PRNGKey(
        seed), n)``, a pure function of (seed, n)), so a moved sampled
        stream is bitwise what it would have been anywhere else —
        placement can never change tokens."""
        self.sched.adopt(req)
        if req.sampled:
            self._keys[req.rid] = np.asarray(jax.random.PRNGKey(req.seed),
                                             np.uint32)

    def adopt_resident(self, req: Request,
                       from_replica: Optional[int] = None) -> None:
        """Migration hook (ISSUE 18): enqueue a sibling engine's LIVE
        resident at the queue front (:meth:`~.scheduler.Scheduler.
        adopt_resident`). A hot migrant carries its extracted block
        set as ``swap_set`` — registering its rid here routes the
        eventual restore through migration accounting instead of the
        swap tier's; a cold (mid-prefill) migrant just re-prefills.
        The sampled key re-derives exactly as :meth:`adopt` — token
        ``n``'s key is a pure function of (seed, n), so migration can
        never change tokens."""
        self.sched.adopt_resident(req)
        if req.swap_set is not None:
            self._migrated_in[req.rid] = from_replica
        else:
            self.migrations_in += 1
        if req.trace_id:
            self._migrate_hold.add(req.rid)
        if req.sampled:
            self._keys[req.rid] = np.asarray(jax.random.PRNGKey(req.seed),
                                             np.uint32)

    def load_gauges(self) -> dict:
        """Live host-side load gauges (ISSUE 14): the placement-policy
        inputs — waiting depth, occupied slots, and KV pool pressure —
        read straight off the scheduler/BlockManager so a router never
        parses its own telemetry stream to route. These are the same
        figures the per-iteration ``serve/waiting_depth`` /
        ``serve/running_slots`` series and the ledger's
        ``kv_used_frac`` carry."""
        return {
            "waiting_depth": len(self.sched.waiting),
            "running": sum(1 for s in self.sched.slots if not s.free),
            "kv_used_frac": self.blocks.utilization(),
        }

    def has_work(self) -> bool:
        """True while anything is queued, resident, or in flight in
        the dispatch-ahead pipeline — the loop condition :meth:`run`
        (and a router driving several engines) spins on."""
        return (self.sched.has_work() or self._pending is not None
                or self._pending_spec is not None)

    def submit(self, prompt, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, seed: int = 0,
               group: str = "", arrival_s: Optional[float] = None,
               slo=None, trace_id: str = "",
               deadline_s: Optional[float] = None,
               priority: int = 0) -> Request:
        """Queue one request. ``temperature == 0`` (default) is greedy;
        ``temperature > 0`` samples with the given truncation knobs,
        seeded per request — same knob semantics as
        ``models.generate.generate_causal``. ``group`` is an opaque
        tag (tenant, route) the request's ``request_timeline`` event
        carries so SLO attribution can aggregate per group.

        Open-loop contract (ISSUE 16): ``arrival_s`` is the request's
        arrival stamp in this process's ``perf_counter`` domain —
        distinct from the submit stamp taken here, so queue wait
        decomposes into pre-submit backlog (load-generator hold time)
        plus in-engine queue. ``slo`` is any object with ``ttft_s`` /
        ``tpot_s`` attributes (``serve.loadgen.SloSpec``; duck-typed
        to keep this module import-free of the load generator) naming
        per-axis deadline seconds; the finish event then carries the
        verdicts and :meth:`slo_summary` the attainment. Both are
        absent-when-default: a closed-loop submit adds nothing to the
        telemetry stream.

        Admission-policy contract (ISSUE 20): ``deadline_s`` is an
        end-to-end deadline measured from the request's origin
        (``arrival_s`` when threaded, else the submit stamp) and
        ``priority`` the admission class, smaller = more urgent.
        Under ``policy="slo"`` both order WHO admits WHEN — never
        WHAT; under fifo they still drive the finish-side
        ``deadline_miss`` verdict. Absent-when-default like every
        other rider: no deadline and priority 0 add nothing."""
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=int(seed),
                      group=str(group),
                      arrival_s=(None if arrival_s is None
                                 else float(arrival_s)),
                      slo_ttft_s=(None if slo is None or slo.ttft_s is None
                                  else float(slo.ttft_s)),
                      slo_tpot_s=(None if slo is None or slo.tpot_s is None
                                  else float(slo.tpot_s)),
                      trace_id=str(trace_id),
                      deadline_s=(None if deadline_s is None
                                  else float(deadline_s)),
                      priority=int(priority))
        req.submit_t = time.perf_counter()
        self.sched.submit(req)
        if req.sampled:
            self._keys[req.rid] = np.asarray(jax.random.PRNGKey(req.seed),
                                             np.uint32)
        extra = {}
        if req.arrival_s is not None:
            self._has_arrivals = True
            extra["arrival_s"] = round(req.arrival_s, 6)
        if req.has_slo:
            self._has_slo = True
            if req.slo_ttft_s is not None:
                extra["slo_ttft_s"] = req.slo_ttft_s
            if req.slo_tpot_s is not None:
                extra["slo_tpot_s"] = req.slo_tpot_s
        if req.deadline_s is not None:
            extra["deadline_s"] = req.deadline_s
        if req.priority:
            self._has_priorities = True
            extra["priority"] = req.priority
        obs.serve("submit", request=req.rid,
                  prompt_len=len(req.prompt),
                  max_new_tokens=req.max_new_tokens,
                  sampled=req.sampled, **self._replica_kw(),
                  **self._trace_kw(req), **extra)
        return req

    def output_ids(self, req: Request) -> np.ndarray:
        """Generated ids (preemption-folded tokens included)."""
        folded = req.prompt[req.orig_prompt_len:]
        return np.concatenate(
            [folded, np.asarray(req.output, np.int32)]).astype(np.int32)

    @property
    def speculative(self) -> bool:
        return self.speculate_k > 0

    def warmup(self, sampled: bool = False) -> None:
        """Compile the prefill step and EVERY bucket's decode (or
        speculative draft/verify) step on null work so the serving loop
        itself never traces: the compile-tracker event count stays flat
        across steady state (the bench asserts decode compiles ≤
        #buckets). With ``sampled=True`` the per-slot-sampling variants
        of every step are ALSO precompiled — without it they compile
        lazily on the first sampled batch (one mid-serve stall per
        bucket), which latency-sensitive sampled traffic should not
        pay. Idempotent per mode; ``warmup(sampled=True)`` after a
        plain warmup compiles only the sampled variants."""
        modes = [False] + ([True] if sampled else [])
        modes = [m for m in modes if m not in self._warmed_modes]
        if not modes:
            return
        with self._mesh_ctx(), obs.span("serve/warmup"):
            C = self.sched.prefill_chunk
            nb = self.max_blocks_per_seq
            S = self.num_slots
            sf = np.zeros((S,), np.float32)
            si = np.zeros((S,), np.int32)
            for mode in modes:
                # both prefill dispatch shapes: the lone-request [1, C]
                # variant and the batched [prefill_batch, C] one (the
                # draft's prefill rides the target's greedy variant
                # only — drafts never sample at prefill)
                for G in sorted({1, self.prefill_batch}):
                    zf = np.zeros((G,), np.float32)
                    zi = np.zeros((G,), np.int32)
                    tok, self._pools = self._prefill_fn(
                        self.model, self.params, self._pools,
                        np.zeros((G, C), np.int32),
                        np.zeros((G, nb), np.int32),
                        zi, np.full((G,), -1, np.int32), zf, zi, zf,
                        np.zeros((G, 2), np.uint32), zi, self._plan,
                        mode)
                    if self.speculative and not mode:
                        tok, self._d_pools = self._prefill_fn(
                            self.draft_model, self.draft_params,
                            self._d_pools,
                            np.zeros((G, C), np.int32),
                            np.zeros((G, nb), np.int32),
                            zi, np.full((G,), -1, np.int32), zf, zi, zf,
                            np.zeros((G, 2), np.uint32), zi,
                            self._d_plan, False)
                for bucket in self.gather_buckets:
                    if self.speculative:
                        (_, _, tok, self._pools,
                         self._d_pools) = self._spec_fn(
                            self.model, self.params, self.draft_model,
                            self.draft_params, self._pools,
                            self._d_pools, si,
                            np.zeros((S, nb), np.int32), si,
                            np.zeros((S,), bool), sf, si, sf,
                            np.zeros((S, 2), np.uint32), si, self._plan,
                            self._d_plan, bucket, self.speculate_k,
                            mode)
                    else:
                        tok, self._pools = self._decode_fn(
                            self.model, self.params, self._pools, si,
                            np.zeros((S, nb), np.int32), si,
                            np.zeros((S,), bool), sf, si, sf,
                            np.zeros((S, 2), np.uint32), si, self._plan,
                            bucket, mode)
            if (self.overlap and not self.speculative
                    and not self._warmed_modes):
                # precompile the dispatch-ahead token-feed select (the
                # host-known-token merge over the previous dispatch's
                # un-fetched device output) — one fixed-shape [S]
                # executable, so the pipelined loop mints zero compiled
                # variants beyond the serial loop's own set
                tok = jnp.where(np.zeros((S,), bool), tok,
                                np.zeros((S,), np.int32))
            if self.prefix_cache and not self._warmed_modes:
                # precompile the COW block copy (null-block self-copy:
                # a no-op) so a cache hit that must privatize never
                # traces mid-serve — the "hit path adds zero new
                # compiled variants" contract
                self._pools = self._copy_fn(self._pools,
                                            np.int32(0), np.int32(0))
                if self.speculative:
                    self._d_pools = self._copy_fn(self._d_pools,
                                                  np.int32(0), np.int32(0))
            if self.swap != "off" and not self._warmed_modes:
                # precompile BOTH spill-tier directions (a null-block
                # self-round-trip: extract reads block 0, insert puts
                # the same zeros back) so a mid-serve swap-out, prefix
                # demotion, or restore never traces — the "zero new
                # step variants" contract of ISSUE 17
                d = self._d_pools if self.speculative else None
                bset = extract_blocks(self._pools, [0], d_pools=d)
                self._pools, d = insert_blocks(
                    self._pools, bset, [0], d_pools=d,
                    donate=self._donate)
                if self.speculative:
                    self._d_pools = d
            jax.block_until_ready(tok)
        if not self._warmed_modes:
            # announce the starting bucket so every instrumented run
            # has a bucket baseline to diff switches against
            obs.serve("bucket_switch", gather_bucket=self._bucket,
                      prev_bucket=None, max_context=0)
        self._warmed_modes.update(modes)

    def run(self) -> dict[int, Request]:
        """Drive the loop until every submitted request finishes;
        returns {rid: Request}. Ends with one ``serve`` *report* event
        carrying the run's SLO summary (TTFT / end-to-end latency
        percentiles, gather-bucket accounting) so the cross-host report
        (`obs/report.py`) reads the serving story from a single line."""
        self.warmup()
        with obs.span("serve/run"):
            while self.has_work():
                self.step()
        obs.scalar("serve/kv_peak_utilization",
                   self.blocks.peak_used / max(self.blocks.num_blocks - 1, 1))
        summary = self.slo_summary()
        if summary:
            obs.serve("report", **summary)
        return self.finished

    def slo_summary(self) -> dict:
        """TTFT / end-to-end latency percentiles + scheduler/gather
        gauges over every FINISHED request ({} until one finishes)."""
        reqs = list(self.finished.values())
        if not reqs:
            return {}
        ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
        e2es = [r.finish_t - r.submit_t for r in reqs
                if r.finish_t is not None and r.submit_t is not None]
        out = {
            "requests": len(reqs),
            "sampled_requests": sum(1 for r in reqs if r.sampled),
            "tokens": self.tokens_generated,
            "iterations": self.iterations,
            "preemptions": self.sched.n_preemptions,
            "peak_waiting_depth": self.peak_waiting,
            "bucket_switches": self.bucket_switches,
            "gather_bucket": self._bucket,
            "gather_read_waste_peak": round(
                self.blocks.peak_gather_waste, 4),
            "gather_read_waste_mean": round(
                self.blocks.gather_waste(), 4),
            "kv_peak_utilization": round(
                self.blocks.peak_used
                / max(self.blocks.num_blocks - 1, 1), 4),
        }
        if self.decode_time_s > 0:
            out["decode_tokens_per_sec"] = round(
                self.decode_tokens / self.decode_time_s, 1)
        out["kernel"] = self.kernel
        out["kv_dtype"] = self.kv_cache_dtype
        # multi-replica serving (ISSUE 14): a router-owned replica's
        # report names itself so the merged cross-host report (and
        # `obsctl slo`'s per-replica grouping) can attribute it; absent
        # on router-less engines — the byte-identity contract
        out.update(self._replica_kw())
        # tensor-parallel serving (ISSUE 13): the degree + the pool's
        # per-device byte footprint (what `obsctl diff` watches as
        # serve_kv_pool_bytes_per_device — more bytes per device for
        # the same capacity is worse)
        out["tp"] = self.tp
        out["kv_pool_bytes_per_device"] = self.blocks.pool_bytes
        if self.overlap:
            # dispatch-ahead accounting (absent entirely with the
            # overlap off — that stream stays byte-identical to the
            # serial engine's)
            out["overlap"] = True
            out["overlap_flushes"] = self.overlap_flushes
        if self.decode_steps:
            out["kv_bytes_read_per_step"] = round(
                self.kv_bytes_read / self.decode_steps, 1)
        from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
            percentile,
        )

        if self.timeline:
            # lifecycle decomposition aggregates (ISSUE 10): queue-wait
            # percentiles and run-wide phase-time fractions over the
            # finished requests — the live decision inputs SLO-aware
            # admission needs, and the figures `obsctl diff` gates on
            # (absent entirely with the timeline off, keeping the
            # report event byte-identical to the pre-tracing stream)
            qs = sorted(r.phase_s["queue"] for r in reqs)
            out["queue_wait_p50_s"] = round(percentile(qs, 0.50), 6)
            out["queue_wait_p99_s"] = round(percentile(qs, 0.99), 6)
            tot = sum(e2es)
            if tot > 0:
                sums = {ph: sum(r.phase_s[ph] for r in reqs)
                        for ph in ("queue", "prefill", "decode",
                                   "preempted")}
                for ph, v in sums.items():
                    out[f"{ph}_time_frac"] = round(v / tot, 4)
                out["overhead_time_frac"] = round(
                    1.0 - sum(sums.values()) / tot, 4)

        if self.prefix_cache:
            cached = sum(r.prefix_cached_tokens for r in reqs)
            admitted = sum(r.prefix_prompt_tokens for r in reqs)
            out["prefix_cache"] = True
            out["prefix_cached_tokens"] = cached
            out["cache_hit_rate"] = (round(cached / admitted, 4)
                                     if admitted else 0.0)
            out["blocks_shared_peak"] = self.blocks.peak_shared_blocks
            out["blocks_saved_peak"] = self.blocks.peak_blocks_saved
            out["cow_copies"] = self.blocks.cow_copies
            out["prefix_evictions"] = self.blocks.prefix_evictions
            out["shared_read_frac"] = round(
                self.blocks.shared_read_frac(), 4)
        out["peak_resident_requests"] = self.peak_resident

        # open-loop SLO attainment (ISSUE 16): the DistServe goodput
        # numerator — fraction of deadline-carrying finished requests
        # that met EVERY set target, plus the per-group (tenant) split
        # and the peak arrival-stamped backlog. Each key is gated on
        # its own feed having appeared, so closed-loop (and target-
        # less open-loop) reports stay byte-identical to before.
        if self._has_slo and self._slo_total:
            out["slo_attainment"] = round(
                self._slo_met / self._slo_total, 4)
            out["group_slo_attainment"] = {
                g: round(m / t, 4)
                for g, (m, t) in sorted(self._group_slo.items()) if t}
        if self._has_arrivals:
            out["arrival_backlog_peak"] = self._arrival_backlog_peak

        # admission policy (ISSUE 20): each rider gated on its own
        # feed so a fifo run (and a deadline-less / priority-less slo
        # run) reports byte-identically to the pre-policy engine
        if self.policy != "fifo":
            out["policy"] = self.policy
            out["aging_promotions"] = self.sched.aging_promotions
        if self._deadline_total:
            out["deadline_miss_frac"] = round(
                self._deadline_miss / self._deadline_total, 4)
        if self._has_priorities and self._slo_total:
            out["priority_slo_attainment"] = {
                str(p): round(m / t, 4)
                for p, (m, t) in sorted(self._priority_slo.items())
                if t}

        # host-RAM spill tier (ISSUE 17): swap traffic and prefix
        # demotion-tier accounting — absent entirely with the tier off,
        # keeping that report byte-identical to the pre-tier engine's
        if self.swap != "off":
            out["swap_policy"] = self.swap
            out["swap_outs"] = self.swap_outs
            out["swap_ins"] = self.swap_ins
            out["swap_bytes"] = self.swap_bytes_moved
            out["restore_s"] = round(self.restore_s, 6)
            out["recompute_tokens_avoided"] = self.recompute_tokens_avoided
            out["host_tier_hits"] = self.blocks.host_tier_hits
            out["host_tier_hit_rate"] = round(
                self.blocks.host_tier_hits
                / max(1, self.blocks.host_tier_lookups), 4)

        # cross-engine transport (ISSUE 18): absent entirely unless a
        # migration touched this engine — the byte-identity contract
        # for single-engine and migration-free fleet traffic
        if self.migrations_in or self.migrations_out:
            out["migrations_in"] = self.migrations_in
            out["migrations_out"] = self.migrations_out
            out["migration_bytes"] = self.migration_bytes
            out["migration_restore_s"] = round(
                self.migration_restore_s, 6)

        if self.speculative:
            out["speculate_k"] = self.speculate_k
            out["draft_proposed"] = self.draft_proposed
            out["draft_accepted"] = self.draft_accepted
            if self.draft_proposed:
                out["acceptance_rate"] = round(
                    self.draft_accepted / self.draft_proposed, 4)
            # the PER-REQUEST acceptance distribution: the aggregate
            # hides a single request speculating badly (a pathological
            # prompt for the draft) — p50/min name it
            rates = sorted(r.spec_accepted / r.spec_proposed
                           for r in reqs if r.spec_proposed)
            if rates:
                out["acceptance_rate_p50"] = round(
                    percentile(rates, 0.50), 4)
                out["acceptance_rate_min"] = round(rates[0], 4)
            out["verify_read_waste_peak"] = round(
                self.blocks.peak_verify_waste, 4)
            out["verify_read_waste_mean"] = round(
                self.blocks.verify_waste(), 4)

        for label, vals in (("ttft", ttfts), ("e2e", e2es)):
            if not vals:
                continue
            s = sorted(vals)
            out[f"{label}_p50_s"] = round(percentile(s, 0.50), 6)
            out[f"{label}_p95_s"] = round(percentile(s, 0.95), 6)
            out[f"{label}_p99_s"] = round(percentile(s, 0.99), 6)
        return out

    def stats(self) -> EngineStats:
        return EngineStats(
            decode_steps=self.decode_steps,
            prefill_chunks=self.prefill_chunks,
            prefill_dispatches=self.prefill_dispatches,
            tokens_generated=self.tokens_generated,
            decode_tokens=self.decode_tokens,
            decode_time_s=self.decode_time_s,
            preemptions=self.sched.n_preemptions,
            bucket_switches=self.bucket_switches,
            kv_peak_utilization=self.blocks.peak_used
            / max(self.blocks.num_blocks - 1, 1),
            kv_utilization=self.blocks.utilization(),
            gather_waste_peak=self.blocks.peak_gather_waste,
            gather_waste_mean=self.blocks.gather_waste(),
            draft_proposed=self.draft_proposed,
            draft_accepted=self.draft_accepted,
            acceptance_rate=(self.draft_accepted / self.draft_proposed
                             if self.draft_proposed else None),
            spec_windows=self.spec_windows,
            verify_waste_peak=self.blocks.peak_verify_waste,
            verify_waste_mean=self.blocks.verify_waste(),
            prefix_cache=self.prefix_cache,
            prefix_cached_tokens=sum(
                r.prefix_cached_tokens for r in self.finished.values()),
            cache_hit_rate=self._aggregate_hit_rate(),
            blocks_shared_peak=self.blocks.peak_shared_blocks,
            blocks_saved_peak=self.blocks.peak_blocks_saved,
            cow_copies=self.blocks.cow_copies,
            prefix_evictions=self.blocks.prefix_evictions,
            shared_read_frac=self.blocks.shared_read_frac(),
            peak_resident_requests=self.peak_resident,
            kernel=self.kernel,
            kv_dtype=self.kv_cache_dtype,
            kv_bytes_read=self.kv_bytes_read,
            kv_token_bytes=self.blocks.token_bytes,
            overlap=self.overlap,
            overlap_flushes=self.overlap_flushes,
            tp=self.tp,
            kv_pool_bytes_per_device=self.blocks.pool_bytes,
            swap_policy=self.swap,
            swap_outs=self.swap_outs,
            swap_ins=self.swap_ins,
            swap_bytes=self.swap_bytes_moved,
            restore_s=self.restore_s,
            recompute_tokens_avoided=self.recompute_tokens_avoided,
            host_tier_hits=self.blocks.host_tier_hits,
            host_tier_hit_rate=(
                self.blocks.host_tier_hits
                / max(1, self.blocks.host_tier_lookups)
                if self.swap != "off" else None),
            migrations_in=self.migrations_in,
            migrations_out=self.migrations_out,
            migration_bytes=self.migration_bytes)

    def _aggregate_hit_rate(self) -> Optional[float]:
        """Prompt tokens served from cache / prompt tokens admitted,
        over every finished request (None with prefix caching off or
        before any finish)."""
        if not self.prefix_cache:
            return None
        admitted = sum(r.prefix_prompt_tokens
                       for r in self.finished.values())
        if not admitted:
            return None
        return (sum(r.prefix_cached_tokens
                    for r in self.finished.values()) / admitted)

    # -- one engine iteration ------------------------------------------------

    def step(self) -> None:
        """Admit → batched prefill under the token budget → one decode
        step over all slots at the iteration's gather bucket. With
        ``timeline`` on, every phase transition is stamped host-side
        (queue→prefill at admission, preemption intervals at eviction)
        and one ``iteration_ledger`` event records the iteration's
        phase mix — all ``perf_counter`` arithmetic, zero new compiled
        variants.

        With ``overlap`` on (the default) the decode tail of the
        iteration runs DISPATCH-AHEAD: the admission/prefill/stamping
        above already executed concurrently with the previous
        iteration's in-flight device step, and the plain families
        dispatch iteration N before committing N−1's (already
        computed) tokens — see :meth:`_dispatch_decode` /
        :meth:`_commit_decode`. A speculative engine commits its
        in-flight window first (:meth:`_commit_spec`) because the next
        window's inputs are data-dependent on the acceptance counts.

        Under a tensor-parallel mesh (ISSUE 13) the whole iteration
        runs inside ``use_mesh`` — the ambient mesh model code (and
        the gathered-view head pinning in ``ops.attention``) keys on;
        every dispatch's SPMD partitioning is otherwise driven by the
        committed param/pool shardings alone."""
        with self._mesh_ctx():
            self._step()

    def _mesh_ctx(self):
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
            use_mesh,
        )

        return (use_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def _step(self) -> None:
        t_iter0 = time.perf_counter()
        tokens0 = self.tokens_generated
        chunks0, disp0 = self.prefill_chunks, self.prefill_dispatches
        self._iter_prefill_s = 0.0
        self._iter_decode_s = 0.0
        self._iter_decode_slots = 0
        for slot in self.sched.admit():
            n_cow = len(slot.pending_copies)
            if self.timeline:
                # stamp BEFORE the COW copies run: the queue/preempted
                # interval ends at admission, and the copy dispatches
                # land in overhead (the documented contract)
                self._stamp_admit(slot, n_cow)
            self._apply_restores(slot)
            self._apply_cow(slot)
            extra = {}
            if self.prefix_cache:
                extra["prefix_cached_tokens"] = slot.prefill_pos
            obs.serve("admit", request=slot.request.rid, slot=slot.index,
                      queue_depth=len(self.sched.waiting),
                      **self._replica_kw(),
                      **self._trace_kw(slot.request), **extra)
        if self.timeline and self.sched.waiting:
            # admission-block attribution: only the policy's TOP-RANKED
            # candidate is ever capacity-blocked (everyone behind it is
            # blocked BY it) — under fifo that is the queue head, under
            # slo the ranked front — name why it is still waiting
            head = self.sched.blocked_head()
            head.blocked_iters += 1
            head.blocked_reason = (
                "no_free_slot"
                if all(not s.free for s in self.sched.slots)
                else "kv_capacity")
        self.peak_resident = max(
            self.peak_resident,
            sum(1 for s in self.sched.slots if not s.free))
        C = self.sched.prefill_chunk
        budget = self.sched.prefill_token_budget(
            len(self.sched.decode_slots()))
        while budget >= C:
            # charged at DISPATCH cost (incl. pad rows of a partially
            # filled batch), not real chunks — the budget bounds the
            # decode stall, and the stall is what the device computes
            dispatched_rows = self._prefill_batch(budget // C)
            if not dispatched_rows:
                break
            budget -= dispatched_rows * C
        if self.prefill_only:
            # disaggregated prefill replica (ISSUE 18): no decode phase
            # at all — no capacity math either, since parked DECODE
            # slots never grow their tables here (the router migrates
            # them to a decode replica between iterations, and "zero
            # decode iterations on a prefill replica" is the bench's
            # role-separation gate)
            pass
        elif not self.overlap:
            self._capacity_phase()
            self._decode_all()
        elif self.speculative:
            # the in-flight window overlapped the admission/prefill
            # work above; it must land before the capacity math (the
            # context advance is data-dependent) and the next dispatch
            self._commit_spec(self._pending_spec)
            self._pending_spec = None
            self._capacity_phase()
            self._pending_spec = self._dispatch_spec()
        else:
            # plain/bucketed/kernel families: flush the pipeline only
            # when the capacity math could preempt (the recompute path
            # must see committed state), dispatch N, then commit N−1's
            # tokens while N runs on the device
            if (self._pending is not None
                    and not self._capacity_covered()):
                self._flush("kv_pressure")
            self._capacity_phase()
            if self._lone_stream():
                # low-load auto-flush (ISSUE 13, the PR 12 TTFT
                # follow-up): a LONE stream with nothing waiting has
                # no concurrent host work for the pipeline to hide —
                # dispatch-ahead would only defer every token's fetch
                # (and the final token's delivery) by one iteration.
                # Run this iteration serially instead: land any
                # in-flight dispatch (a plain commit, not a forced
                # drain — overlap_flushes counts mandatory drains
                # only), then dispatch+fetch in one go, exactly the
                # overlap='off' schedule. The condition re-evaluates
                # every iteration, so the pipeline re-engages the
                # moment a second stream admits.
                prev, self._pending = self._pending, None
                self._commit_decode(prev)
                self._decode_all()
            else:
                prev, self._pending = (self._pending,
                                       self._dispatch_decode())
                self._commit_decode(prev)
        # per-iteration scheduler gauges (SLO telemetry): queue pressure
        # and slot occupancy as series, one sample per engine iteration
        waiting = len(self.sched.waiting)
        self.peak_waiting = max(self.peak_waiting, waiting)
        arrival_kw = {}
        if self._has_arrivals:
            # open-loop backlog (ISSUE 16): how many arrival-stamped
            # requests are queued at this instant — a deterministic
            # integer (unlike the wall-time queue decomposition), so
            # the virtual-clock bench can gate on it. Absent entirely
            # on closed-loop runs — the byte-identity contract.
            backlog = sum(1 for r in self.sched.waiting
                          if r.arrival_s is not None)
            self._arrival_backlog_peak = max(
                self._arrival_backlog_peak, backlog)
            arrival_kw["arrival_backlog"] = backlog
        if obs.has_sink():
            obs.scalar("serve/waiting_depth", waiting, self.iterations)
            obs.scalar("serve/running_slots",
                       len(self.sched.decode_slots()), self.iterations)
            obs.scalar("serve/preemptions", self.sched.n_preemptions,
                       self.iterations)
            obs.scalar("serve/gather_bucket", self._bucket,
                       self.iterations)
            if self.timeline:
                # the engine ledger: one line per iteration with the
                # phase mix (prefill vs decode dispatch seconds inside
                # the iteration wall), the bucket, the slot/token
                # throughput, and pool pressure — what `obsctl tail`
                # follows live
                obs.serve(
                    "iteration_ledger", iteration=self.iterations,
                    dur_s=round(time.perf_counter() - t_iter0, 6),
                    prefill_s=round(self._iter_prefill_s, 6),
                    decode_s=round(self._iter_decode_s, 6),
                    gather_bucket=self._bucket,
                    prefill_chunks=self.prefill_chunks - chunks0,
                    prefill_dispatches=self.prefill_dispatches - disp0,
                    decode_slots=self._iter_decode_slots,
                    tokens=self.tokens_generated - tokens0,
                    waiting=waiting,
                    kv_used_frac=round(self.blocks.utilization(), 4),
                    **arrival_kw, **self._replica_kw())
        self.iterations += 1

    def _capacity_phase(self) -> None:
        """Decode-side block capacity for the next dispatch, preempting
        when the pool runs dry (serial semantics — under overlap the
        caller drained the pipeline first when this could preempt)."""
        for req in self.sched.ensure_decode_capacity():
            obs.serve("preempt", request=req.rid,
                      reason="kv_pool_exhausted", **self._replica_kw(),
                      **self._trace_kw(req))
            if self.timeline:
                # the preempted interval runs from here to re-admission;
                # emit the partial timeline NOW so a request that never
                # comes back (a killed run) still left its history
                req.preempt_t = time.perf_counter()
                self._emit_timeline(req, "preempt", req.preempt_t)

    def _lone_stream(self) -> bool:
        """True when decode-batch occupancy is exactly one and the
        waiting queue is empty — the dispatch-ahead pipeline's
        auto-flush condition (ISSUE 13): the single resident stream is
        decoding, no other slot is prefilling alongside it and nothing
        is queued, so there is no concurrent host work to overlap and
        the deferred fetch would be pure added latency per token."""
        busy = [s for s in self.sched.slots if not s.free]
        return (not self.sched.waiting and len(busy) == 1
                and busy[0].request is not None
                and busy[0].request.state == DECODE)

    def _capacity_covered(self) -> bool:
        """True when every decode slot's next write span is coverable
        without touching the preemption path — the cheap host-side
        precheck that decides whether the dispatch-ahead pipeline must
        drain before :meth:`_capacity_phase` runs. Conservative: a
        False here only costs one lost overlap window."""
        need = sum(
            max(0, self.blocks.blocks_for(
                s.context_len + self.sched.decode_lookahead)
                - len(s.table))
            for s in self.sched.decode_slots())
        return self.blocks.can_allocate(need)

    def _flush(self, reason: str) -> None:
        """Drain the dispatch-ahead pipeline: fetch and commit the
        in-flight iteration NOW (losing its overlap window) so the
        caller's next decision acts on fully committed state. The
        mandatory drains — preemption and KV-pressure block math — are
        what ``overlap_flushes`` counts."""
        if self._pending is None:
            return
        self.overlap_flushes += 1
        prev, self._pending = self._pending, None
        self._commit_decode(prev)

    def _select_bucket(self, need: int) -> int:
        """Smallest configured bucket covering ``need`` resident
        context, with shrink hysteresis: growth is immediate
        (correctness — the write position must be addressable),
        shrinking waits ``SHRINK_PATIENCE`` consecutive iterations
        where the smaller bucket would have sufficed, so churn around
        a boundary stays bounded. Every switch is telemetered."""
        fit = next(b for b in self.gather_buckets if b >= need)
        if fit > self._bucket:
            self._switch_bucket(fit, need)
        elif fit < self._bucket:
            self._shrink_streak += 1
            if self._shrink_streak >= self.SHRINK_PATIENCE:
                self._switch_bucket(fit, need)
        else:
            self._shrink_streak = 0
        return self._bucket

    def _switch_bucket(self, new: int, need: int) -> None:
        prev, self._bucket = self._bucket, new
        self._shrink_streak = 0
        self.bucket_switches += 1
        obs.serve("bucket_switch", gather_bucket=new, prev_bucket=prev,
                  max_context=need)

    def _prefill_batch(self, max_rows: int) -> int:
        """One batched prefill dispatch over up to
        ``min(max_rows, prefill_batch)`` prefilling slots (static
        [G, C] shape — unused rows ride to the null block). A LONE
        prefilling request runs the [1, C] variant instead: padding it
        to the full batch would multiply low-load prefill compute (and
        TTFT) by ``prefill_batch``. Two compiled shapes total, both
        warmed. Returns the DISPATCHED row count G — pad rows included,
        so the caller's token budget charges what the device actually
        computed, keeping the decode-stall bound honest at partial
        load (0 = no prefill work)."""
        slots = self.sched.next_prefill_slots(
            min(max_rows, self.prefill_batch))
        if not slots:
            return 0
        G = 1 if len(slots) == 1 else self.prefill_batch
        C = self.sched.prefill_chunk
        chunks = np.full((G, C), self.pad_token_id, np.int32)
        tables = np.zeros((G, self.max_blocks_per_seq), np.int32)
        start = np.zeros((G,), np.int32)
        rel = np.full((G,), -1, np.int32)
        temps = np.zeros((G,), np.float32)
        top_ks = np.zeros((G,), np.int32)
        top_ps = np.zeros((G,), np.float32)
        keys = np.zeros((G, 2), np.uint32)
        folds = np.zeros((G,), np.int32)
        finals = []
        sampled = False
        for i, slot in enumerate(slots):
            req = slot.request
            pos = slot.prefill_pos
            real = req.prompt[pos:pos + C]
            chunks[i, :len(real)] = real
            tables[i, :len(slot.table)] = slot.table
            start[i] = pos
            if pos + C >= self.sched.padded_prompt_len(req):
                rel[i] = (len(req.prompt) - 1) - pos
                finals.append((i, slot))
                if req.sampled:
                    sampled = True
                    temps[i] = req.temperature
                    top_ks[i] = req.top_k
                    top_ps[i] = req.top_p
                    keys[i] = self._keys[req.rid]
                    folds[i] = self._generated(req)
        t0 = time.perf_counter()
        with obs.span("serve/prefill_chunk",
                      {"chunks": len(slots)} if obs.has_sink() else None):
            tok, self._pools = self._prefill_fn(
                self.model, self.params, self._pools, chunks, tables,
                start, rel, temps, top_ks, top_ps, keys, folds,
                self._plan, sampled)
            if self.speculative:
                # the draft's pools must hold the prompt KV too — same
                # chunks/tables, its own address space; the returned
                # token is discarded (the draft never emits)
                _, self._d_pools = self._prefill_fn(
                    self.draft_model, self.draft_params, self._d_pools,
                    chunks, tables, start, rel, temps, top_ks, top_ps,
                    keys, folds, self._d_plan, False)
        if self.timeline:
            # dispatch-enqueue wall time (an async backend's device
            # wait surfaces at the next sync and lands in overhead —
            # attribution stays disjoint, never double-counted)
            dur = time.perf_counter() - t0
            self._iter_prefill_s += dur
            for slot in slots:
                self._accrue_prefill(slot, t0, dur)
        for slot in slots:
            slot.prefill_pos += C
        self.prefill_chunks += len(slots)
        self.prefill_dispatches += 1
        if finals:
            # fetch the continuation tokens; also the sync point that
            # makes TTFT an honest end-to-end wall time
            # graftlint: allow[R2] first-token fetch at prompt completion: the value gates the slot's prefill->decode flip and is the sync that keeps TTFT an honest wall time
            tok_host = np.asarray(jax.device_get(tok))
            for i, slot in finals:
                req = slot.request
                self.sched.finish_prefill(slot)
                if self.speculative and self._generated(req) > 0:
                    # preemption-resumed speculative request: its next
                    # token's index is mid-stream, and mid-stream
                    # tokens come from verify windows — emitting the
                    # prefill sample here would consume a different
                    # RNG draw than the uninterrupted run's window did
                    # (breaking bitwise seed-reproducibility across
                    # preemption). Hand the slot to the window loop
                    # instead: its newest committed token is the
                    # folded prompt's last id, whose K/V the next
                    # window re-writes at context_len (same value the
                    # prefill just wrote — an idempotent overwrite)
                    slot.context_len -= 1
                else:
                    self._append(slot, int(tok_host[i]))
        return G

    def _decode_all(self) -> None:
        if self.speculative:
            return self._decode_all_spec()
        ds = self.sched.decode_slots()
        if not ds:
            return
        bucket = self._select_bucket(self.sched.max_decode_context())
        S = self.num_slots
        tokens = np.zeros((S,), np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), np.int32)
        ctx = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        temps = np.zeros((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.zeros((S,), np.float32)
        keys = np.zeros((S, 2), np.uint32)
        folds = np.zeros((S,), np.int32)
        sampled = False
        for slot in ds:
            req = slot.request
            i = slot.index
            tokens[i] = req.output[-1]
            tables[i, :len(slot.table)] = slot.table
            ctx[i] = slot.context_len
            active[i] = True
            if req.sampled:
                sampled = True
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                top_ps[i] = req.top_p
                keys[i] = self._keys[req.rid]
                folds[i] = self._generated(req)
        self.blocks.note_gather([s.context_len + 1 for s in ds], bucket)
        # the step's KV read traffic in POOL bytes (every slot row of
        # the dispatch × the bucket width × bytes/token across pools —
        # int8 pools halve this, which is the point): one scalar per
        # decode step, aggregated into the SLO report
        step_bytes = self.num_slots * bucket * self.blocks.token_bytes
        self.kv_bytes_read += step_bytes
        if obs.has_sink():
            obs.scalar("serve/kv_bytes_read", step_bytes, self.iterations)
        # blocks_saved() == 0 means no block is shared right now — the
        # per-slot table walk would only accumulate zeros, so skip it
        # (the common case for non-templated traffic with the cache on)
        if self.prefix_cache and self.blocks.blocks_saved() > 0:
            self.blocks.note_shared_reads(sum(
                self.blocks.shared_read_tokens(s.table, s.context_len)
                for s in ds))
        t0 = time.perf_counter()
        with obs.span("serve/decode_step",
                      {"active": len(ds), "gather_bucket": bucket}
                      if obs.has_sink() else None):
            nxt, self._pools = self._decode_fn(
                self.model, self.params, self._pools, tokens, tables,
                ctx, active, temps, top_ks, top_ps, keys, folds,
                self._plan, bucket, sampled)
            # graftlint: allow[R2] the SERIAL loop's per-step fetch: this is the overlap=off reference implementation the dispatch-ahead gates compare against, serial by definition
            nxt = np.asarray(jax.device_get(nxt))
        dur = time.perf_counter() - t0
        self.decode_time_s += dur
        self.decode_steps += 1
        self.decode_tokens += len(ds)
        if self.timeline:
            self._iter_decode_s += dur
            self._iter_decode_slots = len(ds)
        for slot in ds:
            slot.context_len += 1        # the fed token's K/V landed
            if self.timeline:
                self._accrue_decode(slot.request, t0, dur, bucket, 1)
            self._append(slot, int(nxt[slot.index]))

    def _dispatch_decode(self) -> Optional[_PendingDecode]:
        """Dispatch-ahead plain decode (ISSUE 12): enqueue iteration N
        WITHOUT waiting for iteration N−1's tokens. A rider of the
        in-flight dispatch feeds its un-fetched DEVICE token (the
        pipeline's data chain — the value never round-trips through
        the host); slots whose newest token is host-known (fresh from
        prefill, first step after a flush) merge in through the warmed
        fixed-shape select. Slots that will BUDGET-finish when N−1
        commits are excluded up front (a pure count — re-derived
        exactly, no token value needed); an EOS finish is unknowable
        here, so that rider runs one wasted row whose output the
        commit discards — the stale K/V write is hidden by the
        context masks and ordered before any block reuse by the pool
        chain. Context lengths advance AT DISPATCH (the write lands
        regardless of the token's value), which keeps bucket choice
        and block math exact, not speculative.

        The per-slot staging/accounting here deliberately MIRRORS
        :meth:`_decode_all` instead of replacing it: the serial loop
        stays an INDEPENDENT reference implementation, which is what
        gives the overlap-on == overlap-off torture gates their teeth
        (shared code would compare a path against itself). Accounting
        changes must land in both."""
        prev = self._pending
        ds = []
        for slot in self.sched.decode_slots():
            eff = self._generated(slot.request) + slot.inflight
            if eff >= slot.request.max_new_tokens:
                continue         # finishes at the in-flight commit
            ds.append(slot)
        if not ds:
            return None
        bucket = self._select_bucket(
            max(s.context_len + self.sched.decode_lookahead
                for s in ds))
        S = self.num_slots
        vals = np.zeros((S,), np.int32)
        use_dev = np.zeros((S,), bool)
        tables = np.zeros((S, self.max_blocks_per_seq), np.int32)
        ctx = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        temps = np.zeros((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.zeros((S,), np.float32)
        keys = np.zeros((S, 2), np.uint32)
        folds = np.zeros((S,), np.int32)
        sampled = False
        for slot in ds:
            req = slot.request
            i = slot.index
            if slot.inflight:
                use_dev[i] = True
            else:
                # a DECODE slot always has output resident (prefill
                # appends the first token before the state flips) —
                # same invariant the serial loop indexes on
                vals[i] = req.output[-1]
            tables[i, :len(slot.table)] = slot.table
            ctx[i] = slot.context_len
            active[i] = True
            if req.sampled:
                sampled = True
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                top_ps[i] = req.top_p
                keys[i] = self._keys[req.rid]
                # the in-flight token counts: token N's fold index is
                # its request-global position, exactly the serial value
                folds[i] = self._generated(req) + slot.inflight
        self.blocks.note_gather([s.context_len + 1 for s in ds], bucket)
        step_bytes = self.num_slots * bucket * self.blocks.token_bytes
        self.kv_bytes_read += step_bytes
        if obs.has_sink():
            obs.scalar("serve/kv_bytes_read", step_bytes, self.iterations)
        if self.prefix_cache and self.blocks.blocks_saved() > 0:
            self.blocks.note_shared_reads(sum(
                self.blocks.shared_read_tokens(s.table, s.context_len)
                for s in ds))
        if prev is None or not use_dev.any():
            tokens = vals
        elif all(s.inflight for s in ds):
            # steady pipeline: every active slot rode the in-flight
            # dispatch, so its token array IS the feed — no select op
            # on the device chain at all (the common decode-bound case)
            tokens = prev.nxt
        else:
            tokens = jnp.where(use_dev, prev.nxt, vals)
        t0 = time.perf_counter()
        with obs.span("serve/decode_step",
                      {"active": len(ds), "gather_bucket": bucket}
                      if obs.has_sink() else None):
            nxt, self._pools = self._decode_fn(
                self.model, self.params, self._pools, tokens, tables,
                ctx, active, temps, top_ks, top_ps, keys, folds,
                self._plan, bucket, sampled)
        dispatch_s = time.perf_counter() - t0
        if self.timeline:
            # the enqueue cost lands in THIS iteration's ledger (the
            # blocked fetch lands in the committing iteration's), so
            # dur_s >= prefill_s + decode_s stays true per ledger line
            self._iter_decode_s += dispatch_s
        for slot in ds:
            slot.context_len += 1        # the fed token's K/V lands
            slot.inflight = 1
        return _PendingDecode(nxt, tuple((s, s.request) for s in ds),
                              bucket, dispatch_s, t0)

    def _commit_decode(self, prev: Optional[_PendingDecode]) -> None:
        """Land one in-flight plain decode iteration: the deferred
        ``device_get`` — by now the device has computed through all
        the host work since dispatch, so the blocked wait is only the
        residual — then append/EOS-check per rider. Decode time
        accounts dispatch enqueue + blocked fetch ONLY: the host work
        in between ran concurrently with the device, which is the
        measurable claim of the dispatch-ahead loop. A rider whose
        request finished at the previous commit (EOS discovered one
        step late) has its token discarded — a serial loop would
        never have computed it, and discarding reproduces the serial
        output exactly."""
        if prev is None:
            return
        t0 = time.perf_counter()
        # graftlint: allow[R2] THE deferred commit fetch (ISSUE 12): deliberately one iteration late, so only the residual past the overlapped host work blocks here
        nxt = np.asarray(prev.nxt)
        t_end = time.perf_counter()
        fetch_s = t_end - t0
        # the ENGINE's decode-time accounting stays blocked-time only
        # (dispatch enqueue + residual fetch wait): the host work in
        # between ran concurrently, and hiding it is exactly what the
        # bench's decode-tokens/sec ratio measures
        self.decode_time_s += prev.dispatch_s + fetch_s
        self.decode_steps += 1
        # riders of the CURRENT in-flight dispatch keep their inflight
        # mark (dispatch N ran before this commit of N−1 and re-marked
        # them); everyone else's newest token is host-resident again
        live = {id(s) for s, _ in (self._pending.riders
                                   if self._pending is not None else ())}
        committed = 0
        for slot, req in prev.riders:
            if id(slot) not in live:
                slot.inflight = 0
            if req.rid in self.finished or slot.request is not req:
                continue         # wasted row past an EOS: discarded
            committed += 1
            self.decode_tokens += 1
            if self.timeline:
                # the REQUEST's decode interval is the whole
                # dispatch→fetch window — the host work inside it ran
                # concurrently with the device, so it is decode time,
                # not overhead — clipped to the request's previous
                # attributed end so intervals stay disjoint (the
                # checkable-decomposition invariant): back-to-back
                # overlapped iterations tile the decode-bound stretch
                # with no overhead gaps, which is the decomposition's
                # view of the de-overheaded loop
                start = prev.t_dispatch
                if req.decode_attr_end is not None:
                    start = max(start, req.decode_attr_end)
                self._accrue_decode(req, start, t_end - start,
                                    prev.bucket, 1)
                req.decode_attr_end = t_end
            self._append(slot, int(nxt[slot.index]))
        if self.timeline:
            self._iter_decode_s += fetch_s
            self._iter_decode_slots = committed

    def _decode_all_spec(self) -> None:
        """One SERIAL speculative iteration: dispatch + immediate
        commit (the dispatch-ahead loop splits these across the
        iteration boundary instead, overlapping the next iteration's
        admission/prefill/telemetry with the in-flight window)."""
        self._commit_spec(self._dispatch_spec())

    def _dispatch_spec(self) -> Optional[_PendingSpec]:
        """Enqueue one speculative draft-k propose + width-(k+1)
        verify dispatch over all decode slots; the host-side commit
        (:meth:`_commit_spec`) lands the accepted prefix + bonus per
        slot — ``context_len`` advanced over exactly the committed
        tokens (the O(1) rewind: rejected draft K/V past it is stale,
        invisible to context-derived masks, and overwritten by the
        next window), and the block-table tail past the committed
        context returns to the free list."""
        ds = self.sched.decode_slots()
        if not ds:
            return None
        k = self.speculate_k
        bucket = self._select_bucket(self.sched.max_decode_context())
        S = self.num_slots
        tokens = np.zeros((S,), np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), np.int32)
        ctx = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        temps = np.zeros((S,), np.float32)
        top_ks = np.zeros((S,), np.int32)
        top_ps = np.zeros((S,), np.float32)
        keys = np.zeros((S, 2), np.uint32)
        folds = np.zeros((S,), np.int32)
        sampled = False
        for slot in ds:
            req = slot.request
            i = slot.index
            # newest committed token: the last generated one, or the
            # prompt tail when no generation is resident in `output`
            # (fresh post-preemption resume)
            tokens[i] = req.output[-1] if req.output else req.prompt[-1]
            tables[i, :len(slot.table)] = slot.table
            ctx[i] = slot.context_len
            active[i] = True
            if req.sampled:
                sampled = True
                temps[i] = req.temperature
                top_ks[i] = req.top_k
                top_ps[i] = req.top_p
                keys[i] = self._keys[req.rid]
                folds[i] = self._generated(req)   # window start index
        self.blocks.note_gather(
            [s.context_len + k + 1 for s in ds], bucket)
        # draft (k+1 steps) + verify each read a bucket-wide assembled
        # cache: the target-pool read is what the fp-vs-int8 comparison
        # isolates, so account the verify read (one bucket per slot row)
        step_bytes = self.num_slots * bucket * self.blocks.token_bytes
        self.kv_bytes_read += step_bytes
        if obs.has_sink():
            obs.scalar("serve/kv_bytes_read", step_bytes, self.iterations)
        if self.prefix_cache and self.blocks.blocks_saved() > 0:
            self.blocks.note_shared_reads(sum(
                self.blocks.shared_read_tokens(s.table, s.context_len)
                for s in ds))
        t0 = time.perf_counter()
        with obs.span("serve/spec_decode_step",
                      {"active": len(ds), "gather_bucket": bucket,
                       "speculate_k": k} if obs.has_sink() else None):
            drafts, n_acc, bonus, self._pools, self._d_pools = \
                self._spec_fn(
                    self.model, self.params, self.draft_model,
                    self.draft_params, self._pools, self._d_pools,
                    tokens, tables, ctx, active, temps, top_ks, top_ps,
                    keys, folds, self._plan, self._d_plan, bucket, k,
                    sampled)
        dispatch_s = time.perf_counter() - t0
        if self.timeline:
            # enqueue cost in the dispatching iteration's ledger (the
            # fetch lands in the committing one's) — see the plain
            # pipeline's convention
            self._iter_decode_s += dispatch_s
        return _PendingSpec(drafts, n_acc, bonus,
                            tuple((s, s.request) for s in ds),
                            bucket, dispatch_s, t0)

    def _commit_spec(self, pending: Optional[_PendingSpec]) -> None:
        """Land one speculative window: ONE fused tuple transfer for
        (drafts, n_acc, bonus) — the three per-iteration host reads
        collapse into a single ``device_get`` round trip — then the
        per-slot commit. Serial mode calls this immediately after the
        dispatch; the dispatch-ahead loop calls it one iteration
        late, after the next iteration's admission/prefill work
        overlapped the window's device compute."""
        if pending is None:
            return
        ds = [slot for slot, _ in pending.riders]
        k = self.speculate_k
        bucket = pending.bucket
        t0 = time.perf_counter()
        # graftlint: allow[R2] the speculative window's deferred commit fetch: one fused tuple transfer per window (three reads collapsed), data-dependent acceptance makes it unavoidable
        drafts, n_acc, bonus = map(np.asarray, jax.device_get(
            (pending.drafts, pending.n_acc, pending.bonus)))
        t_end = time.perf_counter()
        fetch_s = t_end - t0
        self.decode_time_s += pending.dispatch_s + fetch_s
        self.decode_steps += 1
        self.spec_windows += len(ds)
        if self.timeline:
            self._iter_decode_s += fetch_s
            self._iter_decode_slots = len(ds)
        committed = []
        for slot in ds:
            req = slot.request
            i = slot.index
            acc = int(n_acc[i])
            self.draft_proposed += k
            self.draft_accepted += acc
            req.spec_proposed += k
            req.spec_accepted += acc
            if self.timeline:
                # committed-token count lands below, one bump per
                # append (the finish emission inside _append must see
                # the segment current); the window's attributed
                # interval is [dispatch, fetch-end] — the concurrent
                # host work is decode time, not overhead — clipped
                # against the request's previous interval (a no-op in
                # serial mode, where commit precedes the next
                # dispatch)
                start = pending.t_dispatch
                if req.decode_attr_end is not None:
                    start = max(start, req.decode_attr_end)
                self._accrue_decode(req, start, t_end - start,
                                    bucket, 0, k, acc)
                req.decode_attr_end = t_end
            window = [int(drafts[i, j]) for j in range(acc)]
            window.append(int(bonus[i]))
            j = 0
            for tok in window:
                j += 1
                slot.context_len += 1    # this token's K/V is resident
                self.decode_tokens += 1
                if self.timeline:
                    req.segments[-1]["tokens"] += 1
                self._append(slot, tok)
                if req.rid in self.finished:
                    break                # EOS / budget: drop the rest
            committed.append(j)
            if req.rid not in self.finished:
                # rejected-tail blocks (reserved for the verify window,
                # now holding only stale K/V) go back to the free list
                self.blocks.trim(slot.table, slot.context_len)
        self.blocks.note_verify(committed, k + 1)

    # -- lifecycle tracing (ISSUE 10) ----------------------------------------
    #
    # All host-side perf_counter stamps: the decomposition the
    # `request_timeline` event carries is CHECKABLE — queue + prefill +
    # decode + preempted + overhead sums to the request's e2e (overhead
    # is the derived remainder: host scheduling, COW copies, and the
    # stall a resident request pays for dispatches it did not ride, e.g.
    # a decoding slot waiting out another request's prefill chunk).
    # Dispatch durations are attributed to EVERY request riding the
    # dispatch (they run concurrently — this is per-request latency
    # attribution, not a wall-clock partition across requests), and each
    # request's attributed intervals are disjoint in wall time, so its
    # phase sum can never exceed e2e (negative overhead = accounting
    # bug, which `obs.timeline.check_decomposition` flags).

    def _stamp_admit(self, slot, n_cow: int) -> None:
        """Close the request's queue (first admission) or preempted
        (re-admission) interval and record its segment — with the
        cached-prefix skip, admission-block attribution, and COW-copy
        count riding as extras."""
        req = slot.request
        now = time.perf_counter()
        if req.preempt_t is not None:
            phase, t_from = "preempted", req.preempt_t
        else:
            phase, t_from = "queue", req.submit_t
        dt = max(now - t_from, 0.0)
        req.phase_s[phase] += dt
        seg = {"ph": phase, "t0": t_from - req.submit_t, "dur": dt}
        if req.trace_id:
            # fleet tracing (ISSUE 19): segments carry WHERE they ran,
            # and a segment that closes a migration hold says so — the
            # stitcher splits cross-engine admission wait (`via:
            # "migrate"`, priced net of the source's extraction
            # seconds) out of same-engine preemption. Tagged only on
            # traced requests: untraced streams stay byte-identical.
            if self.replica is not None:
                seg["replica"] = self.replica
            if req.rid in self._migrate_hold:
                self._migrate_hold.discard(req.rid)
                if phase == "preempted":
                    seg["via"] = "migrate"
                    seg["hop"] = req.hop
        if slot.prefill_pos:
            # prefix-cache hit: prefill starts past the cached span
            seg["cached_tokens"] = int(slot.prefill_pos)
        if req.blocked_iters:
            seg["blocked_iters"] = req.blocked_iters
            seg["blocked_reason"] = req.blocked_reason
            req.blocked_iters = 0
        req.segments.append(seg)
        req.preempt_t = None
        req.cow_copies += n_cow

    def _accrue_prefill(self, slot, t0: float, dur: float) -> None:
        """Attribute one prefill dispatch's wall time to a riding slot;
        consecutive chunks coalesce into one segment (dur accumulates
        dispatch time only — host gaps between chunks stay overhead)."""
        req = slot.request
        req.phase_s["prefill"] += dur
        last = req.segments[-1] if req.segments else None
        if last is not None and last["ph"] == "prefill":
            last["dur"] += dur
            last["chunks"] += 1
        else:
            seg = {"ph": "prefill",
                   "t0": t0 - req.submit_t, "dur": dur,
                   "from": int(slot.prefill_pos),
                   "chunks": 1}
            if req.trace_id and self.replica is not None:
                seg["replica"] = self.replica
            req.segments.append(seg)

    def _accrue_decode(self, req: Request, t0: float, dur: float,
                       bucket: int, tokens: int, proposed: int = 0,
                       accepted: int = 0) -> None:
        """Attribute one decode dispatch to a riding request.
        Consecutive iterations at the SAME gather bucket coalesce into
        one segment run (per-iteration granularity is preserved exactly
        where it matters — a bucket switch starts a new run); a
        speculative engine's runs additionally carry the window
        acceptance counts."""
        req.phase_s["decode"] += dur
        last = req.segments[-1] if req.segments else None
        if (last is not None and last["ph"] == "decode"
                and last["bucket"] == bucket):
            last["dur"] += dur
            last["iters"] += 1
            last["tokens"] += tokens
            if self.speculative:
                last["proposed"] += proposed
                last["accepted"] += accepted
        else:
            seg = {"ph": "decode", "t0": t0 - req.submit_t, "dur": dur,
                   "bucket": int(bucket), "iters": 1, "tokens": tokens}
            if req.trace_id and self.replica is not None:
                seg["replica"] = self.replica
            if self.speculative:
                seg["proposed"] = proposed
                seg["accepted"] = accepted
            req.segments.append(seg)

    def _emit_timeline(self, req: Request, at: str,
                       now: Optional[float] = None) -> None:
        """One compact ``request_timeline`` event: the five-way phase
        decomposition plus the coalesced segment list. Emitted at
        finish (complete) and at preempt-requeue (partial, ``at`` says
        which — consumers keep the LAST event per request)."""
        if not (self.timeline and obs.has_sink()):
            return
        end = req.finish_t if at == "finish" else now
        e2e = max(end - req.submit_t, 0.0)
        q = req.phase_s["queue"]
        pf = req.phase_s["prefill"]
        dc = req.phase_s["decode"]
        pe = req.phase_s["preempted"]
        segs = [{k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in s.items()} for s in req.segments]
        fields = {
            "request": req.rid, "at": at,
            "e2e_s": round(e2e, 6),
            "queue_s": round(q, 6),
            "prefill_s": round(pf, 6),
            "decode_s": round(dc, 6),
            "preempted_s": round(pe, 6),
            "overhead_s": round(e2e - (q + pf + dc + pe), 6),
            "tokens": self._generated(req),
            "prompt_len": req.orig_prompt_len,
            "preemptions": req.preemptions,
            "segments": segs,
        }
        if req.ttft_s is not None:
            fields["ttft_s"] = round(req.ttft_s, 6)
        fields.update(self._replica_kw())
        fields.update(self._trace_kw(req))
        if req.group:
            fields["group"] = req.group
        # open-loop riders (ISSUE 16): the arrival stamp lets goodput
        # attribution join pre-submit backlog onto the phase split, and
        # the finish-time verdict lets `obsctl goodput` name the
        # dominant phase of each MISS without a second join pass —
        # absent on closed-loop / target-less requests
        if req.arrival_s is not None:
            fields["arrival_s"] = round(req.arrival_s, 6)
        if at == "finish" and req.slo_met is not None:
            fields["slo_met"] = req.slo_met
            if req.slack_s is not None:
                fields["slack_s"] = req.slack_s
        # admission-policy riders (ISSUE 20) — absent unless the
        # request actually carried a deadline / nonzero priority
        if req.deadline_s is not None:
            fields["deadline_s"] = req.deadline_s
            if at == "finish" and req.deadline_miss is not None:
                fields["deadline_miss"] = req.deadline_miss
        if req.priority:
            fields["priority"] = req.priority
        if req.cow_copies:
            fields["cow_copies"] = req.cow_copies
        if self.prefix_cache:
            fields["prefix_cached_tokens"] = req.prefix_cached_tokens
        # admission-block attribution rides the queue/preempted
        # SEGMENTS (closed by _stamp_admit) — emission here happens
        # only at finish or at the preempt instant, when the request
        # was resident and blocked_iters is necessarily 0
        obs.serve("request_timeline", **fields)

    # -- helpers -------------------------------------------------------------

    def _apply_cow(self, slot) -> None:
        """Apply the admission's queued copy-on-write block copies to
        EVERY pool addressed by the slot's table — the draft's pools
        ride the same block tables as the target's, so both KV address
        spaces must duplicate the privatized blocks."""
        for src, dst in slot.pending_copies:
            self._pools = self._copy_fn(self._pools, np.int32(src),
                                        np.int32(dst))
            if self.speculative:
                self._d_pools = self._copy_fn(self._d_pools,
                                              np.int32(src), np.int32(dst))
        slot.pending_copies = []

    def _spill_block(self, b: int):
        """BlockManager spill hook (ISSUE 17): one block's payload out
        of the live pools — target and draft atomically, int8 scale
        planes included (they are ordinary pool entries in the plan)."""
        return extract_blocks(
            self._pools, [b],
            d_pools=self._d_pools if self.speculative else None)

    def _swap_out(self, slot) -> bool:
        """Scheduler preemption hook (ISSUE 17): try to EXTRACT the
        victim's resident blocks to host instead of recomputing. Runs
        before the scheduler releases the table (extraction copies; the
        release is the same either way), and only ever on committed
        state — the overlap pipeline drained before the capacity phase
        that picked this victim, exactly as for recompute. Returns True
        when the request now carries its ``swap_set`` (the scheduler
        then skips the prompt fold), False to fall back to vLLM
        recompute: policy ``never``/``off``, an ``auto`` estimate that
        favors re-prefill, or a host budget that cannot take the
        reservation."""
        if self.swap in ("off", "never"):
            return False
        req = slot.request
        n = self.blocks.blocks_for(slot.context_len)
        if n <= 0 or n > len(slot.table):
            return False
        est = n * self._host_block_bytes
        if self.swap == "auto":
            # bytes moved (extract now + scatter on re-admit) vs the
            # weight traffic re-prefill streams: params once per chunk
            # dispatch. Contexts long enough that re-prefill re-reads
            # the weights more than the block set costs to round-trip
            # swap; short ones recompute — the vLLM crossover.
            dispatches = -(-slot.context_len // self.sched.prefill_chunk)
            if 2 * est > self._param_bytes * dispatches:
                return False
        if not self.blocks.host_reserve(est):
            return False
        req.swap_set = extract_blocks(
            self._pools, slot.table[:n],
            d_pools=self._d_pools if self.speculative else None)
        actual = req.swap_set.nbytes
        if actual != est:
            # true the reservation up to the payload's real size (the
            # estimate is exact for full pools; belt and braces)
            self.blocks.host_release(est - actual)
        req.swap_context = slot.context_len
        self.swap_outs += 1
        self.swap_bytes_moved += actual
        obs.serve("swap_out", request=req.rid, swap_bytes=actual,
                  **self._replica_kw(), **self._trace_kw(req))
        return True

    def _apply_restores(self, slot) -> None:
        """Apply the admission's queued HOST->DEVICE scatters before
        any dispatch reads the slot's table (the pending-copies timing
        contract): a swapped victim's whole block set, and/or the
        per-block prefix-cache revivals the reservation pulled out of
        the host tier."""
        req = slot.request
        if slot.pending_swap_in is not None:
            bset, slot.pending_swap_in = slot.pending_swap_in, None
            t0 = time.perf_counter()
            self._pools, d = insert_blocks(
                self._pools, bset, slot.table[:bset.n_blocks],
                d_pools=self._d_pools if self.speculative else None,
                donate=self._donate)
            if self.speculative:
                self._d_pools = d
            dt = time.perf_counter() - t0
            if req.rid in self._migrated_in:
                # migration arrival (ISSUE 18): the set came from a
                # SIBLING engine's pools, not this engine's host tier —
                # no reservation to release (host_release here would
                # corrupt the swap budget), and the traffic lands in
                # migration accounting, not the swap tier's
                src_replica = self._migrated_in.pop(req.rid)
                self.migrations_in += 1
                self.migration_bytes += bset.nbytes
                self.migration_restore_s += dt
                kw = {}
                if src_replica is not None:
                    kw["from_replica"] = src_replica
                if self.replica is not None:
                    kw["to_replica"] = self.replica
                kw.update(self._trace_kw(req))
                if req.trace_id and req.migrate_out_t is not None:
                    # the transport hop's full price (ISSUE 19):
                    # source extraction stamp → destination scatter
                    # complete — the sample behind the router's
                    # transport_hop_s_p99 rider; extract_s rides so
                    # the stitcher can split pure data movement out
                    # of the admission wait it telescopes against
                    kw["transport_hop_s"] = round(
                        time.perf_counter() - req.migrate_out_t, 6)
                    kw["extract_s"] = round(req.migrate_extract_s, 6)
                    self.transport_hop_s.append(kw["transport_hop_s"])
                req.migrate_out_t = None
                req.migrate_extract_s = 0.0
                obs.serve("migrate", request=req.rid,
                          migration_bytes=bset.nbytes,
                          restore_s=round(dt, 6), **kw)
            else:
                self.restore_s += dt
                self.blocks.host_release(bset.nbytes)
                self.swap_ins += 1
                self.swap_bytes_moved += bset.nbytes
                self.recompute_tokens_avoided += slot.context_len
                obs.serve("swap_in", request=req.rid,
                          swap_bytes=bset.nbytes, restore_s=round(dt, 6),
                          recompute_tokens_avoided=slot.context_len,
                          **self._replica_kw(), **self._trace_kw(req))
        if slot.pending_restores:
            t0 = time.perf_counter()
            for b, payload in slot.pending_restores:
                self._pools, d = insert_blocks(
                    self._pools, payload, [b],
                    d_pools=self._d_pools if self.speculative else None,
                    donate=self._donate)
                if self.speculative:
                    self._d_pools = d
            self.restore_s += time.perf_counter() - t0
            slot.pending_restores = []

    def _generated(self, req: Request) -> int:
        return (len(req.prompt) - req.orig_prompt_len) + len(req.output)

    def _append(self, slot, token: int) -> None:
        req = slot.request
        req.output.append(token)
        now = time.perf_counter()
        if req.first_token_t is None:
            req.first_token_t = now
            obs.serve("first_token", request=req.rid,
                      ttft_s=round(req.ttft_s, 6)
                      if req.ttft_s is not None else None,
                      **self._replica_kw(), **self._trace_kw(req))
        self.tokens_generated += 1
        if (token == self.eos_token_id
                or self._generated(req) >= req.max_new_tokens):
            req.finish_t = now
            self.sched.finish(slot)
            self.finished[req.rid] = req
            self._keys.pop(req.rid, None)
            extra = {}
            if self.speculative:
                extra = {
                    "speculate_k": self.speculate_k,
                    "draft_proposed": req.spec_proposed,
                    "draft_accepted": req.spec_accepted,
                    "acceptance_rate": (
                        round(req.spec_accepted / req.spec_proposed, 4)
                        if req.spec_proposed else None),
                }
            if self.prefix_cache:
                extra["prefix_cached_tokens"] = req.prefix_cached_tokens
                extra["cache_hit_rate"] = (
                    round(req.cache_hit_rate, 4)
                    if req.cache_hit_rate is not None else None)
            extra["kernel"] = self.kernel
            extra["kv_dtype"] = self.kv_cache_dtype
            extra["tp"] = self.tp
            if req.has_slo:
                extra.update(self._slo_verdict(req))
            if req.deadline_s is not None:
                extra.update(self._deadline_verdict(req))
            obs.serve("finish", request=req.rid,
                      tokens=self._generated(req),
                      preemptions=req.preemptions,
                      **self._replica_kw(), **self._trace_kw(req),
                      **extra)
            self._emit_timeline(req, "finish")

    def _slo_verdict(self, req: Request) -> dict:
        """Write the request's SLO verdicts at finish and return the
        finish-event riders (ISSUE 16). TTFT is measured from the
        ARRIVAL stamp when one was threaded (the open-loop truth — the
        request waited from arrival, not from when the generator got
        around to submitting it), else from the submit stamp. TPOT is
        the steady-state inter-token mean over the post-first-token
        tail. ``slack_s`` is the TIGHTEST remaining margin across the
        set targets — negative exactly on a miss, the quantity a
        capacity planner reads as "how close to the knee"."""
        origin = (req.arrival_s if req.arrival_s is not None
                  else req.submit_t)
        margins = []
        if req.slo_ttft_s is not None:
            ttft = ((req.first_token_t - origin)
                    if req.first_token_t is not None else None)
            req.ttft_slo_met = (ttft is not None
                                and ttft <= req.slo_ttft_s)
            if ttft is not None:
                margins.append(req.slo_ttft_s - ttft)
        if req.slo_tpot_s is not None:
            tokens = self._generated(req)
            tpot = ((req.finish_t - req.first_token_t)
                    / max(tokens - 1, 1)
                    if req.first_token_t is not None else None)
            req.tpot_slo_met = (tpot is not None
                                and tpot <= req.slo_tpot_s)
            if tpot is not None:
                margins.append(req.slo_tpot_s - tpot)
        req.slo_met = (req.ttft_slo_met is not False
                       and req.tpot_slo_met is not False)
        if margins:
            req.slack_s = round(min(margins), 6)
        self._slo_total += 1
        self._slo_met += int(req.slo_met)
        bucket = self._group_slo.setdefault(req.group, [0, 0])
        bucket[0] += int(req.slo_met)
        bucket[1] += 1
        if self._has_priorities:
            # per-priority-class attainment (ISSUE 20): only tracked
            # once any submit named a class, so the rider — and this
            # dict — stays absent on priority-less traffic
            pb = self._priority_slo.setdefault(req.priority, [0, 0])
            pb[0] += int(req.slo_met)
            pb[1] += 1
        out = {"slo_met": req.slo_met}
        if req.ttft_slo_met is not None:
            out["ttft_slo_met"] = req.ttft_slo_met
        if req.tpot_slo_met is not None:
            out["tpot_slo_met"] = req.tpot_slo_met
        if req.slack_s is not None:
            out["slack_s"] = req.slack_s
        return out

    def _deadline_verdict(self, req: Request) -> dict:
        """End-to-end deadline verdict at finish (ISSUE 20): measured
        from the same origin as the SLO verdicts (arrival when
        threaded, else submit), so deadline slack and TTFT share one
        time domain. Feeds ``deadline_miss_frac`` — the figure the
        slo admission policy exists to push down — and the
        ``deadline_miss`` riders on the finish/timeline events."""
        origin = (req.arrival_s if req.arrival_s is not None
                  else req.submit_t)
        req.deadline_miss = bool(
            req.finish_t - origin > req.deadline_s)
        self._deadline_total += 1
        self._deadline_miss += int(req.deadline_miss)
        return {"deadline_miss": req.deadline_miss}
