"""The serving engine: continuous batching over a paged KV cache with
prefill/decode disaggregation.

Architecture (ISSUE 3 tentpole; vLLM + Orca + Sarathi lineage):

- **Paged KV** — one preallocated pool per KV leaf of the model's flax
  ``"cache"`` collection, ``[num_blocks, block_size, heads, head_dim]``.
  Persistent memory scales with blocks actually held (= tokens
  resident), not ``slots × max_model_len``. The jitted steps rebuild
  the model's cache pytree from the pools via
  ``ops.attention.gather_paged_kv`` (block-table gather), run the
  UNMODIFIED model decode path (same ``write_kv_cache`` protocol
  ``models/generate.py`` drives), then scatter the newly-written K/V
  back into the pools. No model code changes: paging is an addressing
  layer around the existing cache contract.
- **Iteration-level scheduling** — a fixed set of ``num_slots`` decode
  slots (static shapes, so after one warmup compile of each step
  function NOTHING retraces); requests admit/evict between decode
  steps (``serve/scheduler.py``).
- **Prefill/decode disaggregation** — prompt ingestion runs as its own
  fixed-width chunked dispatch (one chunk per engine iteration,
  interleaved against in-flight decode), so TTFT and steady decode
  tokens/sec are separately visible host-side and a long prompt never
  stalls running streams for more than one chunk.

Greedy decoding only (the serving throughput story; temperature
sampling stays on the ``models/generate.py`` one-shot paths), and
token-for-token identical to per-request ``generate_causal`` — the
exactness gate ``tests/test_serve.py`` pins.

Telemetry: ``serve`` events (``obs/schema.py``) for request lifecycle
(submit/admit/first_token/finish/preempt), spans around every prefill
and decode dispatch, and pool-utilization metrics.
"""

from __future__ import annotations

import functools
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    gather_paged_kv,
    scatter_paged_kv,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    BlockManager,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    Request,
    Scheduler,
)


class CachePlan(NamedTuple):
    """Static (hashable — it rides jit static_argnames) description of
    the model's flax cache pytree: the treedef plus, per flattened leaf,
    what it is — ``("kv", pool_index)`` for cached_key/cached_value,
    ``("index",)`` for the per-row write indices, ``("scalar",)`` for
    model-level counters (unused under explicit position_ids)."""

    treedef: Any
    kinds: tuple


# (model, max_ctx) -> (plan, pool_shapes): the cache structure is a
# function of the model config + width, so engine rebuilds (bench's
# measured pass, server restarts) skip the eval_shape re-trace
_PLAN_CACHE: dict = {}


def build_cache_plan(model, params, max_ctx: int) -> tuple[CachePlan, list]:
    """(plan, pool_shapes): traverse the cache collection's SHAPE (via
    ``jax.eval_shape`` — nothing is allocated) for a batch-1 decode at
    width ``max_ctx`` and classify every leaf. ``pool_shapes`` is one
    ``(heads, head_dim, dtype)`` per KV leaf in flatten order."""
    key = (model, max_ctx)
    cached = _PLAN_CACHE.get(key)
    if cached is not None:
        return cached

    def init_cache(p):
        _, variables = model.apply(
            {"params": p}, jnp.ones((1, max_ctx), jnp.int32), decode=True,
            deterministic=True, mutable=["cache"])
        return variables["cache"]

    shapes = jax.eval_shape(init_cache, params)
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    kinds, pool_shapes = [], []
    for path, leaf in flat:
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            b, h, s, d = leaf.shape
            if s != max_ctx:
                raise ValueError(
                    f"cache leaf {name} has kv width {s}, expected "
                    f"{max_ctx} — non-slot-indexed cache layouts "
                    "(e.g. T5 encoder-decoder) are not serveable here")
            kinds.append(("kv", len(pool_shapes)))
            pool_shapes.append((h, d, leaf.dtype))
        elif name == "cache_index":
            kinds.append(("index",))
        elif name == "position_index":
            kinds.append(("scalar",))
        else:
            raise ValueError(
                f"unsupported cache leaf {name!r}: the serve engine "
                "speaks the fp cached_key/cached_value protocol only "
                "(set kv_cache_dtype='fp')")
    result = CachePlan(treedef, tuple(kinds)), pool_shapes
    _PLAN_CACHE[key] = result
    return result


def _assemble_cache(plan: CachePlan, pools, block_tables, context_lens):
    """The model-facing cache pytree: contiguous per-slot KV gathered
    from the pools, write indices set to each slot's context length."""
    leaves = []
    for kind in plan.kinds:
        if kind[0] == "kv":
            leaves.append(gather_paged_kv(pools[kind[1]], block_tables))
        elif kind[0] == "index":
            leaves.append(context_lens.astype(jnp.int32))
        else:
            leaves.append(jnp.zeros((), jnp.int32))
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _decode_step(model, params, pools, tokens, block_tables, context_lens,
                 active, plan: CachePlan):
    """One decode iteration over ALL slots (static [S] shapes): feed
    each slot's last token, write its K/V at ``context_len`` (scattered
    back to the pools; inactive slots write the reserved null block 0),
    return the greedy next token per slot."""
    S = tokens.shape[0]
    bs = pools[0].shape[1]
    max_ctx = block_tables.shape[1] * bs
    cache = _assemble_cache(plan, pools, block_tables, context_lens)
    # kv-buffer validity includes the slot being written this step —
    # exactly generate_causal's decode-step mask
    valid = (jnp.arange(max_ctx)[None, :]
             <= context_lens[:, None]).astype(jnp.int32)
    logits, mut = model.apply(
        {"params": params, "cache": cache}, tokens[:, None], valid,
        position_ids=context_lens[:, None], decode=True,
        deterministic=True, mutable=["cache"])
    next_tok = jnp.argmax(logits[:, -1, :].astype(jnp.float32),
                          axis=-1).astype(jnp.int32)
    # scatter the step's writes back; inactive slots route to the null
    # block so the scatter itself needs no masking
    safe_tables = jnp.where(active[:, None], block_tables, 0)
    pos = jnp.where(active, context_lens, 0)
    mut_leaves = jax.tree_util.tree_leaves(mut["cache"])
    new_pools = list(pools)
    for leaf, kind in zip(mut_leaves, plan.kinds):
        if kind[0] != "kv":
            continue
        written = jnp.take_along_axis(
            leaf, pos[:, None, None, None], axis=2)[:, :, 0, :]  # [S, H, D]
        new_pools[kind[1]] = scatter_paged_kv(
            new_pools[kind[1]], safe_tables, pos, written)
    return next_tok, new_pools


def _prefill_chunk(model, params, pools, chunk, block_tables, start, rel,
                   plan: CachePlan):
    """One fixed-width prefill chunk for ONE request (batch 1): write
    the chunk's K/V into the request's blocks starting at ``start``,
    and return the greedy token after the prompt position ``rel``
    (chunk-relative index of the last REAL prompt token; meaningful on
    the final chunk only — earlier chunks return a discarded value)."""
    C = chunk.shape[1]
    bs = pools[0].shape[1]
    max_ctx = block_tables.shape[1] * bs
    cache = _assemble_cache(plan, pools, block_tables, start)
    # chunk slots are marked valid; the model's step mask
    # (key_slot <= cache_index + q_index) imposes causality within the
    # chunk, and pad-tail keys sit AFTER every real query so they are
    # never attended. Pad-tail writes land in block space the scheduler
    # trims back after the final chunk.
    valid = (jnp.arange(max_ctx)[None, :]
             < start[:, None] + C).astype(jnp.int32)
    pos_ids = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    logits, mut = model.apply(
        {"params": params, "cache": cache}, chunk, valid,
        position_ids=pos_ids, decode=True, deterministic=True,
        mutable=["cache"])
    sel = jnp.take_along_axis(
        logits.astype(jnp.float32),
        jnp.clip(rel, 0, C - 1)[:, None, None], axis=1)[:, 0]  # [1, V]
    next_tok = jnp.argmax(sel, axis=-1).astype(jnp.int32)      # [1]
    start0 = start[0]
    positions = start0 + jnp.arange(C, dtype=jnp.int32)
    tables_c = jnp.broadcast_to(block_tables, (C, block_tables.shape[1]))
    mut_leaves = jax.tree_util.tree_leaves(mut["cache"])
    new_pools = list(pools)
    for leaf, kind in zip(mut_leaves, plan.kinds):
        if kind[0] != "kv":
            continue
        h, d = leaf.shape[1], leaf.shape[3]
        written = lax.dynamic_slice(
            leaf, (0, 0, start0, 0), (1, h, C, d))[0].transpose(1, 0, 2)
        new_pools[kind[1]] = scatter_paged_kv(
            new_pools[kind[1]], tables_c, positions, written)
    return next_tok, new_pools


@functools.lru_cache(maxsize=2)
def _decode_step_jit(donate: bool):
    """Process-wide jitted decode step (one per donation mode). ``plan``
    and ``model`` are static; pools are donated on accelerator backends
    so the scatter updates them in place (CPU has no donation and would
    warn every call)."""
    return jax.jit(_decode_step, static_argnums=(0, 7),
                   donate_argnums=(2,) if donate else ())


@functools.lru_cache(maxsize=2)
def _prefill_chunk_jit(donate: bool):
    return jax.jit(_prefill_chunk, static_argnums=(0, 7),
                   donate_argnums=(2,) if donate else ())


class EngineStats(NamedTuple):
    decode_steps: int
    prefill_chunks: int
    tokens_generated: int
    preemptions: int
    kv_peak_utilization: float
    kv_utilization: float


class ServeEngine:
    """Continuous-batching engine for the decoder-only families that
    follow the slot-indexed KV-cache protocol (GPT-2, dense Llama).

    ``num_blocks`` includes the reserved null block: allocatable KV is
    ``(num_blocks - 1) * block_size`` tokens, shared by every request —
    size it for the expected CONCURRENT context, not
    ``num_slots × max_model_len``.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 block_size: int = 16, num_blocks: int = 129,
                 prefill_chunk: int = 16,
                 max_model_len: Optional[int] = None):
        cfg = model.config
        if getattr(cfg, "num_experts", 0):
            raise ValueError(
                "ServeEngine does not support MoE models: expert "
                "capacity depends on the apply's sequence length, so "
                "chunked prefill could drop token->expert assignments "
                "the one-shot path never drops")
        if getattr(cfg, "kv_cache_dtype", "fp") != "fp":
            raise ValueError("ServeEngine requires kv_cache_dtype='fp' "
                             "(paged int8 scales are not wired)")
        if getattr(cfg, "sliding_window", None) is not None:
            raise ValueError("ServeEngine does not support sliding-"
                             "window configs (windowed block eviction "
                             "is not implemented)")
        if getattr(cfg, "pipeline_stages", 0):
            raise ValueError("ServeEngine needs the dense stack "
                             "(pipeline_stages=0)")
        self.model, self.params = model, params
        self.eos_token_id = int(cfg.eos_token_id)
        self.pad_token_id = min(int(cfg.pad_token_id), cfg.vocab_size - 1)
        if max_model_len is None:
            max_model_len = (cfg.max_position_embeddings
                             // block_size) * block_size
        self.max_model_len = int(max_model_len)
        max_pos = getattr(cfg, "max_position_embeddings", None)
        if max_pos is not None and self.max_model_len > max_pos:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the "
                f"model's max_position_embeddings {max_pos}")
        self.num_slots = int(num_slots)
        self.blocks = BlockManager(num_blocks, block_size)
        self.sched = Scheduler(num_slots, self.blocks, prefill_chunk,
                               self.max_model_len)
        self.max_blocks_per_seq = self.max_model_len // block_size

        plan, pool_shapes = build_cache_plan(model, params,
                                             self.max_model_len)
        self._plan = plan
        self._pools = [jnp.zeros((num_blocks, block_size, h, d), dtype)
                       for h, d, dtype in pool_shapes]
        # the jitted step functions are MODULE-level and keyed on
        # (model, plan) static args: a second engine over the same
        # model/geometry — the bench's measured pass, a restarted
        # server — reuses the compiled executables instead of retracing
        donate = jax.default_backend() != "cpu"
        self._decode_fn = _decode_step_jit(donate)
        self._prefill_fn = _prefill_chunk_jit(donate)
        self.finished: dict[int, Request] = {}
        self.decode_steps = 0
        self.prefill_chunks = 0
        self.tokens_generated = 0
        self.iterations = 0
        self.peak_waiting = 0
        self._warm = False

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> Request:
        req = Request(prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new_tokens))
        req.submit_t = time.perf_counter()
        self.sched.submit(req)
        obs.serve("submit", request=req.rid,
                  prompt_len=len(req.prompt),
                  max_new_tokens=req.max_new_tokens)
        return req

    def output_ids(self, req: Request) -> np.ndarray:
        """Generated ids (preemption-folded tokens included)."""
        folded = req.prompt[req.orig_prompt_len:]
        return np.concatenate(
            [folded, np.asarray(req.output, np.int32)]).astype(np.int32)

    def warmup(self) -> None:
        """Compile both step functions on null work so the serving loop
        itself never traces: the compile-tracker event count is FLAT
        across steady state (the bench asserts it)."""
        if self._warm:
            return
        with obs.span("serve/warmup"):
            C = self.sched.prefill_chunk
            nb = self.max_blocks_per_seq
            zero_tables1 = np.zeros((1, nb), np.int32)
            tok, self._pools = self._prefill_fn(
                self.model, self.params, self._pools,
                np.zeros((1, C), np.int32), zero_tables1,
                np.zeros((1,), np.int32), np.full((1,), -1, np.int32),
                self._plan)
            S = self.num_slots
            tok, self._pools = self._decode_fn(
                self.model, self.params, self._pools,
                np.zeros((S,), np.int32), np.zeros((S, nb), np.int32),
                np.zeros((S,), np.int32), np.zeros((S,), bool),
                self._plan)
            jax.block_until_ready(tok)
        self._warm = True

    def run(self) -> dict[int, Request]:
        """Drive the loop until every submitted request finishes;
        returns {rid: Request}. Ends with one ``serve`` *report* event
        carrying the run's SLO summary (TTFT / end-to-end latency
        percentiles) so the cross-host report (`obs/report.py`) reads
        the serving story from a single line."""
        self.warmup()
        with obs.span("serve/run"):
            while self.sched.has_work():
                self.step()
        obs.scalar("serve/kv_peak_utilization",
                   self.blocks.peak_used / max(self.blocks.num_blocks - 1, 1))
        summary = self.slo_summary()
        if summary:
            obs.serve("report", **summary)
        return self.finished

    def slo_summary(self) -> dict:
        """TTFT / end-to-end latency percentiles + scheduler gauges over
        every FINISHED request ({} until one finishes)."""
        reqs = list(self.finished.values())
        if not reqs:
            return {}
        ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
        e2es = [r.finish_t - r.submit_t for r in reqs
                if r.finish_t is not None and r.submit_t is not None]
        out = {
            "requests": len(reqs),
            "tokens": self.tokens_generated,
            "iterations": self.iterations,
            "preemptions": self.sched.n_preemptions,
            "peak_waiting_depth": self.peak_waiting,
            "kv_peak_utilization": round(
                self.blocks.peak_used
                / max(self.blocks.num_blocks - 1, 1), 4),
        }
        from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
            percentile,
        )

        for label, vals in (("ttft", ttfts), ("e2e", e2es)):
            if not vals:
                continue
            s = sorted(vals)
            out[f"{label}_p50_s"] = round(percentile(s, 0.50), 6)
            out[f"{label}_p95_s"] = round(percentile(s, 0.95), 6)
            out[f"{label}_p99_s"] = round(percentile(s, 0.99), 6)
        return out

    def stats(self) -> EngineStats:
        return EngineStats(
            decode_steps=self.decode_steps,
            prefill_chunks=self.prefill_chunks,
            tokens_generated=self.tokens_generated,
            preemptions=self.sched.n_preemptions,
            kv_peak_utilization=self.blocks.peak_used
            / max(self.blocks.num_blocks - 1, 1),
            kv_utilization=self.blocks.utilization())

    # -- one engine iteration ------------------------------------------------

    def step(self) -> None:
        """Admit → prefill chunks → one decode step over all slots.

        The prefill budget is adaptive (Sarathi-flavored): with a full
        decode batch only ONE chunk runs per iteration (bounding the
        decode stall a long prompt can inject), but every idle decode
        slot buys one more chunk — refilling drained slots fast is
        worth more than the stall when the batch is running light."""
        for slot in self.sched.admit():
            obs.serve("admit", request=slot.request.rid, slot=slot.index,
                      queue_depth=len(self.sched.waiting))
        budget = max(1, self.num_slots - len(self.sched.decode_slots()))
        for _ in range(budget):
            if not self._prefill_one():
                break
        for req in self.sched.ensure_decode_capacity():
            obs.serve("preempt", request=req.rid,
                      reason="kv_pool_exhausted")
        self._decode_all()
        # per-iteration scheduler gauges (SLO telemetry): queue pressure
        # and slot occupancy as series, one sample per engine iteration
        waiting = len(self.sched.waiting)
        self.peak_waiting = max(self.peak_waiting, waiting)
        if obs.has_sink():
            obs.scalar("serve/waiting_depth", waiting, self.iterations)
            obs.scalar("serve/running_slots",
                       len(self.sched.decode_slots()), self.iterations)
            obs.scalar("serve/preemptions", self.sched.n_preemptions,
                       self.iterations)
        self.iterations += 1

    def _prefill_one(self) -> bool:
        """One prefill chunk for the next PREFILL-state slot
        (round-robin); False when no prefill work exists."""
        slot = self.sched.next_prefill_slot()
        if slot is None:
            return False
        req = slot.request
        C = self.sched.prefill_chunk
        padded = self.sched.padded_prompt_len(req)
        pos = slot.prefill_pos
        chunk = np.full((1, C), self.pad_token_id, np.int32)
        real = req.prompt[pos:pos + C]
        chunk[0, :len(real)] = real
        final = pos + C >= padded
        rel = (len(req.prompt) - 1) - pos if final else -1
        table = self._slot_table(slot)
        with obs.span("serve/prefill_chunk"):
            tok, self._pools = self._prefill_fn(
                self.model, self.params, self._pools, chunk, table,
                np.asarray([pos], np.int32), np.asarray([rel], np.int32),
                self._plan)
        slot.prefill_pos += C
        self.prefill_chunks += 1
        if final:
            self.sched.finish_prefill(slot)
            # fetch the sampled continuation token; also the sync point
            # that makes TTFT an honest end-to-end wall time
            self._append(slot, int(jax.device_get(tok)[0]))
        return True

    def _decode_all(self) -> None:
        ds = self.sched.decode_slots()
        if not ds:
            return
        S = self.num_slots
        tokens = np.zeros((S,), np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), np.int32)
        ctx = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for slot in ds:
            tokens[slot.index] = slot.request.output[-1]
            tables[slot.index] = self._slot_table(slot)[0]
            ctx[slot.index] = slot.context_len
            active[slot.index] = True
        with obs.span("serve/decode_step",
                      {"active": len(ds)} if obs.has_sink() else None):
            nxt, self._pools = self._decode_fn(
                self.model, self.params, self._pools, tokens, tables,
                ctx, active, self._plan)
        nxt = np.asarray(jax.device_get(nxt))
        self.decode_steps += 1
        for slot in ds:
            slot.context_len += 1        # the fed token's K/V landed
            self._append(slot, int(nxt[slot.index]))

    # -- helpers -------------------------------------------------------------

    def _slot_table(self, slot) -> np.ndarray:
        out = np.zeros((1, self.max_blocks_per_seq), np.int32)
        out[0, :len(slot.table)] = slot.table
        return out

    def _generated(self, req: Request) -> int:
        return (len(req.prompt) - req.orig_prompt_len) + len(req.output)

    def _append(self, slot, token: int) -> None:
        req = slot.request
        req.output.append(token)
        now = time.perf_counter()
        if req.first_token_t is None:
            req.first_token_t = now
            obs.serve("first_token", request=req.rid,
                      ttft_s=round(req.ttft_s, 6)
                      if req.ttft_s is not None else None)
        self.tokens_generated += 1
        if (token == self.eos_token_id
                or self._generated(req) >= req.max_new_tokens):
            req.finish_t = now
            self.sched.finish(slot)
            self.finished[req.rid] = req
            obs.serve("finish", request=req.rid,
                      tokens=self._generated(req),
                      preemptions=req.preemptions)
