"""Open-loop load generation + deadline-aware driving (ISSUE 16).

Every serving number the repo produced before this module came from a
CLOSED loop: submit a fixed trace, run to completion. A closed loop
self-throttles — the engine's own backpressure slows the offered load —
so it structurally cannot exhibit queueing collapse, and "requests/sec
at an SLO" has no honest denominator. This module is the open-loop
half: requests arrive on a SCHEDULE that does not care how busy the
engine is, each carries a deadline (:class:`SloSpec`), and the driver
measures the DistServe goodput question — what fraction of arrivals
met their TTFT/TPOT targets at this arrival rate.

Three layers, in the house determinism style:

- **Arrival processes** — seeded stdlib-``random`` generators
  (:func:`poisson_arrivals`, :func:`bursty_arrivals` — a two-state
  Markov-modulated Poisson process whose bursts are what actually
  breaks p99s in production traces) plus bounded-Pareto
  :func:`heavy_tailed_lengths` for prompt/output sizing.
  :func:`make_schedule` composes them into ``(arrival_s,
  request_spec)`` rows — pure functions of their seeds, so every
  schedule is replayable byte-for-byte.
- **:class:`OpenLoopDriver`** — submits a schedule through a
  :class:`~.router.Router` or a bare :class:`~.engine.ServeEngine` in
  one of two clock modes. ``virtual`` interleaves arrivals with engine
  iterations on a deterministic virtual clock (``tick_s`` of virtual
  time per fleet step): token streams, backlog integers, and the
  driver's own attainment/miss-attribution accounting are exact across
  reruns — what the tier-1 gates and bench line run on a shared CPU.
  ``wall`` honors arrival times with real sleeps and threads
  ``arrival_s``/``slo`` into :meth:`~.engine.ServeEngine.submit`, so
  the engine stamps real verdicts into the telemetry stream — the mode
  ``obsctl goodput`` replays, banked for hardware.
- **Knob parsing** — ``--arrival poisson:2.0 | bursty:4,0.5,0.25 |
  closed`` (:func:`parse_arrival`, env ``HSTD_SERVE_ARRIVAL`` +
  ``HSTD_SERVE_ARRIVAL_SEED``) and ``--slo ttft:0.5,tpot:0.05``
  (:func:`parse_slo`, env ``HSTD_SERVE_SLO_TTFT_S`` /
  ``HSTD_SERVE_SLO_TPOT_S``), mirrored by ``scripts/serve.py``.

The driver stamps each run with ONE ``serve`` ``open_loop`` event
(process / rate / clock / request count / targets) so a downstream
``obsctl goodput`` replay can split a rate sweep's merged stream back
into its runs.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    WAITING,
    Request,
)

ENV_ARRIVAL = "HSTD_SERVE_ARRIVAL"
ENV_ARRIVAL_SEED = "HSTD_SERVE_ARRIVAL_SEED"
ENV_SLO_TTFT = "HSTD_SERVE_SLO_TTFT_S"
ENV_SLO_TPOT = "HSTD_SERVE_SLO_TPOT_S"

PROCESSES = ("poisson", "bursty")
CLOCKS = ("virtual", "wall")

# driver miss-attribution phases, coarser than the PR 10 five-way split
# on purpose: the virtual clock can only observe SCHEDULER transitions
# (arrival -> admit -> first token -> finish), and queue-vs-service is
# the decision boundary capacity planning acts on. Order is the
# tie-break (earlier phase wins a tie, matching obs.timeline).
MISS_PHASES = ("queue", "prefill", "decode")


@dataclass(frozen=True)
class SloSpec:
    """Per-request deadline targets, in seconds (None = no target on
    that axis; at least one must be set). ``ttft_s`` bounds time to
    first token FROM ARRIVAL; ``tpot_s`` bounds the mean inter-token
    time over the post-first-token tail. Duck-typed by
    :meth:`~.engine.ServeEngine.submit` (the engine never imports this
    module), frozen so a single spec can be shared across a whole
    schedule."""

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None

    def __post_init__(self):
        if self.ttft_s is None and self.tpot_s is None:
            raise ValueError("SloSpec needs at least one target "
                             "(ttft_s and/or tpot_s)")
        for name in ("ttft_s", "tpot_s"):
            v = getattr(self, name)
            if v is not None and not float(v) > 0:
                raise ValueError(f"SloSpec.{name} must be > 0, got {v!r}")


# -- knob parsing ------------------------------------------------------------


def parse_arrival(spec=None):
    """The arrival-process knob: ``closed`` (None — the pre-open-loop
    submit-everything trace), ``poisson:RATE`` (requests/sec), or
    ``bursty:RATE_HI,RATE_LO,P_SWITCH`` (two-state Markov-modulated
    Poisson: gaps draw at the current state's rate, the state flips
    with probability ``p_switch`` after each arrival). None reads
    ``HSTD_SERVE_ARRIVAL`` (default ``closed``). Returns None or
    ``(process, params_dict)``."""
    if spec is None:
        spec = os.environ.get(ENV_ARRIVAL, "closed") or "closed"
    s = str(spec).strip().lower()
    if s in ("", "closed"):
        return None
    name, _, argstr = s.partition(":")
    try:
        if name == "poisson":
            rate = float(argstr)
            if not rate > 0:
                raise ValueError
            return ("poisson", {"rate": rate})
        if name == "bursty":
            hi, lo, p = (float(x) for x in argstr.split(","))
            if not (hi > 0 and lo > 0 and 0 <= p <= 1):
                raise ValueError
            return ("bursty", {"rate_hi": hi, "rate_lo": lo,
                               "p_switch": p})
    except ValueError:
        pass
    raise ValueError(
        f"unparseable {ENV_ARRIVAL} value {spec!r}: expected "
        "closed | poisson:RATE | bursty:RATE_HI,RATE_LO,P_SWITCH")


def parse_arrival_seed(spec=None) -> int:
    """The schedule seed knob: any int. None reads
    ``HSTD_SERVE_ARRIVAL_SEED`` (default 0)."""
    if spec is None:
        spec = os.environ.get(ENV_ARRIVAL_SEED, "0") or "0"
    try:
        return int(str(spec).strip() or "0")
    except ValueError:
        raise ValueError(f"unparseable {ENV_ARRIVAL_SEED} value "
                         f"{spec!r}: expected an integer")


def parse_slo(spec=None) -> Optional[SloSpec]:
    """The deadline knob: ``ttft:SECS[,tpot:SECS]`` in either order,
    or ``none``. None reads ``HSTD_SERVE_SLO_TTFT_S`` /
    ``HSTD_SERVE_SLO_TPOT_S`` (both unset = no SLO — every new
    telemetry field stays absent, the byte-identity contract)."""
    if spec is None:
        ttft = os.environ.get(ENV_SLO_TTFT, "") or None
        tpot = os.environ.get(ENV_SLO_TPOT, "") or None
        if ttft is None and tpot is None:
            return None
        try:
            return SloSpec(
                ttft_s=float(ttft) if ttft is not None else None,
                tpot_s=float(tpot) if tpot is not None else None)
        except ValueError as e:
            raise ValueError(f"unparseable {ENV_SLO_TTFT}/"
                             f"{ENV_SLO_TPOT} values: {e}")
    s = str(spec).strip().lower()
    if s in ("", "none"):
        return None
    kw = {}
    try:
        for part in s.split(","):
            axis, _, val = part.partition(":")
            axis = axis.strip()
            if axis not in ("ttft", "tpot") or f"{axis}_s" in kw:
                raise ValueError
            kw[f"{axis}_s"] = float(val)
        return SloSpec(**kw)
    except ValueError:
        raise ValueError(f"unparseable SLO spec {spec!r}: expected "
                         "ttft:SECS[,tpot:SECS] | none")


# -- arrival processes + length sampling -------------------------------------


def poisson_arrivals(rate: float, n: int, seed: int = 0) -> list:
    """``n`` arrival offsets (seconds from schedule start) with
    exponential inter-arrival gaps at ``rate`` requests/sec — a pure
    function of ``(rate, n, seed)``."""
    if not rate > 0:
        raise ValueError(f"rate must be > 0, got {rate!r}")
    rng = random.Random(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def bursty_arrivals(rate_hi: float, rate_lo: float, p_switch: float,
                    n: int, seed: int = 0) -> list:
    """Two-state Markov-modulated Poisson arrivals: each gap draws at
    the current state's rate (starting hot), and the state flips with
    probability ``p_switch`` after every arrival — mean burst length
    ``1/p_switch`` requests. The burst/lull alternation is what drives
    transient backlogs (and p99 TTFT) that a rate-matched plain
    Poisson stream never shows."""
    if not (rate_hi > 0 and rate_lo > 0):
        raise ValueError("rates must be > 0")
    if not 0 <= p_switch <= 1:
        raise ValueError(f"p_switch must be in [0, 1], got {p_switch!r}")
    rng = random.Random(seed)
    hot, t, out = True, 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate_hi if hot else rate_lo)
        out.append(t)
        if rng.random() < p_switch:
            hot = not hot
    return out


def heavy_tailed_lengths(n: int, lo: int, hi: int, seed: int = 0,
                         alpha: float = 1.5) -> list:
    """``n`` bounded-Pareto(``alpha``) lengths in ``[lo, hi]``: mass
    near ``lo`` with an occasional near-``hi`` outlier — the
    production-trace shape (most prompts short, a few huge) whose
    stragglers dominate queueing behavior. Smaller ``alpha`` =
    heavier tail."""
    if not 0 < lo <= hi:
        raise ValueError(f"need 0 < lo <= hi, got ({lo}, {hi})")
    if not alpha > 0:
        raise ValueError(f"alpha must be > 0, got {alpha!r}")
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        u = max(rng.random(), 1e-12)
        out.append(int(min(hi, max(lo, round(lo / u ** (1.0 / alpha))))))
    return out


def make_schedule(n_requests: int, vocab_size: int, *,
                  process: str = "poisson", rate: float = 1.0,
                  rate_lo: Optional[float] = None,
                  p_switch: float = 0.1, seed: int = 0,
                  prompt_lo: int = 4, prompt_hi: int = 32,
                  new_lo: int = 4, new_hi: int = 32,
                  alpha: float = 1.5,
                  eos_token_id: Optional[int] = None,
                  groups: Sequence[str] = (),
                  deadline_s: Optional[float] = None,
                  priorities: Sequence[int] = ()) -> list:
    """Compose an arrival process with heavy-tailed prompt/output
    lengths into ``[(arrival_s, spec), ...]`` sorted by arrival, where
    each spec is ``{"prompt": [ids], "max_new_tokens": n, "group":
    tag?}`` — exactly the keys :meth:`OpenLoopDriver.run` forwards to
    ``submit``. Prompts avoid ``eos_token_id``; ``groups`` (tenants)
    round-robin over arrivals, as do ``priorities`` (ISSUE 20
    admission classes, smaller = more urgent); ``deadline_s`` stamps
    rows with an end-to-end deadline — a scalar stamps every row, a
    sequence round-robins aligned with ``priorities``/``groups`` (the
    per-class-deadline shape the admission bench drives). Pure in ``seed``: the
    same call is the same schedule, which is what the replay-identity
    gates rest on."""
    if process == "poisson":
        arrivals = poisson_arrivals(rate, n_requests, seed)
    elif process == "bursty":
        arrivals = bursty_arrivals(
            rate, rate_lo if rate_lo is not None else rate / 4.0,
            p_switch, n_requests, seed)
    else:
        raise ValueError(f"unknown arrival process {process!r}: "
                         f"expected {' | '.join(PROCESSES)}")
    rng = random.Random(f"{seed}:lengths")
    plens = heavy_tailed_lengths(n_requests, prompt_lo, prompt_hi,
                                 seed=rng.randrange(1 << 30), alpha=alpha)
    nlens = heavy_tailed_lengths(n_requests, new_lo, new_hi,
                                 seed=rng.randrange(1 << 30), alpha=alpha)
    tok_rng = random.Random(f"{seed}:tokens")
    out = []
    for i, arrival in enumerate(arrivals):
        prompt = []
        while len(prompt) < plens[i]:
            tok = tok_rng.randrange(vocab_size)
            if tok != eos_token_id:
                prompt.append(tok)
        spec = {"prompt": prompt, "max_new_tokens": nlens[i]}
        if groups:
            spec["group"] = groups[i % len(groups)]
        if deadline_s is not None:
            spec["deadline_s"] = float(
                deadline_s[i % len(deadline_s)]
                if isinstance(deadline_s, (list, tuple)) else deadline_s)
        if priorities:
            spec["priority"] = int(priorities[i % len(priorities)])
        out.append((arrival, spec))
    return out


# -- the driver --------------------------------------------------------------

_SPEC_KEYS = ("temperature", "top_k", "top_p", "seed", "group",
              "deadline_s", "priority")


class OpenLoopDriver:
    """Submit a ``[(arrival_s, spec), ...]`` schedule through a target
    (:class:`~.router.Router` or bare :class:`~.engine.ServeEngine` —
    anything with ``submit/step/has_work/warmup/run``) honoring arrival
    times, then drain.

    ``clock="virtual"``: arrivals interleave with engine iterations on
    a driver-owned virtual clock — each fleet step advances it by
    ``tick_s`` virtual seconds, idle time jumps to the next arrival —
    and the driver polls scheduler transitions after every step to
    stamp virtual admit/first-token/finish times. All accounting
    (:meth:`summary`: attainment, per-group split, per-phase miss
    attribution) is then a pure function of (schedule, tokens,
    iteration count): deterministic on a noisy shared CPU, which is
    what lets tier-1 gates assert exact figures. The SLO spec is NOT
    forwarded to the engine in this mode — wall-domain verdicts would
    be nondeterministic booleans in the event stream — but
    ``arrival_s`` is, so the deterministic ``arrival_backlog`` ledger
    rider and backlog peak still appear.

    ``clock="wall"``: real sleeps to each arrival, ``arrival_s`` AND
    ``slo`` threaded into ``submit`` — the engine stamps real verdicts
    into finish events and its report carries real attainment; the
    stream ``obsctl goodput`` replays. Warmup runs BEFORE the clock
    starts in both modes so compile time never lands in a TTFT.
    """

    def __init__(self, target, schedule, *, clock: str = "virtual",
                 tick_s: float = 0.001, slo: Optional[SloSpec] = None,
                 process: str = "custom", rate: Optional[float] = None):
        if clock not in CLOCKS:
            raise ValueError(f"unknown clock {clock!r}: expected "
                             f"{' | '.join(CLOCKS)}")
        if not tick_s > 0:
            raise ValueError(f"tick_s must be > 0, got {tick_s!r}")
        self.target = target
        # stable sort: simultaneous arrivals keep schedule order
        self.schedule = sorted(schedule, key=lambda row: row[0])
        self.clock = clock
        self.tick_s = float(tick_s)
        self.slo = slo
        self.process = str(process)
        self.rate = rate
        self._recs: list[dict] = []
        self._ran = False

    # -- submission ----------------------------------------------------------

    def _submit(self, arrival: float, spec: dict, t0: float):
        kw = {k: spec[k] for k in _SPEC_KEYS if k in spec}
        req = self.target.submit(
            spec["prompt"], spec["max_new_tokens"],
            arrival_s=t0 + arrival,
            slo=self.slo if self.clock == "wall" else None, **kw)
        if getattr(req, "rejected", False):
            # structured rate-limit rejection (ISSUE 20): recorded —
            # never a silent drop — but excluded from service-time
            # accounting, because the request was refused, not served
            self._recs.append({"arrival": arrival,
                               "group": spec.get("group", ""),
                               "rejected": True})
            return req
        rec = {"arrival": arrival, "req": req,
               "group": spec.get("group", "")}
        if "deadline_s" in spec:
            rec["deadline_s"] = float(spec["deadline_s"])
        self._recs.append(rec)
        return req

    # -- clock loops ---------------------------------------------------------

    def _poll(self, vt: float) -> None:
        """Stamp virtual times for every scheduler transition since the
        last step: queue->resident (admit), first emitted token,
        finish. A request that crossed several transitions within one
        iteration stamps them all at this tick — per-iteration
        granularity is the virtual clock's resolution."""
        for rec in self._recs:
            if "v_finish" in rec or "req" not in rec:
                continue
            req = rec["req"]
            if "v_admit" not in rec and req.state != WAITING:
                rec["v_admit"] = vt
            if "v_first" not in rec and req.first_token_t is not None:
                rec["v_first"] = vt
            if req.finish_t is not None:
                rec["v_finish"] = vt

    def _set_policy_clock(self, now: float) -> None:
        """Pin every scheduler's admission-policy clock to the virtual
        timeline (``t0 + vt``, the same domain ``arrival_s`` is stamped
        in) so aging promotions under ``policy="slo"`` are a pure
        function of the schedule — deterministic on a noisy host. Wall
        mode leaves the clock unpinned (``perf_counter`` truth)."""
        for eng in getattr(self.target, "engines", None) or [self.target]:
            sched = getattr(eng, "sched", None)
            if sched is not None:
                sched.policy_now = now

    def _run_virtual(self, t0: float) -> None:
        idx, vt = 0, 0.0
        while idx < len(self.schedule) or self.target.has_work():
            if (idx < len(self.schedule) and not self.target.has_work()
                    and vt < self.schedule[idx][0]):
                # idle: jump straight to the next arrival — virtual
                # time never burns host iterations on an empty fleet
                vt = self.schedule[idx][0]
            while (idx < len(self.schedule)
                   and self.schedule[idx][0] <= vt):
                arrival, spec = self.schedule[idx]
                idx += 1
                self._submit(arrival, spec, t0)
            if self.target.has_work():
                self._set_policy_clock(t0 + vt)
                self.target.step()
                vt += self.tick_s
                self._poll(vt)

    def _run_wall(self, t0: float) -> None:
        idx = 0
        while idx < len(self.schedule):
            now = time.perf_counter() - t0
            arrival, spec = self.schedule[idx]
            if arrival <= now:
                self._submit(arrival, spec, t0)
                idx += 1
            elif self.target.has_work():
                # serve resident work while the next arrival is in the
                # future — the open-loop property: waiting for work to
                # drain never delays an arrival, but an idle engine
                # never spins either
                self.target.step()
            else:
                time.sleep(min(arrival - now, 0.05))

    def run(self) -> dict:
        """Drive the schedule to completion; returns the target's
        merged ``{rid: Request}``. Emits one ``open_loop`` stamp event
        up front, then the target's own ``run()`` drains the tail and
        emits the report event (which carries attainment/backlog when
        the run threaded targets/arrivals)."""
        if self._ran:
            raise RuntimeError("OpenLoopDriver.run() is one-shot: "
                               "build a fresh driver per run")
        self._ran = True
        extra = {}
        if self.rate is not None:
            extra["rate"] = float(self.rate)
        if self.slo is not None:
            if self.slo.ttft_s is not None:
                extra["slo_ttft_s"] = float(self.slo.ttft_s)
            if self.slo.tpot_s is not None:
                extra["slo_tpot_s"] = float(self.slo.tpot_s)
        obs.serve("open_loop", process=self.process, clock=self.clock,
                  requests=len(self.schedule), **extra)
        sampled = any(spec.get("temperature", 0) > 0
                      for _, spec in self.schedule)
        self.target.warmup(sampled=sampled)
        t0 = time.perf_counter()
        if self.clock == "virtual":
            self._run_virtual(t0)
        else:
            self._run_wall(t0)
        finished = self.target.run()
        if self.clock == "virtual":
            # anything the loop's last poll missed (run() drained it)
            # stamps at one tick past the loop's horizon
            vt = max((rec.get("v_finish", 0.0) for rec in self._recs),
                     default=0.0) + self.tick_s
            self._poll(vt)
        return finished

    # -- accounting ----------------------------------------------------------

    def _virtual_phases(self, rec: dict) -> dict:
        """The coarse queue/prefill/decode split of one request's
        virtual lifetime (arrival -> admit -> first token -> finish)."""
        admit = rec.get("v_admit", rec.get("v_finish", rec["arrival"]))
        first = rec.get("v_first", rec.get("v_finish", admit))
        return {
            "queue": max(admit - rec["arrival"], 0.0),
            "prefill": max(first - admit, 0.0),
            "decode": max(rec.get("v_finish", first) - first, 0.0),
        }

    def _generated(self, req: Request) -> int:
        return (len(req.prompt) - req.orig_prompt_len) + len(req.output)

    def summary(self) -> dict:
        """The run's goodput accounting — deterministic in virtual
        mode, wall-truth otherwise. Keys: ``requests``/``process``/
        ``clock`` always; with an SLO also ``slo_attainment``,
        ``slo_met``/``slo_missed`` counts, ``group_slo_attainment``,
        ``miss_phases`` (miss count per dominant phase) and
        ``dominant_miss_phase`` (None with zero misses); plus
        ``goodput_tokens`` — tokens generated by deadline-meeting
        requests, the DistServe goodput numerator. In virtual mode the
        summary also carries ``ttft_p50/p95/p99_s`` and
        ``tpot_p50/p95/p99_s`` over the virtual timeline — the
        deterministic per-side attribution the disagg bench gates read
        (TTFT is the prefill side's figure, TPOT the decode side's).
        Structured rate-limit rejections surface as ``rate_limited``
        and are excluded from attainment (refused, not served late);
        schedules carrying ``deadline_s`` add ``deadline_misses`` /
        ``deadline_miss_frac`` — deterministic virtual-timeline
        verdicts, the admission bench's strictly-lower gate (ISSUE
        20)."""
        out: dict = {"requests": len(self._recs), "clock": self.clock,
                     "process": self.process}
        if self.rate is not None:
            out["rate"] = self.rate
        served = [rec for rec in self._recs if "req" in rec]
        if len(served) < len(self._recs):
            out["rate_limited"] = len(self._recs) - len(served)
        if self.clock == "virtual":
            from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (  # noqa: E501
                percentile,
            )
            ttfts = sorted(rec["v_first"] - rec["arrival"]
                           for rec in self._recs if "v_first" in rec)
            tpots = sorted(
                (rec["v_finish"] - rec["v_first"])
                / max(self._generated(rec["req"]) - 1, 1)
                for rec in self._recs
                if "v_first" in rec and "v_finish" in rec)
            for label, vals in (("ttft", ttfts), ("tpot", tpots)):
                if vals:
                    out[f"{label}_p50_s"] = round(percentile(vals, 0.50), 6)
                    out[f"{label}_p95_s"] = round(percentile(vals, 0.95), 6)
                    out[f"{label}_p99_s"] = round(percentile(vals, 0.99), 6)
        dl_recs = [rec for rec in served if "deadline_s" in rec]
        if dl_recs:
            # end-to-end deadline verdicts: wall mode trusts the
            # engine's stamped verdict, virtual mode recomputes on the
            # driver's deterministic timeline (the engine's verdict is
            # perf_counter truth, which would be noisy here)
            if self.clock == "wall":
                misses = sum(1 for rec in dl_recs
                             if rec["req"].deadline_miss)
            else:
                misses = sum(
                    1 for rec in dl_recs
                    if rec.get("v_finish", float("inf")) - rec["arrival"]
                    > rec["deadline_s"])
            out["deadline_misses"] = misses
            out["deadline_miss_frac"] = round(misses / len(dl_recs), 4)
        if self.slo is None:
            return out
        met = 0
        goodput_tokens = 0
        groups: dict = {}
        miss_phases = dict.fromkeys(MISS_PHASES, 0)
        for rec in served:
            req = rec["req"]
            if self.clock == "wall":
                ok = bool(req.slo_met)
            else:
                ok = True
                tokens = self._generated(req)
                if self.slo.ttft_s is not None:
                    first = rec.get("v_first")
                    ok &= (first is not None
                           and first - rec["arrival"] <= self.slo.ttft_s)
                if self.slo.tpot_s is not None:
                    first = rec.get("v_first")
                    finish = rec.get("v_finish")
                    ok &= (first is not None and finish is not None
                           and (finish - first) / max(tokens - 1, 1)
                           <= self.slo.tpot_s)
            met += int(ok)
            if ok:
                goodput_tokens += self._generated(req)
            else:
                if self.clock == "wall":
                    phases = {ph: req.phase_s.get(ph, 0.0)
                              for ph in MISS_PHASES}
                    # fold pre-submit backlog + preemption stalls into
                    # queue: from the deadline's point of view, both
                    # are time spent not being served
                    if req.arrival_s is not None and req.submit_t:
                        phases["queue"] += max(
                            req.submit_t - req.arrival_s, 0.0)
                    phases["queue"] += req.phase_s.get("preempted", 0.0)
                else:
                    phases = self._virtual_phases(rec)
                dom = max(MISS_PHASES,
                          key=lambda ph: (phases[ph],
                                          -MISS_PHASES.index(ph)))
                miss_phases[dom] += 1
            acc = groups.setdefault(rec["group"], [0, 0])
            acc[0] += int(ok)
            acc[1] += 1
        total = len(served)
        out["slo_met"] = met
        out["slo_missed"] = total - met
        out["slo_attainment"] = round(met / total, 4) if total else 0.0
        out["goodput_tokens"] = goodput_tokens
        out["group_slo_attainment"] = {
            g: round(m / t, 4) for g, (m, t) in sorted(groups.items())
            if t}
        out["miss_phases"] = {ph: n for ph, n in miss_phases.items()
                              if n}
        misses = [(n, ph) for ph, n in miss_phases.items() if n]
        out["dominant_miss_phase"] = (
            max(misses, key=lambda x: (x[0], -MISS_PHASES.index(x[1])))[1]
            if misses else None)
        return out
