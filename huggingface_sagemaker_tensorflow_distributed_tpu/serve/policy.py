"""Admission policy layer (ISSUE 20): WHO admits WHEN, never WHAT.

Pure host arithmetic — this module must stay in the jax-free import
zone (graftlint R7): the scheduler calls into it every ``admit()``
pass, and a jax import here is a hot-loop hazard (tracer leakage,
device sync) with zero upside since every input is a Python scalar.

Two policies:

- ``fifo`` (default) — the pre-ISSUE-20 behaviour, byte-identical
  telemetry: ``make_policy`` returns None and the scheduler walks
  ``waiting[0]`` exactly as before.
- ``slo`` — aging-bounded earliest-effective-deadline order.  The
  effective key folds in, lexicographically:

  (a) the **aging tier**: any request older than ``aging_s`` is
      promoted ahead of ALL younger work, promoted requests ordered
      FIFO among themselves by origin time — the strict starvation
      bound (property-tested in ``tests/test_policy.py``);
  (b) the **priority class** (smaller = more urgent, 0 default);
  (c) the **effective deadline** ``origin + deadline_s`` (requests
      without a deadline sort last within their class);
  (d) the **predicted service demand** in KV blocks — prompt blocks
      minus the ``peek_prefix`` cached-block count (refcount-neutral
      probe), so under KV pressure the largest-cached-prefix request
      admits first;
  (e) the admission sequence / rid as the deterministic tiebreak.

Router-side the same module supplies per-tenant token buckets keyed
on ``group``: ``submit`` past the bucket returns a structured
:class:`RateLimited` rejection (never a silent drop).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

ENV_POLICY = "HSTD_SERVE_POLICY"
ENV_AGING_S = "HSTD_SERVE_AGING_S"

POLICIES = ("fifo", "slo")

DEFAULT_AGING_S = 30.0


def parse_policy(spec) -> str:
    """The admission-policy knob: ``fifo`` (the pre-ISSUE-20 order,
    byte-identical telemetry) or ``slo`` (aging-bounded deadline /
    priority / cache-aware order). None reads ``HSTD_SERVE_POLICY``,
    default ``fifo``."""
    if spec is None:
        spec = os.environ.get(ENV_POLICY, "fifo") or "fifo"
    s = str(spec).strip().lower() or "fifo"
    if s not in POLICIES:
        raise ValueError(f"unparseable {ENV_POLICY} value {spec!r}: "
                         "expected fifo | slo")
    return s


def parse_aging_s(spec) -> float:
    """The starvation bound: under ``policy=slo`` any waiting request
    overtakes all younger work once it has waited ``aging_s`` seconds
    (policy-clock domain). None reads ``HSTD_SERVE_AGING_S``, default
    30.0; must be a positive, finite number."""
    if spec is None:
        spec = os.environ.get(ENV_AGING_S) or None
    if spec is None:
        return DEFAULT_AGING_S
    try:
        s = float(str(spec).strip() or DEFAULT_AGING_S)
    except ValueError:
        raise ValueError(f"unparseable {ENV_AGING_S} value {spec!r}: "
                         "expected a positive number of seconds")
    if not math.isfinite(s) or s <= 0:
        raise ValueError(f"{ENV_AGING_S} must be a positive finite "
                         f"number of seconds, got {spec!r}")
    return s


def request_origin(req) -> float:
    """A request's wait clock starts at its open-loop arrival stamp
    when the driver threaded one, else at the submit wall stamp — the
    same origin the SLO verdicts use, so aging and deadline slack stay
    in one time domain."""
    origin = getattr(req, "arrival_s", None)
    if origin is None:
        origin = getattr(req, "submit_t", None)
    return 0.0 if origin is None else float(origin)


class SloPolicy:
    """Aging-bounded earliest-effective-deadline admission order.

    Stateless between calls except for the parsed ``aging_s`` bound;
    callers supply the clock (``now``) and the per-request demand
    probe so virtual-clock runs stay deterministic."""

    name = "slo"

    def __init__(self, aging_s: float):
        self.aging_s = float(aging_s)

    def promoted(self, req, now: float) -> bool:
        """True once ``req`` has aged past the starvation bound."""
        return (now - request_origin(req)) >= self.aging_s

    def key(self, req, now: float,
            demand_blocks: Callable[[object], int]) -> tuple:
        origin = request_origin(req)
        if (now - origin) >= self.aging_s:
            # promoted tier: FIFO by origin — the aging bound must not
            # let two starving requests reorder each other forever
            return (0, origin, req.rid)
        deadline = getattr(req, "deadline_s", None)
        eff_deadline = (origin + deadline if deadline is not None
                        else math.inf)
        return (1, int(getattr(req, "priority", 0) or 0), eff_deadline,
                int(demand_blocks(req)), req.rid)

    def rank(self, waiting: List, now: float,
             demand_blocks: Callable[[object], int]) -> List:
        """Return ``waiting`` in admission order (a new list; the
        scheduler's queue itself is never reordered, so FIFO replay
        and requeue-at-front preemption semantics are untouched)."""
        return sorted(waiting,
                      key=lambda r: self.key(r, now, demand_blocks))


def make_policy(policy: str, aging_s: float) -> Optional[SloPolicy]:
    """None for ``fifo`` (the scheduler keeps its original admit path
    bit-for-bit); an :class:`SloPolicy` otherwise."""
    if policy == "fifo":
        return None
    return SloPolicy(aging_s)


# ---------------------------------------------------------------------------
# Router-side per-tenant rate limits
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RateLimited:
    """Structured rejection from ``Router.submit`` when a tenant's
    token bucket is empty — never a silent drop. ``retry_after_s`` is
    the bucket's own refill estimate for one request's worth of
    tokens."""

    group: str
    retry_after_s: float
    rate: float
    burst: float

    @property
    def rejected(self) -> bool:
        return True


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` cap.
    One submit costs one token. The caller supplies the clock so
    virtual-time runs replay deterministically."""

    def __init__(self, rate: float, burst: float):
        if not (rate > 0 and math.isfinite(rate)):
            raise ValueError(f"rate_limit rate must be positive and "
                             f"finite, got {rate!r}")
        if not (burst >= 1 and math.isfinite(burst)):
            raise ValueError(f"rate_limit burst must be >= 1, "
                             f"got {burst!r}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last: Optional[float] = None

    def try_take(self, now: float) -> Tuple[bool, float]:
        """(admitted, retry_after_s). Refills lazily from the last
        observed clock; a clock that goes backwards refills nothing
        (never raises — monotonicity is the caller's business)."""
        if self._last is not None and now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.rate


def parse_rate_limit(spec) -> Dict[str, Tuple[float, float]]:
    """Per-tenant rate-limit spec → ``{group: (rate, burst)}``.

    Accepts a dict (``{"tenant": (rate, burst)}`` or ``{"tenant":
    rate}``, burst defaulting to ``max(1, rate)``) or a string of
    ``group=rate[:burst]`` comma-separated entries. ``*`` is the
    default bucket applied to groups without their own entry. None or
    empty → no rate limiting."""
    if spec is None:
        return {}
    out: Dict[str, Tuple[float, float]] = {}
    if isinstance(spec, dict):
        items = spec.items()
    else:
        items = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"unparseable rate_limit entry "
                                 f"{part!r}: expected group=rate[:burst]")
            g, val = part.split("=", 1)
            items.append((g.strip(), val))
    for group, val in items:
        if isinstance(val, (tuple, list)):
            rate, burst = (float(val[0]),
                           float(val[1]) if len(val) > 1 else None)
        elif isinstance(val, (int, float)):
            rate, burst = float(val), None
        else:
            txt = str(val).strip()
            if ":" in txt:
                r, b = txt.split(":", 1)
                rate, burst = float(r), float(b)
            else:
                rate, burst = float(txt), None
        if burst is None:
            burst = max(1.0, rate)
        TokenBucket(rate, burst)  # validate eagerly, with the knob named
        out[str(group)] = (rate, burst)
    return out
