"""Multi-replica serving router (ISSUE 14): N :class:`~.engine.
ServeEngine` replicas — each with its own scheduler, BlockManager,
prefix cache, and telemetry stream — behind ONE ``submit()``/``run()``
facade, with pluggable SLO- and prefix-affinity-aware placement.

This is the data-parallel remainder of the scale-out story: PR 13 made
one engine span chips (tensor parallel — a model bigger than a chip);
the router spreads *requests* over N such engines (traffic bigger than
an engine). vLLM-style fleets win most of their throughput at the
replica-level load balancer, and Sarathi-Serve's analysis says tail
latency is won or lost at placement/admission time — and the repo
already emits every signal a smart router needs (the scheduler's live
waiting-depth/KV-pressure gauges, PR 10's queue-wait attribution, the
PR 7 prefix fingerprints), so the router wires them into a placement
policy instead of FIFO-into-one-engine:

- ``round_robin`` — cycle over admitting replicas; the trivially fair
  baseline every policy gate compares against.
- ``least_loaded`` — score each replica by
  ``waiting_depth + occupied_slots + kv_used_frac`` (the engine's own
  live :meth:`~.engine.ServeEngine.load_gauges`, read host-side — the
  router never parses its own telemetry to route) and place on the
  argmin, index-tiebroken so placement is deterministic.
- ``affinity`` — a ROUTER-level prefix-fingerprint index built from
  the same chain-key hashing as the BlockManager's block-level prefix
  cache (:func:`~.paged_kv.prefix_chain_keys`: key N commits to the
  whole token prefix through chunk N): a request routes to the replica
  whose index entry covers its LONGEST hashed prefix — the replica
  most likely to hold its KV blocks warm — so templated families stick
  to a replica and the per-replica prefix caches stay hot instead of
  every replica paying every family's cold miss. The index is a pure
  function of tokens (no block ids), LRU-aged to ``affinity_cap``
  entries, and IMBALANCE-BOUNDED: when the sticky replica is more than
  ``affinity_max_skew`` load units deeper than the lightest sibling
  (default: one full slot batch), the request falls back to
  least-loaded — affinity is a cache heuristic and must never starve
  load balance (the cache-aware admission-ordering follow-up of PR 7,
  generalized across replicas). Any placement is CORRECT: every
  replica produces token-identical output (greedy exact, sampled
  bitwise — per-request seeds), so a stale or evicted index entry
  degrades to a cold cache, never to wrong tokens.

Replica drain/restart — the fleet degrades instead of dying:
:meth:`Router.drain` stops admitting to replica i, requeues its
WAITING requests onto siblings through the normal placement policy
(recompute semantics, the same state the scheduler's preemption
/requeue path builds — a preemption-folded prompt moves unchanged,
sampled keys re-derive from the request's own seed, queue-wait keeps
counting from the original submit stamp), and LIVE-MIGRATES its
RESIDENT requests (ISSUE 18): each resident's KV block set moves to a
sibling through :func:`~.transport.migrate_request` with zero
re-prefill, so a drain completes without waiting for any resident to
finish — preemption-free rolling restarts. A resident no sibling can
take (heterogeneous fleets) finishes in place, counted in the drain
event's ``residents_in_place``. :meth:`Router.restart` re-admits.
Every move is telemetered (``drain`` / ``requeue`` / ``migrate`` /
``restart`` serve events).

Disaggregated fleets (ISSUE 18): ``Router(roles="prefill:N,decode:M")``
designates prefill-only and decode-only replicas. Submissions place
over the prefill side only; a prefill replica runs chunked prefill
with its decode phase suppressed entirely (its idle decode slots feed
the Sarathi token budget, so prefill runs at full width instead of
one chunk per iteration), and each finished prefill's block set is
handed to the least-loaded decode replica between fleet iterations —
wide prefill dispatches never stall another tenant's decode iteration,
which is the DistServe/Splitwise goodput argument the bench's
disaggregation line gates. With ``replica_kwargs`` the fleet may also
be HETEROGENEOUS (e.g. TP=2 replicas for long-context traffic beside
TP=1 for short) — the ``length_aware`` placement policy routes by
prompt length, and migration re-shards the KV heads axis simply by
scattering into the destination's own sharded pools.

Telemetry: each engine's per-request lifecycle events carry a
``replica`` tag (``obsctl slo`` groups tail attribution by it); the
router's ``run()`` emits one report event per replica plus ONE
aggregate report last (``placement``, ``replicas``,
``replica_load_imbalance`` = max/mean requests served — the figure
``obsctl diff`` watches — and a ``per_replica`` hit-rate/depth
breakdown). A ``replicas=1`` router is a pass-through: it drives the
single engine's own ``run()`` and tags nothing, so its telemetry is
byte-identical to the pre-router engine stream (allowlist-gated).

Compile expectations: replicas over the same model/geometry share the
module-level jitted step families (static keys are (model, plan,
bucket, sampled) — identical across replicas), so N replicas compile
ONE bucket ladder total, not N.
"""

from __future__ import annotations

import contextlib
import os
import time
import types
from collections import OrderedDict
from typing import Optional, Union

import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
    ServeEngine,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    extract_block_sets,
    prefix_chain_keys,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.policy import (
    RateLimited,
    TokenBucket,
    parse_aging_s,
    parse_policy,
    parse_rate_limit,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    DECODE,
    Request,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.transport import (
    TransportError,
    can_accept,
    migrate_request,
)

ENV_REPLICAS = "HSTD_SERVE_REPLICAS"
ENV_PLACEMENT = "HSTD_SERVE_PLACEMENT"
ENV_ROLES = "HSTD_SERVE_ROLES"
ENV_TRACE = "HSTD_SERVE_TRACE"

PLACEMENTS = ("round_robin", "least_loaded", "affinity", "length_aware")


def parse_replicas(spec) -> int:
    """The replica-count knob: a positive int. None reads
    ``HSTD_SERVE_REPLICAS`` (default 1 = the single pass-through
    engine, byte-identical telemetry)."""
    if spec is None:
        spec = os.environ.get(ENV_REPLICAS, "1") or "1"
    try:
        n = int(str(spec).strip() or "1")
    except ValueError:
        raise ValueError(f"unparseable {ENV_REPLICAS} value {spec!r}: "
                         "expected a positive integer")
    if n < 1:
        raise ValueError(f"{ENV_REPLICAS} must be >= 1, got {n}")
    return n


def parse_roles(spec) -> Optional[dict]:
    """The disaggregation knob (ISSUE 18): ``prefill:N,decode:M``
    (both >= 1) designates the first N replicas prefill-only and the
    next M decode-only; an empty value keeps every replica mixed (the
    pre-disaggregation fleet, byte-identical behavior). None reads
    ``HSTD_SERVE_ROLES``. A dict ``{"prefill": N, "decode": M}``
    passes through."""
    if spec is None:
        spec = os.environ.get(ENV_ROLES, "")
    if isinstance(spec, dict):
        parts = {str(k).strip().lower(): v for k, v in spec.items()}
    else:
        s = str(spec).strip().lower()
        if not s:
            return None
        parts = {}
        for tok in s.split(","):
            role, sep, count = tok.partition(":")
            if not sep:
                raise ValueError(
                    f"unparseable {ENV_ROLES} value {spec!r}: expected "
                    "role:count pairs like 'prefill:1,decode:1'")
            parts[role.strip()] = count.strip()
    unknown = set(parts) - {"prefill", "decode"}
    if unknown:
        raise ValueError(
            f"unparseable {ENV_ROLES} value {spec!r}: unknown role(s) "
            f"{sorted(unknown)} (expected prefill / decode)")
    try:
        out = {"prefill": int(parts.get("prefill", 0)),
               "decode": int(parts.get("decode", 0))}
    except (TypeError, ValueError):
        raise ValueError(
            f"unparseable {ENV_ROLES} value {spec!r}: counts must be "
            "positive integers")
    if out["prefill"] < 1 or out["decode"] < 1:
        raise ValueError(
            f"{ENV_ROLES} needs at least one prefill and one decode "
            f"replica, got {out}")
    return out


def parse_placement(spec: Union[str, None]) -> str:
    """The placement-policy knob: one of ``round_robin`` (default) /
    ``least_loaded`` / ``affinity`` / ``length_aware``. None reads
    ``HSTD_SERVE_PLACEMENT``."""
    if spec is None:
        spec = os.environ.get(ENV_PLACEMENT, "round_robin")
    s = str(spec).strip().lower() or "round_robin"
    if s not in PLACEMENTS:
        raise ValueError(f"unparseable {ENV_PLACEMENT} value {spec!r}: "
                         f"expected {' | '.join(PLACEMENTS)}")
    return s


def parse_trace(spec) -> bool:
    """The fleet-tracing knob (ISSUE 19): ``on`` (default) mints a
    ``trace_id`` + hop counter per MULTI-replica submit so every
    lifecycle event the request leaves — on whichever engine — can be
    stitched back into one causal trace (:mod:`~.obs.trace`); ``off``
    suppresses minting, telemetry byte-identical to the pre-tracing
    stream. None reads ``HSTD_SERVE_TRACE``. Single-replica routers
    never mint regardless (the pass-through byte-identity contract —
    there is nothing to stitch)."""
    if spec is None:
        spec = os.environ.get(ENV_TRACE, "on")
    s = str(spec).strip().lower() or "on"
    if s not in ("on", "off"):
        raise ValueError(f"unparseable {ENV_TRACE} value {spec!r}: "
                         "expected on | off")
    return s == "on"


class Router:
    """N :class:`~.engine.ServeEngine` replicas behind one facade.
    ``replicas``/``placement``/``roles`` read their env knobs when
    None (``HSTD_SERVE_REPLICAS`` / ``HSTD_SERVE_PLACEMENT`` /
    ``HSTD_SERVE_ROLES``); every other keyword is forwarded verbatim
    to EACH replica's engine constructor — homogeneous by default
    (which is what makes a drain-requeued request's submit-time
    validation transferable), with per-replica ``replica_kwargs``
    overrides for heterogeneous fleets (ISSUE 18: transport re-checks
    geometry before every cross-replica move).

    ``affinity_cap`` bounds the affinity index (LRU aging — oldest
    fingerprints fall out first, exactly the staleness order the
    per-replica block caches evict in). ``affinity_max_skew`` is the
    load-imbalance bound past which an affinity hit is overridden by
    least-loaded placement (default: one engine's ``num_slots`` — a
    full batch of queue depth buys back a cold prefill, not more).

    Placement changes WHERE a request runs, never WHAT it emits:
    per-request output is token-identical to a single-engine run under
    every policy and across drains (greedy exact, sampled bitwise —
    the engine's own exactness/seed contracts, which are per-request
    and placement-blind)."""

    def __init__(self, model, params, *, replicas=None, placement=None,
                 roles=None, replica_kwargs=None,
                 length_threshold: Optional[int] = None,
                 affinity_cap: int = 4096,
                 affinity_max_skew: Optional[int] = None,
                 trace=None, policy=None, aging_s=None,
                 rate_limit=None, **engine_kwargs):
        self.roles = parse_roles(roles)
        # admission policy (ISSUE 20): parsed ONCE here and threaded
        # into every replica's engine, so one env read configures the
        # whole fleet identically (a replica_kwargs override can still
        # diverge a replica deliberately)
        self.policy = parse_policy(policy)
        self.aging_s = parse_aging_s(aging_s)
        if self.roles is not None:
            n_roles = self.roles["prefill"] + self.roles["decode"]
            if replicas is not None and parse_replicas(replicas) != n_roles:
                raise ValueError(
                    f"replicas={replicas} contradicts roles {self.roles} "
                    f"(= {n_roles} replicas): pass one or the other")
            self.n = n_roles
        else:
            self.n = parse_replicas(replicas)
        self.placement = parse_placement(placement)
        # per-replica overrides (ISSUE 18, heterogeneous fleets): the
        # shared engine_kwargs build the fleet's common geometry; a
        # replica_kwargs[i] dict layers replica i's own knobs (e.g.
        # mesh=2 for a TP=2 long-context replica) on top. Transportable
        # requests require equal POOL signatures (transport validates),
        # which mixed-TP replicas over one model satisfy by design.
        if replica_kwargs is not None and len(replica_kwargs) != self.n:
            raise ValueError(
                f"replica_kwargs has {len(replica_kwargs)} entries for "
                f"{self.n} replicas")
        self.engines = []
        for i in range(self.n):
            kw = dict(engine_kwargs, policy=self.policy,
                      aging_s=self.aging_s)
            if replica_kwargs is not None:
                kw.update(replica_kwargs[i])
            self.engines.append(ServeEngine(model, params, **kw))
        if self.n > 1:
            for i, eng in enumerate(self.engines):
                eng.replica = i
        self.role_of: list[str] = (
            ["prefill"] * self.roles["prefill"]
            + ["decode"] * self.roles["decode"]
            if self.roles is not None else ["mixed"] * self.n)
        for i, eng in enumerate(self.engines):
            if self.role_of[i] == "prefill":
                eng.prefill_only = True
        self.block_size = self.engines[0].blocks.block_size
        self._rr = 0
        self._draining: set[int] = set()
        self._owner: dict[int, int] = {}        # rid -> replica index
        self.drains = 0
        self.requeues = 0
        self.migrations = 0
        # fleet tracing (ISSUE 19): mint only on real fleets — a
        # single-replica router is the byte-identical pass-through and
        # mints nothing. The id is deterministic (router-scoped
        # sequence), so replayed runs produce identical traces.
        self.trace = parse_trace(trace) and self.n > 1
        self._trace_seq = 0
        # length-aware routing threshold (heterogeneous fleets):
        # prompts at/above it go to the deepest capacity class
        if length_threshold is None:
            length_threshold = min(
                e.sched.max_model_len for e in self.engines) // 2
        self.length_threshold = int(length_threshold)
        self.affinity_cap = int(affinity_cap)
        if self.affinity_cap < 1:
            raise ValueError("affinity_cap must be >= 1")
        if affinity_max_skew is None:
            affinity_max_skew = self.engines[0].num_slots
        self.affinity_max_skew = float(affinity_max_skew)
        self.affinity_fallbacks = 0
        # chain key -> replica index, newest-used last (LRU aging)
        self._affinity: "OrderedDict[int, int]" = OrderedDict()
        # per-tenant token buckets (ISSUE 20), keyed on `group`: a
        # submit past its bucket returns a structured RateLimited
        # rejection — never a silent drop. The `*` entry is the
        # default bucket for groups without their own; no spec = no
        # rate limiting (byte-identical submit path).
        self._rate_spec = parse_rate_limit(rate_limit)
        self._buckets: dict[str, TokenBucket] = {}
        self.rate_limited = 0

    # -- placement -----------------------------------------------------------

    def _admitting(self) -> list[int]:
        return [i for i in range(self.n) if i not in self._draining]

    def _intake(self) -> list[int]:
        """Replicas NEW submissions may target: every admitting one —
        minus the decode side of a disaggregated fleet, which only
        receives migrated residents (ISSUE 18)."""
        cand = self._admitting()
        if self.roles is not None:
            cand = [i for i in cand if self.role_of[i] == "prefill"]
        return cand

    def _load(self, i: int) -> float:
        """One replica's placement score from its live gauges: queued +
        resident requests (each is one unit of service ahead of a new
        arrival) plus the KV pool pressure fraction (breaks ties
        toward the replica with block headroom — the one least likely
        to preempt what it admits)."""
        g = self.engines[i].load_gauges()
        return g["waiting_depth"] + g["running"] + g["kv_used_frac"]

    def _least_loaded(self, cand: list[int]) -> int:
        return min(cand, key=lambda i: (self._load(i), i))

    def _affine(self, prompt, cand: list[int]) -> int:
        """The replica covering the prompt's longest hashed prefix —
        unless it is draining or past the imbalance bound, in which
        case fall back to least-loaded (counted, so the bench can see
        affinity yielding to load balance rather than starving it)."""
        hit: Optional[int] = None
        for key, _chunk in prefix_chain_keys(prompt, self.block_size):
            rep = self._affinity.get(key)
            if rep is None:
                break
            hit = rep                    # deepest indexed level wins
        if hit is None:
            return self._least_loaded(cand)
        if hit not in cand or (self._load(hit)
                               - min(self._load(i) for i in cand)
                               > self.affinity_max_skew):
            self.affinity_fallbacks += 1
            return self._least_loaded(cand)
        return hit

    def _register_affinity(self, prompt, replica: int) -> None:
        """Point every full-chunk fingerprint of ``prompt`` at the
        replica that will prefill (and therefore block-cache) it;
        last-writer-wins on requeue redirects, LRU-aged at
        ``affinity_cap``. The index is a routing heuristic over the
        same chain values the replica's BlockManager indexes — an
        entry outliving the physical blocks just degrades to a cold
        cache on arrival, never to wrong tokens."""
        for key, _chunk in prefix_chain_keys(prompt, self.block_size):
            if key in self._affinity:
                self._affinity.move_to_end(key)
            self._affinity[key] = replica
        while len(self._affinity) > self.affinity_cap:
            self._affinity.popitem(last=False)

    def _place(self, prompt, max_new_tokens: int = 1) -> int:
        """The policy's CHOICE only — no state moves here. Callers
        commit via :meth:`_commit_place` once the engine has accepted
        the request: a submit the scheduler rejects (over-length, can
        never fit the pool) must not advance the round-robin cursor or
        pollute the affinity index with fingerprints pointing at a
        replica that will never prefill them.

        Under ``policy="slo"`` (ISSUE 20) the default rotation is
        replaced by live ``load_gauges()`` backpressure — the same
        waiting-depth/KV-pressure signal the admission key consumes,
        so cross-replica placement and per-replica admission pull in
        the same direction. An EXPLICIT placement choice
        (least_loaded / affinity / length_aware) keeps its own
        semantics — they are already load- or cache-aware."""
        cand = self._intake()
        if len(cand) == 1:
            return cand[0]
        if self.placement == "round_robin":
            if self.policy != "fifo":
                return self._least_loaded(cand)
            return cand[self._rr % len(cand)]
        if self.placement == "least_loaded":
            return self._least_loaded(cand)
        if self.placement == "length_aware":
            return self._length_aware(prompt, cand, max_new_tokens)
        return self._affine(prompt, cand)

    def _capacity_class(self, i: int) -> tuple:
        """A replica's capacity rank for length-aware routing: its
        tensor-parallel degree first (a TP=2 replica holds the deep
        pool long contexts need), pool blocks as the tiebreak."""
        eng = self.engines[i]
        return (eng.tp, eng.blocks.num_blocks)

    def _length_aware(self, prompt, cand: list[int],
                      max_new_tokens: int = 1) -> int:
        """Heterogeneous-fleet policy (ISSUE 18): prompts at/above
        ``length_threshold`` go to the DEEPEST capacity class (TP
        degree, then pool size), short ones to the shallowest — so
        long-context traffic lands on the replicas built for it and
        never crowds the small replicas' pools. Least-loaded inside
        the chosen class; on a homogeneous fleet every replica is one
        class and this IS least-loaded.

        Admission-aware refinement (ISSUE 20, PR 18 follow-up): the
        class preference folds in LIVE pool headroom via the
        ``can_accept(live=True)`` probe — a destination whose pool
        cannot carry the request's worst case RIGHT NOW is skipped
        for a class peer with room, and when the whole preferred
        class is full the request falls out to ANY candidate with
        room rather than queueing on a full pool. Static length
        preference alone would happily stack long prompts onto a
        full deep replica while a shallow one idled."""
        shim = types.SimpleNamespace(
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=int(max_new_tokens))
        classes = {self._capacity_class(i) for i in cand}
        want = max(classes) if len(prompt) >= self.length_threshold \
            else min(classes)
        pool = [i for i in cand if self._capacity_class(i) == want]
        roomy = [i for i in pool
                 if can_accept(self.engines[i], shim, live=True)]
        if not roomy:
            roomy = [i for i in cand
                     if can_accept(self.engines[i], shim, live=True)]
        return self._least_loaded(roomy or pool)

    def _commit_place(self, prompt, choice: int) -> None:
        """Land the placement's state changes for an ACCEPTED request:
        advance the round-robin rotation (only when there was a real
        choice to rotate over), register the prompt's fingerprints at
        the chosen replica."""
        if self.placement == "round_robin":
            if len(self._intake()) > 1:
                self._rr += 1
        elif self.placement == "affinity":
            self._register_affinity(prompt, choice)

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw):
        """Place one request per the policy and queue it on the chosen
        replica. Same signature/semantics as
        :meth:`~.engine.ServeEngine.submit` — the returned
        :class:`Request` is the engine's own handle.

        With per-tenant rate limits configured (ISSUE 20), a submit
        whose ``group`` bucket is empty returns a structured
        :class:`~.serve.policy.RateLimited` object instead of a
        Request — a STRUCTURAL rejection (``rate_limited`` serve
        event, counted, ``retry_after_s`` named), never a silent
        drop. The bucket clock is the caller's ``arrival_s`` when
        threaded (deterministic under the virtual-clock driver), else
        wall."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        limited = self._rate_check(str(kw.get("group", "")),
                                   kw.get("arrival_s"))
        if limited is not None:
            return limited
        if self.roles is not None:
            # the prefill side validates against ITS pool below; also
            # require that SOME decode replica can eventually hold the
            # request, or the post-prefill handoff would retry forever
            # (only reachable on heterogeneous decode sides)
            shim = types.SimpleNamespace(
                prompt=prompt, max_new_tokens=int(max_new_tokens))
            if not any(can_accept(self.engines[j], shim)
                       for j in range(self.n)
                       if self.role_of[j] == "decode"):
                raise ValueError(
                    f"request (prompt {len(prompt)} + max_new_tokens "
                    f"{max_new_tokens}) can never fit any decode "
                    "replica of the disaggregated fleet")
        i = self._place(prompt, int(max_new_tokens))
        if self.trace and "trace_id" not in kw:
            kw = dict(kw, trace_id=f"t{self._trace_seq:06d}")
            self._trace_seq += 1
        req = self.engines[i].submit(prompt, max_new_tokens, **kw)
        self._commit_place(prompt, i)       # only an ACCEPTED submit
        self._owner[req.rid] = i
        return req

    def _rate_check(self, group: str,
                    arrival_s: Optional[float]) -> Optional[RateLimited]:
        """One token-bucket decision for ``group`` (its own entry, else
        the ``*`` default, else unlimited). Buckets materialize lazily
        per group so two tenants sharing the ``*`` spec still meter
        independently — a per-tenant limit, not a global one."""
        if not self._rate_spec:
            return None
        spec = self._rate_spec.get(group, self._rate_spec.get("*"))
        if spec is None:
            return None
        bucket = self._buckets.get(group)
        if bucket is None:
            bucket = self._buckets[group] = TokenBucket(*spec)
        now = (time.perf_counter() if arrival_s is None
               else float(arrival_s))
        ok, retry_after = bucket.try_take(now)
        if ok:
            return None
        self.rate_limited += 1
        limited = RateLimited(group=group,
                              retry_after_s=round(retry_after, 6),
                              rate=spec[0], burst=spec[1])
        obs.serve("rate_limited", group=group,
                  retry_after_s=limited.retry_after_s,
                  rate_limited=self.rate_limited)
        return limited

    def replica_of(self, req: Union[Request, int]) -> int:
        """Which replica currently owns a request (post-drain requeues
        included)."""
        rid = req.rid if isinstance(req, Request) else int(req)
        return self._owner[rid]

    def output_ids(self, req: Request) -> np.ndarray:
        return self.engines[self._owner[req.rid]].output_ids(req)

    @property
    def finished(self) -> dict[int, Request]:
        """Merged {rid: Request} across replicas (rids are process
        -global, so keys never collide)."""
        out: dict[int, Request] = {}
        for eng in self.engines:
            out.update(eng.finished)
        return out

    def has_work(self) -> bool:
        return any(eng.has_work() for eng in self.engines)

    def warmup(self, sampled: bool = False) -> None:
        """Warm every replica. Replicas share the module-level jitted
        step families (identical static keys), so replica 0 compiles
        the ladder and the rest reuse it — N replicas cost one bucket
        ladder of compiles, not N (the per-replica compile-flatness
        gate the bench enforces)."""
        for eng in self.engines:
            eng.warmup(sampled=sampled)

    def step(self) -> None:
        """One interleaved fleet iteration: each replica with work runs
        one engine iteration. With the engines' dispatch-ahead loop on
        (the default) replica A's device step stays in flight while
        replicas B..N run their whole host side — the router's
        interleave extends the PR 12 overlap across the fleet."""
        for eng in self.engines:
            if eng.has_work():
                eng.step()
        if self.roles is not None:
            self._harvest()

    def _harvest(self) -> None:
        """Disaggregated handoff (ISSUE 18): every request that
        FINISHED PREFILL on a prefill replica this iteration (parked
        in DECODE state — the replica's decode phase is suppressed)
        migrates to the least-loaded admitting decode replica with
        zero re-prefill. A saturated or draining decode side just
        defers the handoff to the next fleet iteration — the parked
        residents are the disaggregation backpressure, and their held
        slots throttle the prefill side's own admission."""
        for i, eng in enumerate(self.engines):
            if self.role_of[i] != "prefill":
                continue
            ready = sorted(
                (s for s in eng.sched.slots
                 if s.request is not None and s.request.state == DECODE),
                key=lambda s: s.admit_seq, reverse=True)
            for slot in ready:
                req = slot.request
                cand = [j for j in self._admitting()
                        if self.role_of[j] == "decode"
                        and can_accept(self.engines[j], req)]
                if not cand:
                    return
                j = self._least_loaded(cand)
                info = migrate_request(eng, self.engines[j], req.rid)
                if info is None:
                    continue        # finished at the handoff commit
                self._owner[req.rid] = j
                self.migrations += 1

    def drain(self, i: int) -> list[Request]:
        """Stop admitting to replica i: its WAITING requests requeue
        to siblings through the normal placement policy (recompute
        semantics — identical tokens, queue clock unreset), its
        RESIDENT requests LIVE-MIGRATE to the least-loaded compatible
        sibling (:func:`~.transport.migrate_request` — the KV block
        set moves, decode resumes with zero re-prefill, so the drain
        completes without waiting for any resident to finish), and
        until :meth:`restart` no new placement chooses it. A resident
        no sibling can take (heterogeneous fleets) finishes in place —
        the drain event's ``residents_in_place`` counts them. Returns
        the requeued WAITING requests (the migrated residents keep
        their engine handles; :meth:`replica_of` tracks both). Refuses
        to drain the last admitting replica — per role on a
        disaggregated fleet — a fleet with nowhere to admit is an
        outage, not a drain."""
        if not 0 <= i < self.n:
            raise ValueError(f"replica {i} out of range [0, {self.n})")
        if i in self._draining:
            raise ValueError(f"replica {i} is already draining")
        peers_like_i = [j for j in self._admitting()
                        if j != i and self.role_of[j] == self.role_of[i]]
        if not peers_like_i:
            role = ("" if self.roles is None
                    else f" {self.role_of[i]}-role")
            raise ValueError(
                f"cannot drain the last admitting{role} replica: "
                "restart a sibling first (a fleet must always have "
                "somewhere to place work)")
        self._draining.add(i)
        self.drains += 1
        src = self.engines[i]
        moved = src.take_waiting()
        for req in moved:
            if req.swap_set is not None:
                # a swap-preempted victim changing engines: return the
                # SOURCE's host-tier reservation (the destination
                # never reserved for it), and land the restore as a
                # MIGRATION arrival — its restore traffic is migration
                # traffic, not the destination's swap-tier traffic
                src.blocks.host_release(req.swap_set.nbytes)
                cand = [j for j in self._drain_peers(i, req)
                        if can_accept(self.engines[j], req)]
                if cand:
                    j = self._least_loaded(cand)
                    src.migrations_out += 1
                    self.engines[j]._migrated_in[req.rid] = i
                    self.engines[j].adopt(req)
                else:
                    # no compatible sibling for the payload: forfeit
                    # it — recompute semantics, the swap tier's own
                    # lossless fallback
                    req.swap_set = None
                    req.swap_context = 0
                    j = self._place(req.prompt, req.max_new_tokens)
                    self.engines[j].adopt(req)
                    self._commit_place(req.prompt, j)
            else:
                j = self._place(req.prompt, req.max_new_tokens)
                self.engines[j].adopt(req)      # never rejects
                self._commit_place(req.prompt, j)
            self._owner[req.rid] = j
            self.requeues += 1
            trace_kw = {}
            if req.trace_id:
                # a requeue is an inter-engine move: it advances the
                # hop counter just as migrate_request does, and the
                # event is the stitcher's evidence for that hop
                req.hop += 1
                trace_kw = {"trace_id": req.trace_id, "hop": req.hop}
            obs.serve("requeue", request=req.rid, replica=i,
                      to_replica=j, **trace_kw)
        migrated = 0
        residents_in_place = 0
        # land src's in-flight pipeline ONCE for the whole cohort
        # (each migrate_request's own flush then finds it empty): the
        # COMMITTED state decides who is hot, and the batched payloads
        # below must match the exact post-commit context lengths
        with src._mesh_ctx():
            if src._pending is not None:
                src._flush("migrate")
            if src._pending_spec is not None:
                pending, src._pending_spec = src._pending_spec, None
                src._commit_spec(pending)
        # snapshot rids: migrating one resident lands the engine's
        # in-flight pipeline, which can FINISH (or clear) others
        resident_rids = [
            s.request.rid for s in sorted(
                (s for s in src.sched.slots if s.request is not None),
                key=lambda s: s.admit_seq, reverse=True)]
        # batched cohort extraction (ISSUE 20, PR 18 follow-up (c)):
        # every hot (DECODE) victim with a compatible peer gathers its
        # block set device-side, then ONE device_get pulls the whole
        # cohort to host — V victims cost one blocking round-trip, not
        # V sequential pulls. Extraction seconds amortize evenly over
        # the cohort so each request's migrate_extract_s rider keeps
        # its transport-hop-pricing meaning. Migration count, peer
        # choice, and tokens are identical to the sequential path —
        # migrate_request falls back to its own extraction whenever a
        # prefetched set no longer matches.
        prefetched: dict[int, object] = {}
        share = 0.0
        hot = [s for s in src.sched.slots
               if s.request is not None
               and s.request.state == DECODE
               and any(can_accept(self.engines[j], s.request)
                       for j in self._drain_peers(i, s.request))]
        if hot:
            id_lists = [s.table[:src.blocks.blocks_for(s.context_len)]
                        for s in hot]
            t0 = time.perf_counter()
            with src._mesh_ctx():
                sets = extract_block_sets(
                    src._pools, id_lists,
                    d_pools=src._d_pools if src.speculative else None)
            share = (time.perf_counter() - t0) / len(hot)
            prefetched = {s.request.rid: bs
                          for s, bs in zip(hot, sets)}
        for rid in resident_rids:
            if rid in src.finished:
                continue
            slot = next((s for s in src.sched.slots
                         if s.request is not None
                         and s.request.rid == rid), None)
            if slot is None:
                continue
            req = slot.request
            cand = self._drain_peers(i, req)
            cand = [j for j in cand if can_accept(self.engines[j], req)]
            if not cand:
                residents_in_place += 1
                continue
            j = self._least_loaded(cand)
            try:
                info = migrate_request(src, self.engines[j], rid,
                                       prefetched=prefetched.get(rid),
                                       extract_s=share)
            except TransportError:
                residents_in_place += 1
                continue
            if info is None:
                continue            # finished at the pipeline flush
            self._owner[rid] = j
            self.migrations += 1
            migrated += 1
        obs.serve("drain", replica=i, requeued=len(moved),
                  migrated=migrated,
                  residents_in_place=residents_in_place,
                  placement=self.placement)
        return moved

    def _drain_peers(self, i: int, req: Request) -> list[int]:
        """Where a draining replica's resident may go: any admitting
        sibling on a mixed fleet; on a disaggregated one, a DECODE
        resident goes to the decode side (even off a prefill replica —
        it is exactly a finished prefill awaiting handoff) and a
        mid-prefill one to another prefill replica."""
        if self.roles is None:
            return [j for j in self._admitting() if j != i]
        want = ("decode"
                if req.state == DECODE or req.swap_set is not None
                else "prefill")
        return [j for j in self._admitting()
                if j != i and self.role_of[j] == want]

    def restart(self, i: int) -> None:
        """Re-admit to a drained replica (its pools/caches/compiled
        steps were never torn down — restart is instant)."""
        if i not in self._draining:
            raise ValueError(f"replica {i} is not draining")
        self._draining.discard(i)
        obs.serve("restart", replica=i)

    def run(self) -> dict[int, Request]:
        """Drive the fleet until every submitted request finishes;
        returns the merged {rid: Request}. A single-replica router
        delegates to the engine's own :meth:`~.engine.ServeEngine.run`
        — no router events, no replica tags: the telemetry stream is
        byte-identical to the pre-router engine's (the ``--replicas 1``
        contract). A multi-replica run emits one report event per
        replica (each tagged) and ONE aggregate router report LAST, so
        report consumers that keep the last event
        (``obs/report.py::_serve_summary``) see the fleet view."""
        if self.n == 1:
            return dict(self.engines[0].run())
        self.warmup()
        with obs.span("serve/router_run"):
            while self.has_work():
                self.step()
        for eng in self.engines:
            obs.scalar(
                "serve/kv_peak_utilization",
                eng.blocks.peak_used / max(eng.blocks.num_blocks - 1, 1))
            summary = eng.slo_summary()
            if summary:
                obs.serve("report", **summary)
        summary = self.slo_summary()
        if summary:
            obs.serve("report", **summary)
        return self.finished

    # -- aggregates ----------------------------------------------------------

    def replica_load_imbalance(self) -> Optional[float]:
        """max/mean requests served per replica (1.0 = perfectly even;
        worse UP — the figure ``obsctl diff`` gates as
        ``serve_replica_load_imbalance``). None before any finish."""
        served = [len(eng.finished) for eng in self.engines]
        mean = sum(served) / len(served)
        if mean == 0:
            return None
        return max(served) / mean

    def slo_summary(self) -> dict:
        """The fleet-level SLO summary ({} until a request finishes;
        pass-through to the engine's own for a single-replica router):
        aggregate TTFT/e2e percentiles over every replica's finished
        requests, fleet counters (drains/requeues, summed preemptions
        and tokens), ``replica_load_imbalance``, the aggregate decode
        tokens/sec from the engines' own decode accounting, the
        aggregate prefix-cache hit rate, and a compact ``per_replica``
        breakdown (requests / peak waiting depth / pool peak / hit
        rate) — the figures the ``scripts/serve.py`` summary and the
        bench line surface."""
        if self.n == 1:
            out = self.engines[0].slo_summary()
            # the rate-limit counter lives router-side (rejections
            # never reach an engine) — ride it on the pass-through,
            # gated like every ISSUE 20 rider
            if self.rate_limited and out:
                out = dict(out, rate_limited=self.rate_limited)
            return out
        reqs = [r for eng in self.engines for r in eng.finished.values()]
        if not reqs:
            return {}
        from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
            percentile,
        )

        out: dict = {
            "requests": len(reqs),
            "replicas": self.n,
            "placement": self.placement,
            "tokens": sum(e.tokens_generated for e in self.engines),
            "iterations": sum(e.iterations for e in self.engines),
            "preemptions": sum(e.sched.n_preemptions
                               for e in self.engines),
            "peak_waiting_depth": max(e.peak_waiting
                                      for e in self.engines),
            "drains": self.drains,
            "requeues": self.requeues,
        }
        # cross-engine transport (ISSUE 18): absent on migration-free
        # fleets — the byte-identity contract
        mig_out = sum(e.migrations_out for e in self.engines)
        if mig_out:
            out["migrations"] = mig_out
            out["migration_bytes"] = sum(
                e.migration_bytes for e in self.engines)
            out["migration_restore_s"] = round(
                sum(e.migration_restore_s for e in self.engines), 6)
            # fleet tracing (ISSUE 19): the tail price of one transport
            # hop (source extraction stamp -> destination scatter
            # complete), pooled over every engine's observed hops —
            # absent when tracing is off (no samples), so untraced
            # fleets keep their PR 18 report bytes
            hops = sorted(h for e in self.engines
                          for h in e.transport_hop_s)
            if hops:
                out["transport_hop_s_p99"] = round(
                    percentile(hops, 0.99), 6)
        imb = self.replica_load_imbalance()
        if imb is not None:
            out["replica_load_imbalance"] = round(imb, 4)
        # open-loop SLO attainment (ISSUE 16): fleet attainment from the
        # summed per-engine counters (each engine already counted its
        # own finishes), the merged per-group split, and the summed
        # per-replica backlog peaks — an UPPER BOUND on the
        # instantaneous fleet backlog (the replicas need not have
        # peaked at the same iteration). Gated like the engines' own
        # keys: absent entirely on closed-loop fleets.
        if any(e._has_slo for e in self.engines):
            met = sum(e._slo_met for e in self.engines)
            total = sum(e._slo_total for e in self.engines)
            if total:
                out["slo_attainment"] = round(met / total, 4)
                groups: dict = {}
                for eng in self.engines:
                    for g, (m, t) in eng._group_slo.items():
                        acc = groups.setdefault(g, [0, 0])
                        acc[0] += m
                        acc[1] += t
                out["group_slo_attainment"] = {
                    g: round(m / t, 4)
                    for g, (m, t) in sorted(groups.items()) if t}
        if any(e._has_arrivals for e in self.engines):
            out["arrival_backlog_peak"] = sum(
                e._arrival_backlog_peak for e in self.engines)
        # admission policy (ISSUE 20): fleet rollups, gated exactly
        # like the engines' own riders — fifo / unlimited / deadline-
        # less fleets report byte-identically to the pre-policy router
        if self.policy != "fifo":
            out["policy"] = self.policy
            out["aging_promotions"] = sum(
                e.sched.aging_promotions for e in self.engines)
        if self.rate_limited:
            out["rate_limited"] = self.rate_limited
        dl_total = sum(e._deadline_total for e in self.engines)
        if dl_total:
            out["deadline_miss_frac"] = round(
                sum(e._deadline_miss for e in self.engines)
                / dl_total, 4)
        if any(e._has_priorities for e in self.engines):
            prios: dict = {}
            for eng in self.engines:
                for p, (m, t) in eng._priority_slo.items():
                    acc = prios.setdefault(p, [0, 0])
                    acc[0] += m
                    acc[1] += t
            if prios:
                out["priority_slo_attainment"] = {
                    str(p): round(m / t, 4)
                    for p, (m, t) in sorted(prios.items()) if t}
        if self.placement == "affinity":
            out["affinity_fallbacks"] = self.affinity_fallbacks
        dtok = sum(e.decode_tokens for e in self.engines)
        dsec = sum(e.decode_time_s for e in self.engines)
        if dsec > 0:
            out["decode_tokens_per_sec"] = round(dtok / dsec, 1)
        if self.engines[0].prefix_cache:
            admitted = sum(r.prefix_prompt_tokens for r in reqs)
            cached = sum(r.prefix_cached_tokens for r in reqs)
            out["prefix_cache"] = True
            out["prefix_cached_tokens"] = cached
            out["cache_hit_rate"] = (round(cached / admitted, 4)
                                     if admitted else 0.0)
        per_replica = []
        for i, eng in enumerate(self.engines):
            row = {
                "replica": i,
                "requests": len(eng.finished),
                "peak_waiting_depth": eng.peak_waiting,
                "preemptions": eng.sched.n_preemptions,
                "kv_peak_utilization": round(
                    eng.blocks.peak_used
                    / max(eng.blocks.num_blocks - 1, 1), 4),
            }
            if self.roles is not None:
                row["role"] = self.role_of[i]
            hit = eng._aggregate_hit_rate()
            if hit is not None:
                row["cache_hit_rate"] = round(hit, 4)
            per_replica.append(row)
        out["per_replica"] = per_replica
        if self.roles is not None:
            out["roles"] = (f"prefill:{self.roles['prefill']},"
                            f"decode:{self.roles['decode']}")
            if "slo_attainment" in out:
                # the disaggregation bench/diff metric: the fleet's
                # attainment UNDER role separation, named apart so
                # `obsctl diff` can gate disaggregated runs distinctly
                out["disagg_slo_attainment"] = out["slo_attainment"]
            out["per_role"] = self._per_role(reqs)
        ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
        e2es = sorted(r.finish_t - r.submit_t for r in reqs
                      if r.finish_t is not None and r.submit_t is not None)
        for label, vals in (("ttft", ttfts), ("e2e", e2es)):
            if not vals:
                continue
            out[f"{label}_p50_s"] = round(percentile(vals, 0.50), 6)
            out[f"{label}_p95_s"] = round(percentile(vals, 0.95), 6)
            out[f"{label}_p99_s"] = round(percentile(vals, 0.99), 6)
        return out

    def _per_role(self, reqs) -> dict:
        """Per-role attribution for a disaggregated fleet (ISSUE 18).
        Every request prefills on the prefill side and decodes on the
        decode side, so the split is by PHASE, not by request: the
        prefill row carries the fleet's TTFT percentiles (first tokens
        are emitted by the final prefill chunk) and the decode row the
        TPOT percentiles plus the aggregate decode tokens/sec — the
        two figures the bench line's no-worse-than-mixed side gates
        read."""
        from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
            percentile,
        )

        def pcts(row, label, vals):
            vals = sorted(vals)
            if vals:
                row[f"{label}_p50_s"] = round(percentile(vals, 0.50), 6)
                row[f"{label}_p95_s"] = round(percentile(vals, 0.95), 6)
                row[f"{label}_p99_s"] = round(percentile(vals, 0.99), 6)

        out = {}
        for role in ("prefill", "decode"):
            ids = [i for i in range(self.n) if self.role_of[i] == role]
            engs = [self.engines[i] for i in ids]
            row: dict = {
                "replicas": ids,
                "prefill_chunks": sum(e.prefill_chunks for e in engs),
                "prefill_dispatches": sum(e.prefill_dispatches
                                          for e in engs),
                "decode_steps": sum(e.decode_steps for e in engs),
                "migrations_out": sum(e.migrations_out for e in engs),
                "migrations_in": sum(e.migrations_in for e in engs),
            }
            if role == "prefill":
                pcts(row, "ttft",
                     (r.ttft_s for r in reqs if r.ttft_s is not None))
            else:
                pcts(row, "tpot",
                     ((r.finish_t - r.first_token_t)
                      / max((len(r.prompt) - r.orig_prompt_len)
                            + len(r.output) - 1, 1)
                      for r in reqs
                      if r.finish_t is not None
                      and r.first_token_t is not None))
                dtok = sum(e.decode_tokens for e in engs)
                dsec = sum(e.decode_time_s for e in engs)
                if dsec > 0:
                    row["decode_tokens_per_sec"] = round(dtok / dsec, 1)
            out[role] = row
        return out

    @contextlib.contextmanager
    def draining(self, i: int):
        """``with router.draining(i):`` — drain on entry, restart on
        exit (the rolling-restart shape)."""
        self.drain(i)
        try:
            yield self
        finally:
            self.restart(i)
