"""Multi-replica serving router (ISSUE 14): N :class:`~.engine.
ServeEngine` replicas — each with its own scheduler, BlockManager,
prefix cache, and telemetry stream — behind ONE ``submit()``/``run()``
facade, with pluggable SLO- and prefix-affinity-aware placement.

This is the data-parallel remainder of the scale-out story: PR 13 made
one engine span chips (tensor parallel — a model bigger than a chip);
the router spreads *requests* over N such engines (traffic bigger than
an engine). vLLM-style fleets win most of their throughput at the
replica-level load balancer, and Sarathi-Serve's analysis says tail
latency is won or lost at placement/admission time — and the repo
already emits every signal a smart router needs (the scheduler's live
waiting-depth/KV-pressure gauges, PR 10's queue-wait attribution, the
PR 7 prefix fingerprints), so the router wires them into a placement
policy instead of FIFO-into-one-engine:

- ``round_robin`` — cycle over admitting replicas; the trivially fair
  baseline every policy gate compares against.
- ``least_loaded`` — score each replica by
  ``waiting_depth + occupied_slots + kv_used_frac`` (the engine's own
  live :meth:`~.engine.ServeEngine.load_gauges`, read host-side — the
  router never parses its own telemetry to route) and place on the
  argmin, index-tiebroken so placement is deterministic.
- ``affinity`` — a ROUTER-level prefix-fingerprint index built from
  the same chain-key hashing as the BlockManager's block-level prefix
  cache (:func:`~.paged_kv.prefix_chain_keys`: key N commits to the
  whole token prefix through chunk N): a request routes to the replica
  whose index entry covers its LONGEST hashed prefix — the replica
  most likely to hold its KV blocks warm — so templated families stick
  to a replica and the per-replica prefix caches stay hot instead of
  every replica paying every family's cold miss. The index is a pure
  function of tokens (no block ids), LRU-aged to ``affinity_cap``
  entries, and IMBALANCE-BOUNDED: when the sticky replica is more than
  ``affinity_max_skew`` load units deeper than the lightest sibling
  (default: one full slot batch), the request falls back to
  least-loaded — affinity is a cache heuristic and must never starve
  load balance (the cache-aware admission-ordering follow-up of PR 7,
  generalized across replicas). Any placement is CORRECT: every
  replica produces token-identical output (greedy exact, sampled
  bitwise — per-request seeds), so a stale or evicted index entry
  degrades to a cold cache, never to wrong tokens.

Replica drain/restart — the fleet degrades instead of dying:
:meth:`Router.drain` stops admitting to replica i, lets its RESIDENT
requests finish in place, and requeues its WAITING ones onto siblings
through the normal placement policy (recompute semantics, the same
state the scheduler's preemption/requeue path builds — a preemption
-folded prompt moves unchanged, sampled keys re-derive from the
request's own seed, queue-wait keeps counting from the original submit
stamp). :meth:`Router.restart` re-admits. Every move is telemetered
(``drain`` / ``requeue`` / ``restart`` serve events).

Telemetry: each engine's per-request lifecycle events carry a
``replica`` tag (``obsctl slo`` groups tail attribution by it); the
router's ``run()`` emits one report event per replica plus ONE
aggregate report last (``placement``, ``replicas``,
``replica_load_imbalance`` = max/mean requests served — the figure
``obsctl diff`` watches — and a ``per_replica`` hit-rate/depth
breakdown). A ``replicas=1`` router is a pass-through: it drives the
single engine's own ``run()`` and tags nothing, so its telemetry is
byte-identical to the pre-router engine stream (allowlist-gated).

Compile expectations: replicas over the same model/geometry share the
module-level jitted step families (static keys are (model, plan,
bucket, sampled) — identical across replicas), so N replicas compile
ONE bucket ladder total, not N.
"""

from __future__ import annotations

import contextlib
import os
from collections import OrderedDict
from typing import Optional, Union

import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu import obs
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.engine import (
    ServeEngine,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    prefix_chain_keys,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.scheduler import (
    Request,
)

ENV_REPLICAS = "HSTD_SERVE_REPLICAS"
ENV_PLACEMENT = "HSTD_SERVE_PLACEMENT"

PLACEMENTS = ("round_robin", "least_loaded", "affinity")


def parse_replicas(spec) -> int:
    """The replica-count knob: a positive int. None reads
    ``HSTD_SERVE_REPLICAS`` (default 1 = the single pass-through
    engine, byte-identical telemetry)."""
    if spec is None:
        spec = os.environ.get(ENV_REPLICAS, "1") or "1"
    try:
        n = int(str(spec).strip() or "1")
    except ValueError:
        raise ValueError(f"unparseable {ENV_REPLICAS} value {spec!r}: "
                         "expected a positive integer")
    if n < 1:
        raise ValueError(f"{ENV_REPLICAS} must be >= 1, got {n}")
    return n


def parse_placement(spec: Union[str, None]) -> str:
    """The placement-policy knob: one of ``round_robin`` (default) /
    ``least_loaded`` / ``affinity``. None reads
    ``HSTD_SERVE_PLACEMENT``."""
    if spec is None:
        spec = os.environ.get(ENV_PLACEMENT, "round_robin")
    s = str(spec).strip().lower() or "round_robin"
    if s not in PLACEMENTS:
        raise ValueError(f"unparseable {ENV_PLACEMENT} value {spec!r}: "
                         f"expected {' | '.join(PLACEMENTS)}")
    return s


class Router:
    """N homogeneous :class:`~.engine.ServeEngine` replicas behind one
    facade. ``replicas``/``placement`` read their env knobs when None
    (``HSTD_SERVE_REPLICAS`` / ``HSTD_SERVE_PLACEMENT``); every other
    keyword is forwarded verbatim to EACH replica's engine constructor,
    so the fleet is homogeneous by construction (which is what makes a
    drain-requeued request's submit-time validation transferable).

    ``affinity_cap`` bounds the affinity index (LRU aging — oldest
    fingerprints fall out first, exactly the staleness order the
    per-replica block caches evict in). ``affinity_max_skew`` is the
    load-imbalance bound past which an affinity hit is overridden by
    least-loaded placement (default: one engine's ``num_slots`` — a
    full batch of queue depth buys back a cold prefill, not more).

    Placement changes WHERE a request runs, never WHAT it emits:
    per-request output is token-identical to a single-engine run under
    every policy and across drains (greedy exact, sampled bitwise —
    the engine's own exactness/seed contracts, which are per-request
    and placement-blind)."""

    def __init__(self, model, params, *, replicas=None, placement=None,
                 affinity_cap: int = 4096,
                 affinity_max_skew: Optional[int] = None,
                 **engine_kwargs):
        self.n = parse_replicas(replicas)
        self.placement = parse_placement(placement)
        self.engines = [ServeEngine(model, params, **engine_kwargs)
                        for _ in range(self.n)]
        if self.n > 1:
            for i, eng in enumerate(self.engines):
                eng.replica = i
        self.block_size = self.engines[0].blocks.block_size
        self._rr = 0
        self._draining: set[int] = set()
        self._owner: dict[int, int] = {}        # rid -> replica index
        self.drains = 0
        self.requeues = 0
        self.affinity_cap = int(affinity_cap)
        if self.affinity_cap < 1:
            raise ValueError("affinity_cap must be >= 1")
        if affinity_max_skew is None:
            affinity_max_skew = self.engines[0].num_slots
        self.affinity_max_skew = float(affinity_max_skew)
        self.affinity_fallbacks = 0
        # chain key -> replica index, newest-used last (LRU aging)
        self._affinity: "OrderedDict[int, int]" = OrderedDict()

    # -- placement -----------------------------------------------------------

    def _admitting(self) -> list[int]:
        return [i for i in range(self.n) if i not in self._draining]

    def _load(self, i: int) -> float:
        """One replica's placement score from its live gauges: queued +
        resident requests (each is one unit of service ahead of a new
        arrival) plus the KV pool pressure fraction (breaks ties
        toward the replica with block headroom — the one least likely
        to preempt what it admits)."""
        g = self.engines[i].load_gauges()
        return g["waiting_depth"] + g["running"] + g["kv_used_frac"]

    def _least_loaded(self, cand: list[int]) -> int:
        return min(cand, key=lambda i: (self._load(i), i))

    def _affine(self, prompt, cand: list[int]) -> int:
        """The replica covering the prompt's longest hashed prefix —
        unless it is draining or past the imbalance bound, in which
        case fall back to least-loaded (counted, so the bench can see
        affinity yielding to load balance rather than starving it)."""
        hit: Optional[int] = None
        for key, _chunk in prefix_chain_keys(prompt, self.block_size):
            rep = self._affinity.get(key)
            if rep is None:
                break
            hit = rep                    # deepest indexed level wins
        if hit is None:
            return self._least_loaded(cand)
        if hit not in cand or (self._load(hit)
                               - min(self._load(i) for i in cand)
                               > self.affinity_max_skew):
            self.affinity_fallbacks += 1
            return self._least_loaded(cand)
        return hit

    def _register_affinity(self, prompt, replica: int) -> None:
        """Point every full-chunk fingerprint of ``prompt`` at the
        replica that will prefill (and therefore block-cache) it;
        last-writer-wins on requeue redirects, LRU-aged at
        ``affinity_cap``. The index is a routing heuristic over the
        same chain values the replica's BlockManager indexes — an
        entry outliving the physical blocks just degrades to a cold
        cache on arrival, never to wrong tokens."""
        for key, _chunk in prefix_chain_keys(prompt, self.block_size):
            if key in self._affinity:
                self._affinity.move_to_end(key)
            self._affinity[key] = replica
        while len(self._affinity) > self.affinity_cap:
            self._affinity.popitem(last=False)

    def _place(self, prompt) -> int:
        """The policy's CHOICE only — no state moves here. Callers
        commit via :meth:`_commit_place` once the engine has accepted
        the request: a submit the scheduler rejects (over-length, can
        never fit the pool) must not advance the round-robin cursor or
        pollute the affinity index with fingerprints pointing at a
        replica that will never prefill them."""
        cand = self._admitting()
        if len(cand) == 1:
            return cand[0]
        if self.placement == "round_robin":
            return cand[self._rr % len(cand)]
        if self.placement == "least_loaded":
            return self._least_loaded(cand)
        return self._affine(prompt, cand)

    def _commit_place(self, prompt, choice: int) -> None:
        """Land the placement's state changes for an ACCEPTED request:
        advance the round-robin rotation (only when there was a real
        choice to rotate over), register the prompt's fingerprints at
        the chosen replica."""
        if self.placement == "round_robin":
            if len(self._admitting()) > 1:
                self._rr += 1
        elif self.placement == "affinity":
            self._register_affinity(prompt, choice)

    # -- public API ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, **kw) -> Request:
        """Place one request per the policy and queue it on the chosen
        replica. Same signature/semantics as
        :meth:`~.engine.ServeEngine.submit` — the returned
        :class:`Request` is the engine's own handle."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        i = self._place(prompt)
        req = self.engines[i].submit(prompt, max_new_tokens, **kw)
        self._commit_place(prompt, i)       # only an ACCEPTED submit
        self._owner[req.rid] = i
        return req

    def replica_of(self, req: Union[Request, int]) -> int:
        """Which replica currently owns a request (post-drain requeues
        included)."""
        rid = req.rid if isinstance(req, Request) else int(req)
        return self._owner[rid]

    def output_ids(self, req: Request) -> np.ndarray:
        return self.engines[self._owner[req.rid]].output_ids(req)

    @property
    def finished(self) -> dict[int, Request]:
        """Merged {rid: Request} across replicas (rids are process
        -global, so keys never collide)."""
        out: dict[int, Request] = {}
        for eng in self.engines:
            out.update(eng.finished)
        return out

    def has_work(self) -> bool:
        return any(eng.has_work() for eng in self.engines)

    def warmup(self, sampled: bool = False) -> None:
        """Warm every replica. Replicas share the module-level jitted
        step families (identical static keys), so replica 0 compiles
        the ladder and the rest reuse it — N replicas cost one bucket
        ladder of compiles, not N (the per-replica compile-flatness
        gate the bench enforces)."""
        for eng in self.engines:
            eng.warmup(sampled=sampled)

    def step(self) -> None:
        """One interleaved fleet iteration: each replica with work runs
        one engine iteration. With the engines' dispatch-ahead loop on
        (the default) replica A's device step stays in flight while
        replicas B..N run their whole host side — the router's
        interleave extends the PR 12 overlap across the fleet."""
        for eng in self.engines:
            if eng.has_work():
                eng.step()

    def drain(self, i: int) -> list[Request]:
        """Stop admitting to replica i: its WAITING requests requeue to
        siblings through the normal placement policy (recompute
        semantics — identical tokens, queue clock unreset), its
        RESIDENT requests finish in place, and until :meth:`restart`
        no new placement chooses it. Returns the moved requests.
        Refuses to drain the last admitting replica — a fleet with
        nowhere to admit is an outage, not a drain."""
        if not 0 <= i < self.n:
            raise ValueError(f"replica {i} out of range [0, {self.n})")
        if i in self._draining:
            raise ValueError(f"replica {i} is already draining")
        if len(self._admitting()) <= 1:
            raise ValueError(
                "cannot drain the last admitting replica: restart a "
                "sibling first (a fleet must always have somewhere to "
                "place work)")
        self._draining.add(i)
        self.drains += 1
        moved = self.engines[i].take_waiting()
        for req in moved:
            j = self._place(req.prompt)
            self.engines[j].adopt(req)          # never rejects
            self._commit_place(req.prompt, j)
            self._owner[req.rid] = j
            self.requeues += 1
            obs.serve("requeue", request=req.rid, replica=i,
                      to_replica=j)
        obs.serve("drain", replica=i, requeued=len(moved),
                  placement=self.placement)
        return moved

    def restart(self, i: int) -> None:
        """Re-admit to a drained replica (its pools/caches/compiled
        steps were never torn down — restart is instant)."""
        if i not in self._draining:
            raise ValueError(f"replica {i} is not draining")
        self._draining.discard(i)
        obs.serve("restart", replica=i)

    def run(self) -> dict[int, Request]:
        """Drive the fleet until every submitted request finishes;
        returns the merged {rid: Request}. A single-replica router
        delegates to the engine's own :meth:`~.engine.ServeEngine.run`
        — no router events, no replica tags: the telemetry stream is
        byte-identical to the pre-router engine's (the ``--replicas 1``
        contract). A multi-replica run emits one report event per
        replica (each tagged) and ONE aggregate router report LAST, so
        report consumers that keep the last event
        (``obs/report.py::_serve_summary``) see the fleet view."""
        if self.n == 1:
            return dict(self.engines[0].run())
        self.warmup()
        with obs.span("serve/router_run"):
            while self.has_work():
                self.step()
        for eng in self.engines:
            obs.scalar(
                "serve/kv_peak_utilization",
                eng.blocks.peak_used / max(eng.blocks.num_blocks - 1, 1))
            summary = eng.slo_summary()
            if summary:
                obs.serve("report", **summary)
        summary = self.slo_summary()
        if summary:
            obs.serve("report", **summary)
        return self.finished

    # -- aggregates ----------------------------------------------------------

    def replica_load_imbalance(self) -> Optional[float]:
        """max/mean requests served per replica (1.0 = perfectly even;
        worse UP — the figure ``obsctl diff`` gates as
        ``serve_replica_load_imbalance``). None before any finish."""
        served = [len(eng.finished) for eng in self.engines]
        mean = sum(served) / len(served)
        if mean == 0:
            return None
        return max(served) / mean

    def slo_summary(self) -> dict:
        """The fleet-level SLO summary ({} until a request finishes;
        pass-through to the engine's own for a single-replica router):
        aggregate TTFT/e2e percentiles over every replica's finished
        requests, fleet counters (drains/requeues, summed preemptions
        and tokens), ``replica_load_imbalance``, the aggregate decode
        tokens/sec from the engines' own decode accounting, the
        aggregate prefix-cache hit rate, and a compact ``per_replica``
        breakdown (requests / peak waiting depth / pool peak / hit
        rate) — the figures the ``scripts/serve.py`` summary and the
        bench line surface."""
        if self.n == 1:
            return self.engines[0].slo_summary()
        reqs = [r for eng in self.engines for r in eng.finished.values()]
        if not reqs:
            return {}
        from huggingface_sagemaker_tensorflow_distributed_tpu.obs.report import (
            percentile,
        )

        out: dict = {
            "requests": len(reqs),
            "replicas": self.n,
            "placement": self.placement,
            "tokens": sum(e.tokens_generated for e in self.engines),
            "iterations": sum(e.iterations for e in self.engines),
            "preemptions": sum(e.sched.n_preemptions
                               for e in self.engines),
            "peak_waiting_depth": max(e.peak_waiting
                                      for e in self.engines),
            "drains": self.drains,
            "requeues": self.requeues,
        }
        imb = self.replica_load_imbalance()
        if imb is not None:
            out["replica_load_imbalance"] = round(imb, 4)
        # open-loop SLO attainment (ISSUE 16): fleet attainment from the
        # summed per-engine counters (each engine already counted its
        # own finishes), the merged per-group split, and the summed
        # per-replica backlog peaks — an UPPER BOUND on the
        # instantaneous fleet backlog (the replicas need not have
        # peaked at the same iteration). Gated like the engines' own
        # keys: absent entirely on closed-loop fleets.
        if any(e._has_slo for e in self.engines):
            met = sum(e._slo_met for e in self.engines)
            total = sum(e._slo_total for e in self.engines)
            if total:
                out["slo_attainment"] = round(met / total, 4)
                groups: dict = {}
                for eng in self.engines:
                    for g, (m, t) in eng._group_slo.items():
                        acc = groups.setdefault(g, [0, 0])
                        acc[0] += m
                        acc[1] += t
                out["group_slo_attainment"] = {
                    g: round(m / t, 4)
                    for g, (m, t) in sorted(groups.items()) if t}
        if any(e._has_arrivals for e in self.engines):
            out["arrival_backlog_peak"] = sum(
                e._arrival_backlog_peak for e in self.engines)
        if self.placement == "affinity":
            out["affinity_fallbacks"] = self.affinity_fallbacks
        dtok = sum(e.decode_tokens for e in self.engines)
        dsec = sum(e.decode_time_s for e in self.engines)
        if dsec > 0:
            out["decode_tokens_per_sec"] = round(dtok / dsec, 1)
        if self.engines[0].prefix_cache:
            admitted = sum(r.prefix_prompt_tokens for r in reqs)
            cached = sum(r.prefix_cached_tokens for r in reqs)
            out["prefix_cache"] = True
            out["prefix_cached_tokens"] = cached
            out["cache_hit_rate"] = (round(cached / admitted, 4)
                                     if admitted else 0.0)
        per_replica = []
        for i, eng in enumerate(self.engines):
            row = {
                "replica": i,
                "requests": len(eng.finished),
                "peak_waiting_depth": eng.peak_waiting,
                "preemptions": eng.sched.n_preemptions,
                "kv_peak_utilization": round(
                    eng.blocks.peak_used
                    / max(eng.blocks.num_blocks - 1, 1), 4),
            }
            hit = eng._aggregate_hit_rate()
            if hit is not None:
                row["cache_hit_rate"] = round(hit, 4)
            per_replica.append(row)
        out["per_replica"] = per_replica
        ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
        e2es = sorted(r.finish_t - r.submit_t for r in reqs
                      if r.finish_t is not None and r.submit_t is not None)
        for label, vals in (("ttft", ttfts), ("e2e", e2es)):
            if not vals:
                continue
            out[f"{label}_p50_s"] = round(percentile(vals, 0.50), 6)
            out[f"{label}_p95_s"] = round(percentile(vals, 0.95), 6)
            out[f"{label}_p99_s"] = round(percentile(vals, 0.99), 6)
        return out

    @contextlib.contextmanager
    def draining(self, i: int):
        """``with router.draining(i):`` — drain on entry, restart on
        exit (the rolling-restart shape)."""
        self.drain(i)
        try:
            yield self
        finally:
            self.restart(i)
