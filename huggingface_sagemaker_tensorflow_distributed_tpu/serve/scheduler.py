"""Iteration-level (continuous) batching scheduler — Orca (Yu et al.,
OSDI 2022) semantics over a fixed set of decode slots.

The engine calls :meth:`Scheduler.admit` between decode steps; requests
join/leave the running batch at TOKEN granularity instead of waiting for
a whole static batch to drain. The slot count is fixed so every jitted
dispatch keeps one shape (zero recompiles after warmup); an empty slot
simply rides along masked (its writes go to the KV pool's null block).

States: WAITING (queued) → PREFILL (chunked prompt ingestion, one chunk
per engine iteration) → DECODE (one token per decode step) → FINISHED.
Preemption (KV pool exhausted mid-decode) is vLLM-style *recompute*: the
victim — always the youngest running request, so the head of the line
never livelocks — releases every block and re-enters the queue front
with ``prompt + generated-so-far`` as its new prompt; under greedy
decoding the recomputed continuation is exactly what it would have
produced uninterrupted, so preemption changes latency, never tokens.

Prefix caching (ISSUE 8): with ``prefix_cache=True`` admission first
looks up the longest cached full-block prefix of the prompt
(:meth:`~.paged_kv.BlockManager.match_prefix`), points the request's
block table at the shared blocks, and charges the pool only for the
PRIVATE remainder — shared blocks are paid for once, pool-wide, which
is what multiplies effective KV capacity under templated traffic.
Prefill then starts at the prefill-chunk grid point at/below the
cached boundary (``prefill_pos`` > 0); chunk-grid overlap blocks the
rewrite would scatter into are privatized (copy-on-write) AT ADMISSION,
inside the same capacity check, so a prefill dispatch can never die on
a COW allocation. Preemption of a prefix-sharing request releases only
its references — other holders (and the cache) keep the shared blocks.

Pure host-side Python over :class:`~.paged_kv.BlockManager` — all policy
is unit-testable with no jax backend.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.serve.paged_kv import (
    BlockManager,
    PoolExhausted,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.serve.policy import (
    make_policy,
    parse_aging_s,
    parse_policy,
)

WAITING, PREFILL, DECODE, FINISHED = "waiting", "prefill", "decode", "finished"

_rid = itertools.count()


@dataclass
class Request:
    """One generation request. ``prompt`` is token ids [P]; the engine
    appends generated ids to ``output``. Timing fields are engine-side
    ``perf_counter`` stamps (None until reached).

    Sampling is per request: ``temperature == 0`` (the default) is
    greedy — the mode the engine's exactness gate vs ``generate_causal``
    pins; ``temperature > 0`` samples with optional top-k/top-p
    truncation, seeded by ``seed`` so the stream is reproducible
    (including across recompute preemption — the engine derives the
    n-th token's PRNG key from (seed, n) alone)."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = field(default_factory=lambda: next(_rid))
    output: list = field(default_factory=list)
    state: str = WAITING
    submit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    preemptions: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    # speculative-decode accounting (serve engine): draft tokens
    # proposed for / accepted by this request's verify windows — the
    # per-request acceptance rate the finish telemetry event carries
    spec_proposed: int = 0
    spec_accepted: int = 0
    # prefix-cache accounting: prompt tokens served out of shared KV
    # blocks vs prompt tokens admitted, summed across (re-)admissions —
    # the per-request cache_hit_rate the finish event carries
    prefix_cached_tokens: int = 0
    prefix_prompt_tokens: int = 0
    # lifecycle tracing (ISSUE 10): the engine stamps host-side phase
    # accounting here when its `timeline` knob is on — wall seconds per
    # phase (queue / prefill / decode / preempted; overhead is derived
    # at emission) and the compact coalesced segment list the
    # `request_timeline` telemetry event carries. `group` is an opaque
    # caller-supplied key (tenant, route, experiment arm) the SLO
    # attribution report aggregates by.
    group: str = ""
    phase_s: dict = field(default_factory=lambda: {
        "queue": 0.0, "prefill": 0.0, "decode": 0.0, "preempted": 0.0})
    segments: list = field(default_factory=list)
    preempt_t: Optional[float] = None
    # dispatch-ahead attribution cursor (ISSUE 12): where this
    # request's last attributed decode interval ended. Under overlap a
    # dispatch N is enqueued BEFORE iteration N−1's fetch lands, so
    # the per-request decode window [dispatch, fetch] of consecutive
    # iterations would overlap; clipping each window's start to this
    # cursor keeps the attributed intervals disjoint (the checkable-
    # decomposition invariant) while still counting the host work that
    # ran concurrently with the device as decode time, not overhead.
    decode_attr_end: Optional[float] = None
    blocked_iters: int = 0
    blocked_reason: Optional[str] = None
    cow_copies: int = 0
    # open-loop SLO contract (ISSUE 16): `arrival_s` is the request's
    # ARRIVAL stamp in the engine's perf_counter domain — distinct from
    # `submit_t`, so queue wait decomposes into pre-submit backlog
    # (submit_t − arrival_s: time the load generator held the request)
    # + in-engine queue (admit − submit_t). The slo_* targets are
    # deadline seconds (None = no target on that axis); the engine
    # writes the verdicts at finish — slack_s is the TIGHTEST remaining
    # margin across the set targets, negative on a miss.
    arrival_s: Optional[float] = None
    slo_ttft_s: Optional[float] = None
    slo_tpot_s: Optional[float] = None
    slo_met: Optional[bool] = None
    ttft_slo_met: Optional[bool] = None
    tpot_slo_met: Optional[bool] = None
    slack_s: Optional[float] = None
    # admission-policy contract (ISSUE 20): `deadline_s` is an
    # END-TO-END deadline in seconds measured from the request's
    # origin (arrival_s when the open-loop driver threaded one, else
    # submit_t); `priority` is the admission class, smaller = more
    # urgent, 0 default. Under policy=slo these order WHO admits WHEN
    # — never WHAT (outputs stay token-identical under every policy).
    # `aging_promoted` flips once the request waits past the
    # scheduler's aging bound (the starvation counter telemetry sums);
    # `deadline_miss` is the engine's finish verdict (None = no
    # deadline set).
    deadline_s: Optional[float] = None
    priority: int = 0
    aging_promoted: bool = False
    deadline_miss: Optional[bool] = None
    # swap-based preemption (ISSUE 17): the extracted host-side
    # BlockSet a swapped-out victim carries while WAITING, and the
    # context length it restores to. Unlike recompute, the generated
    # tokens stay in `output` (nothing folds into the prompt) — the
    # request resumes decoding from output[-1] the moment its blocks
    # scatter back, no re-prefill.
    swap_set: Optional[object] = None
    swap_context: int = 0
    # recompute preemption folds generated tokens back into the prompt;
    # this keeps the ORIGINAL prompt length so output accounting and
    # first-token semantics survive a preemption
    orig_prompt_len: int = field(default=-1)
    # fleet tracing (ISSUE 19): the Router mints `trace_id` at submit
    # and it rides the request across every engine it visits; `hop`
    # counts inter-engine moves (migrate_request, drain requeue) — 0
    # on the placement engine. `migrate_out_t` is the source-side
    # perf_counter stamp taken just before extraction and
    # `migrate_extract_s` the extraction seconds, both consumed by the
    # destination's restore apply to price the transport hop; empty
    # trace_id = tracing off (single-engine runs), which keeps every
    # telemetry event byte-identical to the pre-tracing stream.
    trace_id: str = ""
    hop: int = 0
    migrate_out_t: Optional[float] = None
    migrate_extract_s: float = 0.0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.orig_prompt_len < 0:
            self.orig_prompt_len = len(self.prompt)
        if len(self.prompt) < 1:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not isinstance(self.group, str):
            raise ValueError("group must be a string")
        for name in ("slo_ttft_s", "slo_tpot_s"):
            target = getattr(self, name)
            if target is not None and not target > 0:
                raise ValueError(f"{name} must be > 0 when set")
        if self.deadline_s is not None and not self.deadline_s > 0:
            raise ValueError("deadline_s must be > 0 when set")
        if isinstance(self.priority, bool) or not isinstance(
                self.priority, int):
            raise ValueError("priority must be an integer class "
                             "(smaller = more urgent)")

    @property
    def sampled(self) -> bool:
        return self.temperature > 0

    @property
    def has_slo(self) -> bool:
        return self.slo_ttft_s is not None or self.slo_tpot_s is not None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def cache_hit_rate(self) -> Optional[float]:
        """Fraction of admitted prompt tokens served from shared KV
        blocks (None before any admission)."""
        if self.prefix_prompt_tokens == 0:
            return None
        return self.prefix_cached_tokens / self.prefix_prompt_tokens


class Slot:
    """One decode slot's device-side bookkeeping: the physical block
    table, how much context is resident (``context_len``), and how far
    prefill has progressed (``prefill_pos``, over the CHUNK-PADDED
    prompt width)."""

    def __init__(self, index: int):
        self.index = index
        self.request: Optional[Request] = None
        self.table: list[int] = []
        self.context_len = 0
        self.prefill_pos = 0
        self.admit_seq = -1          # admission order, for victim choice
        # copy-on-write pool copies admission queued for this slot:
        # (src, dst) block pairs the ENGINE must apply to every pool
        # before the slot's first prefill dispatch
        self.pending_copies: list[tuple[int, int]] = []
        # host-RAM spill tier (ISSUE 17): (block, payload) revivals
        # admission queued — host-tier prefix payloads the ENGINE must
        # scatter into the listed fresh blocks before the slot's first
        # dispatch — and the swapped-out request's whole BlockSet,
        # restored into `table` at re-admission (same timing contract)
        self.pending_restores: list[tuple[int, object]] = []
        self.pending_swap_in: Optional[object] = None
        # dispatch-ahead pipeline (ISSUE 12): 1 while this slot rides
        # an in-flight decode dispatch whose token has not been
        # fetched yet — its newest token lives on the DEVICE, and its
        # host-visible generated count runs one behind by exactly this
        # amount (the engine's budget-finish prediction and sampled
        # fold indices add it back)
        self.inflight = 0

    @property
    def free(self) -> bool:
        return self.request is None

    def clear(self) -> None:
        self.request = None
        self.table = []
        self.context_len = 0
        self.prefill_pos = 0
        self.admit_seq = -1
        self.pending_copies = []
        self.pending_restores = []
        self.pending_swap_in = None
        self.inflight = 0


class Scheduler:
    """Admission into ``num_slots`` decode slots, chunked prefill,
    recompute preemption. The engine owns the clock and the device; this
    class owns WHO runs. Admission ORDER is pluggable (ISSUE 20):
    ``policy="fifo"`` (default) walks ``waiting[0]`` exactly as the
    pre-policy scheduler did — byte-identical telemetry — while
    ``policy="slo"`` ranks the queue by the aging-bounded
    deadline/priority/cache-aware key of :mod:`~.serve.policy`. Either
    way a policy only reorders admission; preemption, capacity math
    and per-request outputs are untouched.

    Under the engine's dispatch-ahead loop (ISSUE 12) every decision
    here consumes LAGGED observations: one decode dispatch may be in
    flight, so a slot freed by an un-fetched EOS is not yet free at
    admission time, and a riding slot's ``context_len`` was already
    advanced at dispatch (the write lands regardless of the token's
    value). That advance is what keeps the block math exact — the
    ``decode_lookahead`` reservation measured from the advanced
    context covers the in-flight step's write span by construction —
    and the engine drains the pipeline before any path that can
    preempt, so recompute always folds fully committed output.

    Under a tensor-parallel engine (ISSUE 13) nothing here changes:
    all capacity math is denominated in BLOCKS, and a block is a
    mesh-wide logical unit (every device holds its head slice of it).
    The per-device re-denomination happens one layer down — the
    engine hands :class:`~.paged_kv.BlockManager` each SHARD's
    bytes/token, so a byte budget buys ``tp``× the blocks and this
    scheduler's unchanged block-denominated admission math admits
    ``tp``× the concurrent requests on the same per-chip memory."""

    def __init__(self, num_slots: int, blocks: BlockManager,
                 prefill_chunk: int, max_model_len: int,
                 decode_lookahead: int = 1, prefix_cache: bool = False,
                 policy=None, aging_s=None):
        if num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        if decode_lookahead < 1:
            raise ValueError("decode_lookahead must be >= 1")
        if max_model_len % blocks.block_size:
            raise ValueError(
                f"max_model_len {max_model_len} must be a multiple of "
                f"block_size {blocks.block_size}")
        if max_model_len % prefill_chunk:
            # padded_prompt_len must never exceed max_model_len (the
            # engine's block tables are sized for it): with the chunk
            # dividing the width, ceil(p/C)*C <= max_model_len for
            # every admissible prompt
            raise ValueError(
                f"max_model_len {max_model_len} must be a multiple of "
                f"prefill_chunk {prefill_chunk}")
        self.slots = [Slot(i) for i in range(num_slots)]
        self.blocks = blocks
        self.prefill_chunk = int(prefill_chunk)
        self.max_model_len = int(max_model_len)
        # tokens a decode dispatch may WRITE past each slot's resident
        # context: 1 for plain decode, speculate_k + 1 for a
        # speculative engine's draft/verify window — every decode-side
        # capacity decision (submit-time worst case, per-iteration
        # block growth, gather-bucket need) reserves this span so a
        # verify dispatch can never address past its block table
        self.decode_lookahead = int(decode_lookahead)
        self.prefix_cache = bool(prefix_cache)
        self.waiting: list[Request] = []
        self._admit_seq = itertools.count()
        self._prefill_rr = 0
        self.n_preemptions = 0
        # admission policy (ISSUE 20): None for fifo — the original
        # admit path runs bit-for-bit. `policy_now` is the virtual
        # clock override the open-loop driver installs so aging and
        # deadline arithmetic replay deterministically; None = wall
        # (perf_counter, the engine's stamp domain).
        self.policy = parse_policy(policy)
        self.aging_s = parse_aging_s(aging_s)
        self._policy = make_policy(self.policy, self.aging_s)
        self.aging_promotions = 0
        self.policy_now: Optional[float] = None
        # swap-based preemption (ISSUE 17): the engine installs a
        # `hook(slot) -> bool` that may extract the victim's blocks to
        # host BEFORE release (True = swapped; the request's `swap_set`
        # is set and :meth:`preempt` skips the recompute prompt fold).
        # None = pure recompute, byte-identical to the pre-swap engine.
        self.swap_hook = None

    # -- queue side ----------------------------------------------------------

    def submit(self, request: Request) -> None:
        total = len(request.prompt) + request.max_new_tokens
        if total + self.decode_lookahead - 1 > self.max_model_len:
            extra = ("" if self.decode_lookahead == 1 else
                     f" + verify-window lookahead "
                     f"{self.decode_lookahead - 1}")
            raise ValueError(
                f"request {request.rid}: prompt {len(request.prompt)} + "
                f"max_new_tokens {request.max_new_tokens}{extra} exceeds "
                f"max_model_len {self.max_model_len}")
        # worst-case lifetime block need: admission reserves the padded
        # prompt, decode grows to `total`, and a preemption at
        # max_new - 1 folds the generation back into a prompt padded up
        # to a chunk multiple again. A request whose worst case exceeds
        # the WHOLE pool can never run — admit() would park it at the
        # queue head forever (or a lone decode slot would preempt
        # itself in a loop), so reject at submit instead of livelocking.
        worst = max(self.padded_prompt_len(request),
                    total + self.decode_lookahead - 1,
                    -(-(total - 1) // self.prefill_chunk)
                    * self.prefill_chunk)
        need = self.blocks.blocks_for(worst)
        capacity = self.blocks.num_blocks - 1
        if need > capacity:
            raise ValueError(
                f"request {request.rid} can need {need} KV blocks "
                f"(context {worst} at block_size "
                f"{self.blocks.block_size}) but the pool only holds "
                f"{capacity}: grow num_blocks or shrink the request")
        self.waiting.append(request)

    def has_work(self) -> bool:
        return bool(self.waiting) or any(not s.free for s in self.slots)

    def take_waiting(self) -> list[Request]:
        """Drain hook (ISSUE 14, multi-replica router): remove and
        return every WAITING (not-yet-admitted) request, preemption
        -requeued ones included — the router moves them onto sibling
        replicas (recompute semantics: a folded prompt rides along
        unchanged, exactly the state :meth:`preempt` builds). Resident
        requests are untouched; a draining replica finishes them
        itself."""
        moved, self.waiting = self.waiting, []
        return moved

    def adopt(self, request: Request) -> None:
        """Requeue hook (ISSUE 14): append an EXISTING request — a
        sibling replica's drain victim — to this queue WITHOUT the
        :meth:`submit` validation or a fresh submit stamp. The
        original submit already validated the worst-case block need
        (replicas are homogeneous, and the submit-time formula covers
        every preemption-folded state of the request), and re-running
        it on a folded prompt would double-count the generated tokens
        and spuriously reject requests near ``max_model_len`` — the
        same reason :meth:`preempt` re-inserts directly. Queue-wait
        accounting keeps running from the ORIGINAL submit stamp, so a
        drain shows up as queue time, never as a reset clock."""
        self.waiting.append(request)

    def adopt_resident(self, request: Request) -> None:
        """Migration hook (ISSUE 18): enqueue a sibling engine's LIVE
        resident at the queue FRONT. The request already held a slot —
        it carries its extracted block set (``swap_set``, restored
        through :meth:`_reserve_swapped` with zero re-prefill) or, for
        a mid-prefill cold move, just its unmodified prompt — and a
        migration must not demote it behind work that was never
        admitted. FIFO order among multiple migrants is the CALLER's
        job (insert in reverse admission order); validation is skipped
        for the same reason :meth:`adopt` skips it, except that a
        HETEROGENEOUS destination's geometry is no longer covered by
        the original submit — :func:`~.transport.can_accept` re-checks
        it before the transplant."""
        self.waiting.insert(0, request)

    # -- admission -----------------------------------------------------------

    def padded_prompt_len(self, request: Request) -> int:
        """Prompt width after right-padding to a prefill-chunk multiple
        (the engine's prefill dispatch is one static chunk shape)."""
        p = len(request.prompt)
        return p + (-p % self.prefill_chunk)

    def admit(self) -> list[Slot]:
        """Move waiting requests into free slots while block capacity
        holds. Admission reserves the FULL padded-prompt block span up
        front so prefill can never die mid-prompt; the pad tail's
        blocks are trimmed back at prefill completion. With
        ``prefix_cache`` the reservation is denominated in PRIVATE
        blocks: the longest cached full-block prefix is mapped onto
        shared blocks (charged to the pool once, whoever admitted them
        first), prefill starts at the chunk-grid point at/below the
        cached boundary, and the chunk-grid overlap — shared blocks
        the first prefill chunk rewrites — is privatized (COW) here,
        inside the same capacity check. Returns the slots admitted
        this call. Order is the policy's: fifo walks the queue head
        only; slo ranks the whole queue once per call and lets a
        smaller-demand candidate fill a slot the front-runner cannot
        — EXCEPT past an aging-promoted request, where admission
        stops entirely (the strict starvation bound: nothing younger
        queue-jumps a starving request, and liveness holds because
        :meth:`submit` already rejected can-never-fit requests)."""
        if self._policy is None:
            return self._admit_fifo()
        return self._admit_policy()

    def _admit_fifo(self) -> list[Slot]:
        admitted = []
        for slot in self.slots:
            if not self.waiting:
                break
            if not slot.free:
                continue
            if not self._try_reserve(self.waiting[0], slot):
                break                       # FIFO: no queue-jumping
            self.waiting.pop(0)
            admitted.append(slot)
        return admitted

    def _admit_policy(self) -> list[Slot]:
        now = self.policy_clock()
        for req in self.waiting:
            if not req.aging_promoted and self._policy.promoted(req, now):
                req.aging_promoted = True
                self.aging_promotions += 1
        ranked = self._policy.rank(self.waiting, now,
                                   self._demand_blocks)
        admitted = []
        for slot in self.slots:
            if not ranked:
                break
            if not slot.free:
                continue
            chosen = None
            for req in ranked:
                if self._try_reserve(req, slot):
                    chosen = req
                    break
                if req.aging_promoted:
                    # a promoted (starving) request that cannot fit
                    # blocks ALL younger admission — the aging bound
                    # is strict, not advisory
                    ranked = []
                    break
            if chosen is None:
                break
            # remove by identity: Request field equality can compare
            # array prompts elementwise
            ranked = [r for r in ranked if r is not chosen]
            for i, r in enumerate(self.waiting):
                if r is chosen:
                    del self.waiting[i]
                    break
            admitted.append(slot)
        return admitted

    def _try_reserve(self, req: Request, slot: Slot) -> bool:
        """Reserve ``slot`` for ``req`` (swapped or fresh) — True on
        success with the slot fully populated, False with every
        acquired reference rolled back. Shared by both admit orders so
        the reservation semantics cannot drift between policies."""
        if req.swap_set is not None:
            return self._reserve_swapped(req, slot)
        table, start0, copies, restores = self._reserve(req)
        if table is None:
            return False
        slot.request = req
        slot.table = table
        slot.context_len = 0
        slot.prefill_pos = start0
        slot.pending_copies = copies
        slot.pending_restores = restores
        slot.admit_seq = next(self._admit_seq)
        req.state = PREFILL
        return True

    def policy_clock(self) -> float:
        """The admission policy's clock: the driver-installed virtual
        stamp when set (deterministic open-loop replay), else wall
        ``perf_counter`` — the same domain as every request stamp."""
        return (time.perf_counter() if self.policy_now is None
                else self.policy_now)

    def blocked_head(self) -> Optional[Request]:
        """The request whose admission is blocked when slots/KV run
        out — ``waiting[0]`` under fifo, the policy's top-ranked
        candidate otherwise. The engine attributes blocked-iteration
        telemetry to it."""
        if not self.waiting:
            return None
        if self._policy is None:
            return self.waiting[0]
        return self._policy.rank(self.waiting, self.policy_clock(),
                                 self._demand_blocks)[0]

    def _demand_blocks(self, req: Request) -> int:
        """Predicted service demand in KV blocks for the policy key:
        the padded-prompt block need minus the ``peek_prefix`` cached
        span (a refcount-neutral, LRU-neutral probe), so under KV
        pressure the largest-cached-prefix request ranks first. A
        swapped-out victim's demand is exactly its extracted set."""
        if req.swap_set is not None:
            return int(req.swap_set.n_blocks)
        need = self.blocks.blocks_for(self.padded_prompt_len(req))
        if self.prefix_cache:
            bs = self.blocks.block_size
            shared, _ = self.blocks.peek_prefix(
                req.prompt, max_blocks=(len(req.prompt) - 1) // bs)
            need -= len(shared)
        return need

    def _reserve_swapped(self, req: Request, slot: Slot) -> bool:
        """Re-admit a SWAPPED-OUT request (ISSUE 17): allocate exactly
        the blocks its extracted :class:`~.paged_kv.BlockSet` fills,
        hand the set to the engine as the slot's pending swap-in (the
        scatter must land before any dispatch reads the table — the
        pending-copies timing contract), and resume in DECODE directly:
        the restored context IS the prefill, no prompt recompute. The
        generated tokens never left ``req.output``, so the decode feed
        (``output[-1]``) and the sampled fold indices are exactly the
        uninterrupted run's."""
        n = req.swap_set.n_blocks
        # charge the decode lookahead on top of the restored blocks so
        # the re-admitted request cannot bounce straight back out on
        # its first post-restore capacity check
        ahead = self.blocks.blocks_for(
            req.swap_context + self.decode_lookahead) - n
        if not self.blocks.can_allocate(n + max(0, ahead)):
            return False
        slot.request = req
        slot.table = self.blocks.allocate(n)
        slot.context_len = req.swap_context
        slot.prefill_pos = 0
        slot.pending_copies = []
        slot.pending_swap_in = req.swap_set
        slot.admit_seq = next(self._admit_seq)
        req.swap_set = None
        req.state = DECODE
        return True

    def _reserve(self, req: Request):
        """One request's admission reservation: ``(table, prefill_pos,
        cow_copies, host_restores)``, or ``(None, 0, [], [])`` when the
        pool cannot carry it yet (every acquired reference rolled
        back)."""
        bs = self.blocks.block_size
        C = self.prefill_chunk
        padded = self.padded_prompt_len(req)
        total_need = self.blocks.blocks_for(padded)
        if not self.prefix_cache:
            if not self.blocks.can_allocate(total_need):
                return None, 0, [], []
            return self.blocks.allocate(total_need), 0, [], []
        # the final prompt token is never served from cache — its
        # logits seed generation, so its block stays recomputed. Peek
        # first, commit only once capacity is assured: a failed probe
        # re-runs EVERY engine iteration while this request heads the
        # queue, and it must neither churn refcounts nor re-park LRU
        # entries as freshly used (which would bias eviction toward
        # everyone else's prefixes)
        max_cached = (len(req.prompt) - 1) // bs
        shared, revivals = self.blocks.peek_prefix(
            req.prompt, max_blocks=max_cached)
        # host-RAM spill tier (ISSUE 17): chunks past the device match
        # may still be resident host-side (demoted before eviction) —
        # each hit extends the cached prefix at the cost of one fresh
        # block plus the engine-applied scatter of its payload
        hosted_keys: list[int] = []
        host_missed = False
        if self.blocks.host_tier_active:
            hosted_keys, host_missed = self.blocks.peek_hosted(
                req.prompt, len(shared), max_blocks=max_cached)
        cached = (len(shared) + len(hosted_keys)) * bs
        # prefill resumes on the chunk grid; the overlap [start0,
        # cached) gets rewritten (with identical values) and must be
        # privately owned before the dispatch scatters into it
        start0 = (cached // C) * C
        overlap = cached // bs - start0 // bs
        private_need = total_need - len(shared) - len(hosted_keys)
        # committing the match pulls `revivals` blocks out of the
        # evictable LRU, so they are charged alongside the private
        # need; every host-tier revival additionally needs a fresh
        # device block to scatter its payload into
        if not self.blocks.can_allocate(
                private_need + overlap + revivals + len(hosted_keys)):
            return None, 0, [], []
        # pin the matched payloads across the commit: the allocations
        # below may evict cached blocks, and spilling those under a
        # tight host budget must not push the matched (still LRU-cold)
        # entries out before revive_hosted lands
        self.blocks.host_pin(hosted_keys)
        try:
            self.blocks.commit_match(shared)
            revive_blocks = self.blocks.allocate(len(hosted_keys))
            restores = self.blocks.revive_hosted(hosted_keys,
                                                 revive_blocks)
            if self.blocks.host_tier_active:
                self.blocks.note_host_probe(len(hosted_keys),
                                            host_missed)
            table = (shared + revive_blocks
                     + self.blocks.allocate(private_need))
            copies = self.blocks.privatize(table, start0 // bs,
                                           cached // bs)
        finally:
            self.blocks.host_unpin(hosted_keys)
        req.prefix_cached_tokens += start0
        req.prefix_prompt_tokens += len(req.prompt)
        return table, start0, copies, restores

    # -- prefill -------------------------------------------------------------

    def next_prefill_slots(self, max_n: int) -> list[Slot]:
        """Up to ``max_n`` DISTINCT prefill-state slots, round-robin
        from where the last call left off — the batch the engine packs
        into ONE prefill dispatch. Rotation is preserved across calls so
        no prefilling request starves when more exist than fit a
        dispatch."""
        n = len(self.slots)
        out: list[Slot] = []
        for k in range(n):
            if len(out) >= max_n:
                break
            slot = self.slots[(self._prefill_rr + k) % n]
            if slot.request is not None and slot.request.state == PREFILL:
                out.append(slot)
        if out:
            self._prefill_rr = (out[-1].index + 1) % n
        return out

    def prefill_token_budget(self, n_active_decode: int) -> int:
        """The iteration's prefill budget in TOKENS-PER-DISPATCH terms
        (Sarathi-flavored, redefined for batched prefill): with a full
        decode batch exactly one chunk's worth of tokens runs per
        iteration — bounding the decode stall a long prompt can inject
        to one chunk of compute — and every idle decode slot buys one
        more chunk of tokens, which the engine packs into as few
        batched dispatches as possible (refilling drained slots fast is
        worth more than the stall when the batch is running light)."""
        idle = max(1, len(self.slots) - n_active_decode)
        return self.prefill_chunk * idle

    def finish_prefill(self, slot: Slot) -> None:
        """Prefill consumed the whole padded prompt: context becomes the
        REAL prompt length, pad-tail blocks return to the pool, the
        prompt's full blocks are published into the prefix index (their
        KV is complete and final — registered blocks are read-only from
        here on), and the slot starts decoding."""
        req = slot.request
        slot.context_len = len(req.prompt)
        self.blocks.trim(slot.table, slot.context_len)
        if self.prefix_cache:
            # a speculative engine's preemption-resume path REWRITES
            # position p-1 (the folded prompt tail, re-fed through the
            # verify window) — so with a verify lookahead the block
            # containing it must never be published read-only
            tokens = (req.prompt if self.decode_lookahead == 1
                      else req.prompt[:len(req.prompt) - 1])
            self.blocks.register_prefix(tokens, slot.table)
        req.state = DECODE

    # -- decode-side capacity ------------------------------------------------

    def decode_slots(self) -> list[Slot]:
        return [s for s in self.slots
                if s.request is not None and s.request.state == DECODE]

    def max_decode_context(self) -> int:
        """The iteration's max decode context INCLUDING every position a
        dispatch may write this step (``context_len + decode_lookahead``:
        one slot for plain decode, the whole draft/verify window for a
        speculative engine) — the quantity the engine's gather-bucket
        choice covers. 0 with no decode work."""
        return max((s.context_len + self.decode_lookahead
                    for s in self.decode_slots()), default=0)

    def ensure_decode_capacity(self) -> list[Request]:
        """Guarantee every DECODE slot owns blocks for every position
        the next dispatch may write (``context_len + decode_lookahead``),
        preempting youngest-first when the pool runs dry. Returns the
        requests preempted this call. Termination: each preemption
        frees ≥ 1 block and empties a slot, and a lone decode slot can
        always be satisfied by the blocks everyone else released (its
        worst-case span was bounded at submit)."""
        preempted = []
        while True:
            ds = self.decode_slots()
            if not ds:
                return preempted
            short = [s for s in ds
                     if self.blocks.blocks_for(
                         s.context_len + self.decode_lookahead)
                     > len(s.table)]
            try:
                for slot in short:
                    self.blocks.grow(slot.table,
                                     slot.context_len + self.decode_lookahead)
                for slot in ds:
                    # the next dispatch writes [context, context +
                    # lookahead): that span is past the cached prompt
                    # prefix, hence private by construction — enforced
                    # here so a sharing bug fails loudly, not by
                    # clobbering another request's (or the cache's) KV
                    self.blocks.ensure_private(
                        slot.table, slot.context_len // self.blocks.block_size,
                        self.blocks.blocks_for(
                            slot.context_len + self.decode_lookahead))
                return preempted
            except PoolExhausted:
                victim = max(ds, key=lambda s: s.admit_seq)
                victim_req = victim.request
                self.preempt(victim)
                preempted.append(victim_req)

    def preempt(self, slot: Slot) -> None:
        """Preempt one slot, rejoining the queue FRONT (it keeps its
        place — preemption must not reorder FIFO service). Default is
        vLLM recompute: release everything and fold the generated
        tokens into the prompt. With a swap hook installed (ISSUE 17)
        the hook may instead extract the victim's resident blocks to
        host BEFORE the release — the request then carries its
        ``swap_set`` while waiting and re-admits straight into DECODE,
        output unfolded, no re-prefill. Either way the blocks release
        here (swap extraction only COPIES), so the pool sees one
        preemption semantics."""
        req = slot.request
        swapped = bool(self.swap_hook is not None
                       and self.swap_hook(slot))
        if not swapped:
            req.prompt = np.concatenate(
                [req.prompt, np.asarray(req.output, np.int32)])
            req.output = []
        req.state = WAITING
        req.preemptions += 1
        self.n_preemptions += 1
        self.blocks.release(slot.table)
        slot.clear()
        self.waiting.insert(0, req)

    def finish(self, slot: Slot) -> Request:
        """Request complete: publish its GENERATED tail into the
        prefix index (ISSUE 12 / PR 7a follow-up), then release the
        table. At finish every resident position's K/V is final —
        prompt AND generated — so the whole ``context_len`` span's
        full aligned blocks are registerable, which is what makes
        agentic multi-turn traffic (a client re-submitting its own
        completion as the next prompt) hit the cache instead of
        re-prefilling its own output. ``register_prefix`` only indexes
        FULL ``block_size`` chunks covered by the table, so the
        partially-filled last block (and, under the dispatch-ahead
        loop, any stale in-flight write past ``context_len``) is never
        published. Zero-ref registered blocks park in the LRU on
        release — reusable until pool pressure evicts them."""
        req = slot.request
        req.state = FINISHED
        if self.prefix_cache and slot.context_len > 0:
            full = np.concatenate(
                [req.prompt, np.asarray(req.output, np.int32)])
            self.blocks.register_prefix(full[:slot.context_len],
                                        slot.table)
        self.blocks.release(slot.table)
        slot.clear()
        return req
