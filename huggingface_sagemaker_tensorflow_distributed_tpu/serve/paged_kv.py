"""Paged KV cache bookkeeping: a fixed population of fixed-size blocks,
allocated to requests as their context grows (vLLM, Kwon et al. 2023).

The device side is dumb on purpose — per-layer pools
``[num_blocks, block_size, heads, head_dim]`` plus the gather/scatter
addressing in ``ops.attention`` — so ALL allocation policy lives here in
plain host Python where it is unit-testable without a backend:

- :class:`BlockManager` owns the free list. Block 0 is reserved as the
  **null block**: inactive decode slots scatter their (discarded) step
  writes there, which is what lets the engine's jitted step keep fully
  static shapes with no per-step masking of the write path.
- memory scales with tokens actually resident: a request holds
  ``ceil(context / block_size)`` blocks, not ``max_model_len`` slots.
  Fragmentation is bounded by ``block_size - 1`` tokens per request
  (the partially-filled last block) — the quantity
  :meth:`BlockManager.fragmentation` reports and the tests pin.
- the READ side wastes separately: every decode step gathers a full
  context-width bucket per slot regardless of how much context the slot
  actually holds. :meth:`BlockManager.note_gather` accounts that
  bucket-padded read waste (peak + token-weighted mean) so the serve
  report can show what width bucketing saves.

Prefix caching (ISSUE 8) adds block-level SHARING on top: every block
carries a refcount, and full ``block_size``-aligned prompt-prefix
chunks are indexed by a rolling hash chain (block N's key includes
blocks 0..N-1's tokens) so identical prompt prefixes across requests
map onto the SAME physical blocks. Lifecycle:

- :meth:`match_prefix` walks the chain for a new prompt, increfs every
  hit, and returns the shared block ids — the engine points the
  request's block table at them and skips their prefill compute.
- :meth:`register_prefix` (at prefill completion) publishes a request's
  full prompt blocks into the index; registered blocks are READ-ONLY.
- :meth:`release` (replacing raw ``free``) decrefs; a zero-ref
  REGISTERED block parks in an LRU of cached blocks — still reusable
  by future lookups, reclaimed oldest-first by :meth:`allocate` only
  under pool pressure. Unregistered zero-ref blocks return to the free
  list immediately.
- :meth:`privatize` is copy-on-write: a request about to scatter into
  a block with refcount > 1 gets a fresh private copy (the caller
  applies the returned (src, dst) device copies); a sole-owner
  registered block is unpublished and written in place instead.

Every entry stores its chunk's actual tokens and its parent key, and
lookup verifies both per level — a hash collision degrades to a cache
miss, never to serving another prompt's KV.

The host-RAM spill tier (ISSUE 17) adds a SECOND level under the device
pool: :func:`extract_blocks` / :func:`insert_blocks` serialize a set of
blocks (every pool atomically — int8 value pools and their fp32 scale
planes travel together) into host memory as a :class:`BlockSet` and
scatter them back into freshly allocated blocks, token-exact by
construction. Two consumers share the primitive:

- **swap-based preemption**: the engine extracts a preemption victim's
  resident blocks before release and restores them at re-admission —
  no re-prefill, the vLLM swap alternative to recompute.
- **prefix demotion**: a zero-ref cached block being evicted spills its
  payload host-side first (when a spill hook is installed), keyed by
  its chain key; a later :meth:`BlockManager.peek_hosted` match revives
  it into a fresh device block, so the effective prefix cache is
  host-RAM-sized, not pool-sized. :meth:`BlockManager.demote`
  additionally write-backs still-resident cold blocks, whose device
  ids then become reclaimable WITHOUT data loss (``num_hosted`` —
  conservation: ``num_free + num_used + num_cached + num_hosted ==
  num_blocks - 1`` at every step).

The BlockManager itself stays payload-agnostic plain Python (payloads
are opaque objects with an ``nbytes`` attribute); only the module-level
extract/insert helpers touch jax, and they import it lazily so the
allocator remains unit-testable with no backend.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import NamedTuple, Optional, Sequence

#: chain seed for block 0's key (any fixed odd 64-bit constant)
_CHAIN_ROOT = 0x9E3779B97F4A7C15


def prefix_chain_keys(tokens, block_size: int):
    """Yield ``(chain_key, chunk_tokens)`` per FULL ``block_size``-sized
    chunk of ``tokens``, lazily — a consumer that stops at the first
    index miss never hashes the rest of the prompt. Key N hashes
    (key N-1, chunk N), so a key commits to the whole token prefix
    through its chunk.

    This is THE prefix fingerprint of the serving stack, shared by two
    consumers on purpose: :meth:`BlockManager.chain_keys` builds the
    block-level prefix-cache index from it, and the multi-replica
    router (``serve/router.py``, ISSUE 14) builds its replica-affinity
    index from the SAME chain values — so "the replica holding this
    prompt's longest cached prefix" and "the blocks this prompt would
    hit" are answers to one question asked at two granularities, and
    the two indexes can never disagree about what counts as a shared
    prefix. The chain value is a pure function of the tokens (no block
    ids, no engine state), which is what lets a router-level entry
    outlive any replica's physical blocks."""
    bs = int(block_size)
    h = _CHAIN_ROOT
    for i in range(len(tokens) // bs):
        chunk = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
        h = hash((h, chunk))
        yield h, chunk


class CachedBlock(NamedTuple):
    """One prefix-index entry: the physical block plus the exact chunk
    tokens and parent chain key the lookup re-verifies (collision
    safety — see module docstring)."""

    block: int
    parent: int
    chunk: tuple


class HostedBlock(NamedTuple):
    """One host-tier entry: the spilled payload (opaque — the engine
    stores a :class:`BlockSet`; tests store anything with ``nbytes``)
    plus the parent chain key and exact chunk tokens revival
    re-verifies, mirroring :class:`CachedBlock`'s collision safety."""

    parent: int
    chunk: tuple
    payload: object
    nbytes: int


class PoolExhausted(Exception):
    """Raised by :meth:`BlockManager.allocate` when the pool cannot
    satisfy a request — the scheduler catches it and preempts."""


class BlockSet(NamedTuple):
    """Host-RAM serialization of a set of KV blocks: one stacked numpy
    array per device pool (shape ``[n_blocks, block_size, H, D]``, the
    pool's own dtype — bf16/int8 round-trip bitwise), plus the draft
    pools' arrays for a speculative engine (the draft rides the same
    block tables, so its KV must travel with the target's). Built by
    :func:`extract_blocks`, consumed by :func:`insert_blocks`; the
    payload is engine-agnostic numpy, which is what lets
    :func:`~.transport.migrate_request` (ISSUE 18) point the same
    object at ANOTHER engine — same-geometry pools accept it bitwise,
    and a destination at a different tensor-parallel degree re-shards
    the heads axis simply by scattering into its own sharded pools
    (the payload is always the full logical block)."""

    payloads: tuple
    draft_payloads: Optional[tuple]

    @property
    def signature(self) -> tuple:
        """Logical pool geometry the set was extracted from — per-pool
        ``(block shape, dtype)``, target then draft. Sets transplant
        only between engines whose pools report the same signature
        (sharding excluded: shapes here are the assembled host
        shapes)."""
        def sig(ps):
            # dim 0 is the set's block count — geometry is the rest
            return tuple((tuple(int(d) for d in p.shape[1:]),
                          str(p.dtype)) for p in ps)
        return (sig(self.payloads),
                sig(self.draft_payloads)
                if self.draft_payloads is not None else None)

    @property
    def n_blocks(self) -> int:
        """How many blocks this set carries."""
        return int(self.payloads[0].shape[0]) if self.payloads else 0

    @property
    def nbytes(self) -> int:
        """Host bytes the set occupies (target + draft pools)."""
        n = sum(int(p.nbytes) for p in self.payloads)
        if self.draft_payloads is not None:
            n += sum(int(p.nbytes) for p in self.draft_payloads)
        return n


def _gather_block(pools, src):
    """One block's rows out of every pool — ``src`` is a TRACED scalar
    (the :func:`~.engine._copy_block` convention), so ONE compile per
    pool geometry covers every block any extraction ever reads."""
    return [p[src] for p in pools]


def _scatter_block(pools, dst, block):
    """One host block's rows into every pool at ``dst`` (traced scalar;
    the per-pool ``block`` arrays are fixed ``[block_size, H, D]``
    shapes) — one compile per pool geometry covers every insertion."""
    return [p.at[dst].set(b) for p, b in zip(pools, block)]


@functools.lru_cache(maxsize=1)
def _gather_block_jit():
    """Process-wide jitted block gather (reads never donate)."""
    import jax

    # graftlint: allow[R3] no static key by design: pools are traced arrays and src is a traced scalar, so one compile covers every block a pool geometry extracts
    return jax.jit(_gather_block)


@functools.lru_cache(maxsize=2)
def _scatter_block_jit(donate: bool):
    """Process-wide jitted block scatter, one per donation mode — the
    pool chain flows through it, so the donating build reuses the pool
    buffers exactly like the engine's COW copy does."""
    import jax

    # graftlint: allow[R3] no static key by design: pools are traced arrays and dst is a traced scalar, so one compile covers every block a pool geometry restores
    return jax.jit(_scatter_block, donate_argnums=(0,) if donate else ())


def extract_blocks(pools, ids: Sequence[int], d_pools=None) -> BlockSet:
    """Serialize blocks ``ids`` out of the device ``pools`` (and the
    draft's ``d_pools`` when given) into one host-side
    :class:`BlockSet`. Every pool travels atomically — int8 KV values
    and their fp32 scale planes are ordinary pool entries, so a
    quantized block's scales can never be separated from its values.
    One jitted traced-index gather per block (zero new compiled
    variants per id value or id count), then ONE ``device_get`` for
    the whole set — this host-side fetch is the swap transfer itself,
    not a hot-loop sync."""
    import jax
    import numpy as np

    if not ids:
        return BlockSet((), None if d_pools is None else ())
    gather = _gather_block_jit()
    dev = [gather(pools, np.int32(b)) for b in ids]
    d_dev = (None if d_pools is None
             else [gather(d_pools, np.int32(b)) for b in ids])
    host, d_host = jax.device_get((dev, d_dev))
    payloads = tuple(np.stack([blk[i] for blk in host])
                     for i in range(len(host[0])))
    draft = (None if d_host is None
             else tuple(np.stack([blk[i] for blk in d_host])
                        for i in range(len(d_host[0]))))
    return BlockSet(payloads, draft)


def extract_block_sets(pools, id_lists: Sequence[Sequence[int]],
                       d_pools=None) -> list:
    """Batch variant of :func:`extract_blocks` (ISSUE 20, the PR 18
    drain follow-up): serialize SEVERAL block sets — one per inner id
    list — with ONE ``device_get`` for the whole cohort instead of one
    blocking pull per set. The per-block jitted gather is the same
    (zero new compiled variants regardless of cohort shape); only the
    host-sync count changes, so a drain migrating V victims pays one
    device round-trip, not V. Each returned :class:`BlockSet` is
    bitwise identical to its sequential extraction."""
    import jax
    import numpy as np

    if not id_lists:
        return []
    gather = _gather_block_jit()
    dev = [[gather(pools, np.int32(b)) for b in ids]
           for ids in id_lists]
    d_dev = (None if d_pools is None
             else [[gather(d_pools, np.int32(b)) for b in ids]
                   for ids in id_lists])
    host, d_host = jax.device_get((dev, d_dev))
    out = []
    for k, ids in enumerate(id_lists):
        if not ids:
            out.append(BlockSet((), None if d_pools is None else ()))
            continue
        payloads = tuple(np.stack([blk[i] for blk in host[k]])
                         for i in range(len(host[k][0])))
        draft = (None if d_host is None
                 else tuple(np.stack([blk[i] for blk in d_host[k]])
                            for i in range(len(d_host[k][0]))))
        out.append(BlockSet(payloads, draft))
    return out


def insert_blocks(pools, block_set: BlockSet, ids: Sequence[int],
                  d_pools=None, donate: bool = False):
    """Scatter a :class:`BlockSet` back into freshly allocated blocks
    ``ids`` (``len(ids) == block_set.n_blocks``); returns the advanced
    ``(pools, d_pools)`` chain. Token-exact by construction: the
    payload was read with :func:`extract_blocks` and lands bitwise
    unchanged, scale planes included. One jitted traced-index scatter
    per block — fixed per-pool block shapes, so zero new compiled
    variants regardless of which (or how many) blocks restore."""
    import numpy as np

    if len(ids) != block_set.n_blocks:
        raise ValueError(
            f"inserting {block_set.n_blocks} extracted blocks into "
            f"{len(ids)} target ids")
    if (d_pools is None) != (block_set.draft_payloads is None):
        raise ValueError(
            "draft pools and draft payloads must be given together "
            "(a speculative engine's draft KV rides the same tables)")
    scatter = _scatter_block_jit(bool(donate))
    for j, b in enumerate(ids):
        pools = scatter(pools, np.int32(b),
                        tuple(p[j] for p in block_set.payloads))
        if d_pools is not None:
            d_pools = scatter(d_pools, np.int32(b),
                              tuple(p[j] for p in block_set.draft_payloads))
    return pools, d_pools


class BlockManager:
    """Free-list allocator over ``num_blocks`` blocks of ``block_size``
    token slots each. Block 0 is the reserved null block and is never
    handed out."""

    def __init__(self, num_blocks: int, block_size: int,
                 token_bytes: int = 0):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (block 0 is the reserved "
                             f"null block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # bytes one resident token costs across every pool this manager
        # allocates for (int8 KV halves it vs fp; the fp32 scale planes
        # ride along) — the KV-element-size parameterization that lets
        # capacity be reasoned about (and pools be sized) in BYTES:
        # ``ServeEngine(kv_pool_bytes=...)`` divides a memory budget by
        # ``block_bytes``, so int8 pools hold ~2x the blocks — and
        # admit ~2x the requests — of fp pools on the same budget.
        # Under a tensor-parallel engine (ISSUE 13) this is each
        # SHARD's bytes/token (the model's figure / tp), making the
        # budget — and every byte-denominated gauge derived here —
        # per DEVICE: same per-chip budget, tp× the blocks.
        self.token_bytes = int(token_bytes)
        # LIFO free list: recently-freed (cache-warm) blocks are reused
        # first; block 0 excluded for good
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # per-block refcount: 0 = free or cached, >= 1 = held by that
        # many block tables (prefix sharing makes > 1 possible)
        self._ref = [0] * self.num_blocks
        self._used = 0
        # prefix cache: chain key -> CachedBlock, the reverse block ->
        # key map, and the LRU of zero-ref registered blocks (oldest
        # first — the eviction order under pool pressure)
        self._index: dict[int, CachedBlock] = {}
        self._block_key: dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # sharing accounting: how many block tables hold a ref BEYOND
        # the first (the allocation the cache deduplicates), peak
        # count of distinct ref>=2 blocks, COW copies performed, and
        # decode reads served out of shared blocks
        self._extra_refs = 0
        self._shared_blocks = 0      # distinct blocks at ref >= 2, live
        self.peak_shared_blocks = 0
        self.peak_blocks_saved = 0
        self.cow_copies = 0
        self.prefix_evictions = 0
        self._shared_read_tokens = 0
        self.peak_used = 0
        # host-RAM spill tier (ISSUE 17): the spill hook (installed by
        # the engine — block id -> opaque payload with an ``nbytes``),
        # the byte budget shared by demoted payloads and swap
        # reservations, the DEMOTED device blocks (still resident and
        # matchable, but reclaimable without data loss — their host
        # copy exists), and the host payload store keyed by chain key
        # (LRU for budget eviction). Payloads are content-addressed by
        # the chain key — a chain key's KV is a pure function of its
        # token prefix — so an entry stays valid across any number of
        # evict/revive cycles of its physical blocks.
        self._spill = None
        self.host_budget: Optional[int] = None
        self._hosted: "OrderedDict[int, None]" = OrderedDict()
        self._host_payloads: "OrderedDict[int, HostedBlock]" = OrderedDict()
        # chain keys an in-flight admission matched and is about to
        # revive: budget eviction must not take them mid-reservation
        # (the reservation's own allocations can spill-demote evicted
        # cached blocks, and without the pin that demotion could push
        # the just-matched oldest payloads out of the budget window
        # between peek_hosted and revive_hosted)
        self._host_pinned: set = set()
        self._host_bytes = 0         # demote-tier payload bytes
        self._swap_bytes_held = 0    # engine swap reservations
        self.host_tier_hits = 0      # blocks revived from host payloads
        self.host_tier_lookups = 0   # host-tier probes at admission
        self.prefix_demotions = 0    # fresh payload spills performed
        self.host_evictions = 0      # payloads dropped by budget pressure
        # bucket-padded READ waste (decode-side, orthogonal to the
        # allocation fragmentation below): latched by note_gather()
        self.peak_gather_waste = 0.0
        self._gather_read_tokens = 0
        self._gather_useful_tokens = 0
        # width-(k+1) verify-window padding (speculative decode),
        # counted SEPARATELY from bucket padding: latched by
        # note_verify()
        self.peak_verify_waste = 0.0
        self._verify_window_tokens = 0
        self._verify_useful_tokens = 0

    # -- capacity arithmetic -------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` context tokens."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    @property
    def block_bytes(self) -> int:
        """Pool bytes one block occupies (0 when the manager was built
        without a ``token_bytes`` figure)."""
        return self.block_size * self.token_bytes

    def bytes_for(self, n_tokens: int) -> int:
        """Pool bytes ``n_tokens`` of resident context occupies
        (block-granular — the allocation, not the useful payload)."""
        return self.blocks_for(n_tokens) * self.block_bytes

    @property
    def pool_bytes(self) -> int:
        """Total pool footprint in ``token_bytes`` terms — under a
        tensor-parallel engine this is the PER-DEVICE figure (the
        engine hands this manager each shard's bytes/token), which is
        the point: the same token capacity costs ``1/tp`` the HBM per
        chip, or equivalently the same per-chip budget holds ``tp``×
        the blocks. 0 when built without a ``token_bytes`` figure."""
        return self.num_blocks * self.block_bytes

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        """Blocks held by at least one block table (refcount >= 1)."""
        return self._used

    @property
    def num_cached(self) -> int:
        """Zero-ref registered blocks parked in the reuse LRU — free
        CAPACITY (evictable on demand) that is still a prefix-cache
        hit until reclaimed."""
        return len(self._lru)

    @property
    def num_hosted(self) -> int:
        """Demoted blocks: zero-ref registered blocks whose payload
        was written back to the host tier while the device copy stays
        resident and matchable — free CAPACITY like the cached LRU,
        but reclaimable WITHOUT data loss (the host copy serves later
        revivals). Conservation: ``num_free + num_used + num_cached +
        num_hosted == num_blocks - 1`` always."""
        return len(self._hosted)

    @property
    def hosted_bytes(self) -> int:
        """Host bytes the spill tier currently holds (demoted payloads
        plus the engine's swap reservations — one budget)."""
        return self._host_bytes + self._swap_bytes_held

    def can_allocate(self, n_blocks: int) -> bool:
        """Cached LRU blocks count as allocatable capacity: they are
        evicted (oldest first) the moment a real allocation needs
        them. Demoted blocks likewise — reclaimed FIRST, since their
        host copy makes the eviction lossless."""
        return n_blocks <= (len(self._free) + len(self._lru)
                            + len(self._hosted))

    def utilization(self) -> float:
        """Fraction of allocatable blocks currently held by requests."""
        return self.num_used / max(self.num_blocks - 1, 1)

    def fragmentation(self, context_lens) -> float:
        """Fraction of HELD token slots that are padding inside
        partially-filled last blocks — the paged design's only waste
        (≤ ``(block_size - 1) / block_size`` per request; a contiguous
        ``max_len`` cache wastes ``1 - context/max_len`` instead)."""
        held_tokens = sum(self.blocks_for(c) * self.block_size
                          for c in context_lens)
        if held_tokens == 0:
            return 0.0
        used_tokens = sum(int(c) for c in context_lens)
        return 1.0 - used_tokens / held_tokens

    def note_gather(self, context_lens, width: int) -> float:
        """Record one decode step's bucket-padded KV READ: the gather
        materializes ``width`` token slots per ACTIVE slot while only
        that slot's context is useful, so the step's read waste is
        ``1 - sum(context) / (slots * width)``. This is the decode-side
        counterpart of :meth:`fragmentation` (which accounts allocation
        padding): bucketing exists precisely to shrink it, and the
        engine surfaces both the PEAK (``peak_gather_waste``, latched
        here) and the token-weighted run mean (:meth:`gather_waste`) in
        its ``serve`` report event and the bench detail line. Returns
        the step's waste fraction (0.0 for an empty step)."""
        read = len(context_lens) * int(width)
        if read == 0:
            return 0.0
        useful = sum(min(int(c), int(width)) for c in context_lens)
        waste = 1.0 - useful / read
        self.peak_gather_waste = max(self.peak_gather_waste, waste)
        self._gather_read_tokens += read
        self._gather_useful_tokens += useful
        return waste

    def gather_waste(self) -> float:
        """Token-weighted mean bucket-padded read waste across every
        :meth:`note_gather`-recorded decode step (0.0 before any)."""
        if self._gather_read_tokens == 0:
            return 0.0
        return 1.0 - self._gather_useful_tokens / self._gather_read_tokens

    def note_verify(self, committed, window: int) -> float:
        """Record one speculative VERIFY dispatch's window padding: each
        active slot computes ``window`` (= k+1) query positions but only
        its ``committed`` tokens (accepted prefix + bonus, post EOS /
        budget truncation) were useful — the rejected tail is the
        width-(k+1) analogue of bucket padding, and it is accounted
        SEPARATELY from :meth:`note_gather` (which this dispatch also
        feeds, for its KV read) so the serve report can tell "we read
        too wide" from "we speculated too deep". Returns the dispatch's
        waste fraction (0.0 for an empty step)."""
        total = len(committed) * int(window)
        if total == 0:
            return 0.0
        useful = sum(min(int(c), int(window)) for c in committed)
        waste = 1.0 - useful / total
        self.peak_verify_waste = max(self.peak_verify_waste, waste)
        self._verify_window_tokens += total
        self._verify_useful_tokens += useful
        return waste

    def verify_waste(self) -> float:
        """Token-weighted mean verify-window waste across every
        :meth:`note_verify`-recorded dispatch (0.0 before any)."""
        if self._verify_window_tokens == 0:
            return 0.0
        return 1.0 - self._verify_useful_tokens / self._verify_window_tokens

    # -- alloc/release -------------------------------------------------------

    def allocate(self, n_blocks: int) -> list[int]:
        """Pop ``n_blocks`` physical block ids (each handed out at
        refcount 1); raises :class:`PoolExhausted` (allocating nothing)
        when short. The free list is consumed first; zero-ref cached
        blocks are evicted from the LRU — oldest first, unpublishing
        their prefix-index entries — only once the free list runs
        dry."""
        if not self.can_allocate(n_blocks):
            raise PoolExhausted(
                f"need {n_blocks} blocks, {len(self._free)} free + "
                f"{len(self._lru)} cached + {len(self._hosted)} hosted "
                f"(pool {self.num_blocks - 1} allocatable)")
        out = []
        for _ in range(n_blocks):
            if not self._free:
                self._reclaim_one()
            b = self._free.pop()
            self._ref[b] = 1
            out.append(b)
        self._used += n_blocks
        self.peak_used = max(self.peak_used, self._used)
        return out

    def _reclaim_one(self) -> None:
        """Put one reclaimable block on the free list. Demoted blocks
        go first (lossless — the host copy keeps serving revivals),
        then the cached LRU's oldest (spilled host-side on the way out
        when a spill hook is installed — "demote before true
        eviction")."""
        if self._hosted:
            b, _ = self._hosted.popitem(last=False)
            key = self._block_key.pop(b)
            del self._index[key]
            self.prefix_evictions += 1
            self._free.append(b)
            return
        self._evict_cached()

    def _evict_cached(self) -> None:
        """Reclaim the least-recently-released cached block: drop its
        index entry (future lookups of that prefix miss at the DEVICE
        level from here on) and put the block on the free list. With a
        spill hook installed the payload is written back to the host
        tier first — budget permitting — so the eviction only demotes
        the prefix instead of forgetting it."""
        b, _ = self._lru.popitem(last=False)
        key = self._block_key.pop(b)
        entry = self._index.pop(key)
        if self._spill is not None:
            if key in self._host_payloads:
                # content-addressed: an identical payload is already
                # resident (a revived block re-cooling) — no new copy
                self._host_payloads.move_to_end(key)
            else:
                payload = self._spill(b)
                nbytes = int(getattr(payload, "nbytes", 0))
                if self._host_admit(nbytes):
                    self._host_payloads[key] = HostedBlock(
                        entry.parent, entry.chunk, payload, nbytes)
                    self._host_bytes += nbytes
                    self.prefix_demotions += 1
        self.prefix_evictions += 1
        self._free.append(b)

    def grow(self, table: list[int], n_tokens: int) -> list[int]:
        """Extend ``table`` (a request's block table) to cover
        ``n_tokens`` of context; returns the newly-allocated ids (empty
        when the table already covers it). All-or-nothing on
        :class:`PoolExhausted`."""
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return []
        fresh = self.allocate(need)
        table.extend(fresh)
        return fresh

    def trim(self, table: list[int], n_tokens: int) -> None:
        """Release table blocks beyond what ``n_tokens`` needs (chunked
        prefill pads the prompt to a chunk multiple; the pad tail's
        blocks come back here once the real length is known)."""
        keep = self.blocks_for(n_tokens)
        while len(table) > keep:
            self.release([table.pop()])

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per listed block. A block reaching
        refcount 0 returns to the free list — unless it is registered
        in the prefix index, in which case it parks in the cached-block
        LRU (reusable by future :meth:`match_prefix` hits, reclaimable
        by :meth:`allocate` under pressure). Releasing a block that is
        not held (already free or cached) raises — the double-free
        guard that keeps the free list corruption-proof."""
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"releasing block {b} outside the pool")
            if self._ref[b] == 0:
                raise ValueError(f"double free of block {b} (not held "
                                 "by any table)")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._used -= 1
                if b in self._block_key:
                    self._lru[b] = None     # newest at the end
                else:
                    self._free.append(b)
            else:
                self._extra_refs -= 1
                if self._ref[b] == 1:
                    self._shared_blocks -= 1

    #: legacy name — release() IS the free of the refcounted pool
    free = release

    # -- prefix cache --------------------------------------------------------

    def chain_keys(self, tokens):
        """Yield ``(chain_key, chunk_tokens)`` per FULL block-sized
        chunk of ``tokens`` (:func:`prefix_chain_keys` at this pool's
        ``block_size``) — lazy, and a pure function of the tokens, the
        property that makes index entries reusable even after their
        physical parent blocks were evicted and re-prefilled
        elsewhere."""
        return prefix_chain_keys(tokens, self.block_size)

    def peek_prefix(self, tokens, max_blocks: Optional[int] = None
                    ) -> tuple[list[int], int]:
        """Read-only longest-cached-prefix probe: ``(block_ids,
        n_revivals)`` where ``n_revivals`` counts matched blocks that
        are currently zero-ref (parked in the LRU — committing the
        match removes them from evictable capacity, so an admission
        capacity check must charge for them). Verifies each level's
        stored chunk AND parent key (collision => miss, never wrong
        KV). Mutates NOTHING: a failed admission probe re-run every
        engine iteration must not touch refcounts or perturb LRU
        order. ``max_blocks`` caps the walk — the engine passes
        ``(prompt_len - 1) // block_size`` so at least the final
        prompt token is always recomputed (its logits seed
        generation)."""
        out: list[int] = []
        revivals = 0
        parent = _CHAIN_ROOT
        for key, chunk in self.chain_keys(tokens):
            if max_blocks is not None and len(out) >= max_blocks:
                break
            entry = self._index.get(key)
            if entry is None or entry.chunk != chunk \
                    or entry.parent != parent:
                break
            out.append(entry.block)
            if self._ref[entry.block] == 0:
                revivals += 1
            parent = key
        return out, revivals

    def commit_match(self, blocks: Sequence[int]) -> None:
        """Take one reference on every peeked block (reviving zero-ref
        ones out of the LRU) — the write half of :meth:`peek_prefix`,
        called once admission capacity is assured."""
        for b in blocks:
            if self._ref[b] == 0:
                if b in self._hosted:
                    # a demoted block revived in place: its host copy
                    # stays resident (content-addressed — still valid)
                    del self._hosted[b]
                else:
                    del self._lru[b]
                self._used += 1
            else:
                self._extra_refs += 1
                if self._ref[b] == 1:
                    self._shared_blocks += 1
            self._ref[b] += 1
        if blocks:
            self.peak_used = max(self.peak_used, self._used)
            self.peak_shared_blocks = max(self.peak_shared_blocks,
                                          self._shared_blocks)
            self.peak_blocks_saved = max(self.peak_blocks_saved,
                                         self._extra_refs)

    def match_prefix(self, tokens, max_blocks: Optional[int] = None
                     ) -> list[int]:
        """Longest cached prefix of ``tokens`` in full blocks, with the
        references taken: peek + commit in one call. The caller owns
        the returned references (release them like any allocated
        block)."""
        out, _ = self.peek_prefix(tokens, max_blocks)
        self.commit_match(out)
        return out

    def register_prefix(self, tokens, table: Sequence[int]) -> int:
        """Publish the full-block prefix of ``tokens`` (whose KV lives
        in ``table``'s leading blocks) into the index; returns how many
        blocks were newly registered. Levels already present keep their
        existing entry — the first writer wins, later identical blocks
        stay private and flow back to the free list on release."""
        registered = 0
        parent = _CHAIN_ROOT
        for i, (key, chunk) in enumerate(self.chain_keys(tokens)):
            if i >= len(table):
                break
            if key not in self._index:
                b = int(table[i])
                if b not in self._block_key:
                    self._index[key] = CachedBlock(b, parent, chunk)
                    self._block_key[b] = key
                    registered += 1
            parent = key
        return registered

    # -- host-RAM spill tier (ISSUE 17) --------------------------------------

    def set_spill(self, spill, host_budget: Optional[int] = None) -> None:
        """Install the spill hook (``block_id -> payload`` — the engine
        wires :func:`extract_blocks` over its live pools; payloads are
        opaque here beyond their ``nbytes``) and the host byte budget
        shared by demoted payloads and swap reservations (None =
        unbounded). With no hook installed every host-tier path is
        inert and the manager behaves exactly as before."""
        self._spill = spill
        self.host_budget = None if host_budget is None else int(host_budget)

    @property
    def host_tier_active(self) -> bool:
        """True once a spill hook is installed — the flag admission
        (``Scheduler._reserve``) keys its host-tier probe on."""
        return self._spill is not None

    def demote(self, max_blocks: int = 1) -> int:
        """Write back up to ``max_blocks`` of the COLDEST zero-ref
        cached blocks to the host tier: the device copy stays resident
        and matchable (a hit revives it in place, no transfer), but
        the id becomes reclaimable without data loss — under pressure
        :meth:`allocate` takes demoted blocks first and only the host
        copy survives. Returns how many blocks were demoted (0 when no
        spill hook is installed, the LRU is empty, or the budget is
        full)."""
        n = 0
        while n < max_blocks and self._lru and self._spill is not None:
            b = next(iter(self._lru))            # oldest
            key = self._block_key[b]
            if key in self._host_payloads:
                self._host_payloads.move_to_end(key)
            else:
                payload = self._spill(b)
                nbytes = int(getattr(payload, "nbytes", 0))
                if not self._host_admit(nbytes):
                    break                        # budget can't take it
                entry = self._index[key]
                self._host_payloads[key] = HostedBlock(
                    entry.parent, entry.chunk, payload, nbytes)
                self._host_bytes += nbytes
                self.prefix_demotions += 1
            del self._lru[b]
            self._hosted[b] = None
            n += 1
        return n

    def peek_hosted(self, tokens, start: int,
                    max_blocks: Optional[int] = None
                    ) -> tuple[list[int], bool]:
        """Read-only host-tier probe CONTINUING a device-level match:
        ``(chain_keys, missed)`` for the chunks from index ``start``
        (= the device-matched block count) whose payloads are resident
        host-side, chunk-and-parent verified like every lookup here;
        ``missed`` is True when the walk ended on a genuine miss
        rather than the ``max_blocks`` cap or the prompt running out —
        the hit-rate denominator's input. Mutates nothing (a failed
        admission probe re-runs every iteration)."""
        out: list[int] = []
        missed = False
        parent = _CHAIN_ROOT
        for i, (key, chunk) in enumerate(self.chain_keys(tokens)):
            if i < start:
                parent = key
                continue
            if max_blocks is not None and start + len(out) >= max_blocks:
                break
            entry = self._host_payloads.get(key)
            if entry is None or entry.chunk != chunk \
                    or entry.parent != parent:
                missed = True
                break
            out.append(key)
            parent = key
        return out, missed

    def note_host_probe(self, hits: int, missed: bool) -> None:
        """Account one COMMITTED admission's host-tier probe outcome
        (the write half of :meth:`peek_hosted` — counters move only
        when an admission actually lands, so failed-capacity re-probes
        do not inflate the hit rate)."""
        self.host_tier_lookups += int(hits) + (1 if missed else 0)

    def host_pin(self, keys: Sequence[int]) -> None:
        """Shield host-tier entries ``keys`` from budget eviction for
        the duration of one admission reservation: between the
        :meth:`peek_hosted` match and the :meth:`revive_hosted` commit
        the reservation's own ``allocate`` calls may evict cached
        blocks, and spilling THOSE on the way out must not push the
        matched (LRU-oldest — peek mutates nothing) payloads out of
        the budget window. While pinned entries block the budget,
        demotion simply drops instead of spilling — a demoted prefix
        is an opportunity, a matched one a commitment. Always paired
        with :meth:`host_unpin` (try/finally)."""
        self._host_pinned.update(keys)

    def host_unpin(self, keys: Sequence[int]) -> None:
        """Release a :meth:`host_pin` (the reservation committed via
        :meth:`revive_hosted` — which re-warms the entries — or rolled
        back)."""
        self._host_pinned.difference_update(keys)

    def revive_hosted(self, keys: Sequence[int], blocks: Sequence[int]
                      ) -> list[tuple[int, object]]:
        """Re-materialize host-tier entries ``keys`` into freshly
        ALLOCATED device blocks ``blocks`` (the caller owns them at ref
        1): each key is re-registered in the prefix index at its new
        block, and the returned ``(block, payload)`` pairs are the
        device-side scatters the CALLER must apply (every pool, target
        and draft alike) before any dispatch reads the blocks —
        exactly the :meth:`privatize` pending-copy contract. Payloads
        stay resident (content-addressed — a re-eviction re-demotes
        without a new copy)."""
        restores: list[tuple[int, object]] = []
        for key, b in zip(keys, blocks):
            entry = self._host_payloads[key]
            self._host_payloads.move_to_end(key)
            self._index[key] = CachedBlock(b, entry.parent, entry.chunk)
            self._block_key[b] = key
            self.host_tier_hits += 1
            restores.append((b, entry.payload))
        return restores

    def host_reserve(self, nbytes: int) -> bool:
        """Charge ``nbytes`` of swap-out payload against the host
        budget (evicting demoted payloads oldest-first to make room —
        a swapped request's restore is a promise, a demoted prefix
        only an opportunity). False = would not fit even empty, and
        the caller must fall back to recompute."""
        nbytes = int(nbytes)
        if self.host_budget is not None:
            while (self.hosted_bytes + nbytes > self.host_budget
                   and self._host_evict_one()):
                pass
            if self.hosted_bytes + nbytes > self.host_budget:
                return False
        self._swap_bytes_held += nbytes
        return True

    def host_release(self, nbytes: int) -> None:
        """Return a swap reservation (the request restored or died)."""
        self._swap_bytes_held -= int(nbytes)

    def _host_admit(self, nbytes: int) -> bool:
        """True when the budget can take one more demoted payload of
        ``nbytes`` after evicting older payloads as needed."""
        if self.host_budget is None:
            return True
        while (self.hosted_bytes + nbytes > self.host_budget
               and self._host_evict_one()):
            pass
        return self.hosted_bytes + nbytes <= self.host_budget

    def _host_evict_one(self) -> bool:
        """Drop the oldest demoted payload (True) or report the tier
        empty (False). A payload backing a currently-DEMOTED device
        block takes that block back to the plain cached LRU — its
        device copy is intact, it just lost the lossless-reclaim
        property — re-inserted at the COLD end (it was the tier's
        oldest)."""
        key = next((k for k in self._host_payloads
                    if k not in self._host_pinned), None)
        if key is None:                  # empty, or everything pinned
            return False
        entry = self._host_payloads.pop(key)
        self._host_bytes -= entry.nbytes
        self.host_evictions += 1
        ent = self._index.get(key)
        if ent is not None and ent.block in self._hosted:
            del self._hosted[ent.block]
            self._lru[ent.block] = None
            self._lru.move_to_end(ent.block, last=False)
        return True

    def privatize(self, table: list[int], lo: int, hi: int
                  ) -> list[tuple[int, int]]:
        """Copy-on-write for table blocks ``[lo, hi)`` that a request
        is about to scatter into: a block with refcount > 1 is swapped
        for a freshly-allocated private copy — the returned
        ``(src, dst)`` pairs are the device-side pool copies the CALLER
        must apply (to every pool addressed by this table, target and
        draft alike) before the write dispatch; a sole-owner block that
        is merely registered is unpublished and written in place (no
        copy — nobody else can be reading it). Raises
        :class:`PoolExhausted` if a copy target cannot be allocated."""
        copies: list[tuple[int, int]] = []
        for i in range(lo, min(hi, len(table))):
            b = table[i]
            if self._ref[b] > 1:
                [dst] = self.allocate(1)
                self._ref[b] -= 1
                self._extra_refs -= 1
                if self._ref[b] == 1:
                    self._shared_blocks -= 1
                table[i] = dst
                copies.append((b, dst))
                self.cow_copies += 1
            elif b in self._block_key:
                key = self._block_key.pop(b)
                del self._index[key]
        return copies

    def is_private(self, block: int) -> bool:
        """True when exactly one table holds ``block`` and it is not
        published in the prefix index — the only state a scatter may
        write without :meth:`privatize`."""
        return self._ref[block] == 1 and block not in self._block_key

    def ensure_private(self, table: Sequence[int], lo: int, hi: int) -> None:
        """Assert-style guard: every table block in ``[lo, hi)`` must be
        writable. Decode/verify write spans are private by construction
        (they sit past the cached prompt prefix); a shared block here
        means allocator-state corruption, so fail loudly instead of
        silently clobbering another request's KV."""
        for i in range(lo, min(hi, len(table))):
            if not self.is_private(table[i]):
                raise RuntimeError(
                    f"block {table[i]} (table index {i}) is shared or "
                    f"registered but sits in a write span — allocator "
                    f"state corrupted")

    def blocks_saved(self) -> int:
        """Block allocations the prefix cache is deduplicating RIGHT
        NOW: total extra references beyond each shared block's first
        (= blocks a cache-off run would additionally hold resident)."""
        return self._extra_refs

    def note_shared_reads(self, n_tokens: int) -> None:
        """Account decode/verify KV reads served out of shared
        (refcount >= 2) blocks — the read-side extension of the waste
        accounting: these tokens are resident ONCE but read by several
        requests' gathers."""
        self._shared_read_tokens += int(n_tokens)

    def shared_read_tokens(self, table: Sequence[int],
                           context_len: int) -> int:
        """How many of one slot's ``context_len`` resident tokens live
        in shared blocks (the per-step input to
        :meth:`note_shared_reads`)."""
        bs = self.block_size
        n = 0
        for i in range(self.blocks_for(context_len)):
            if i < len(table) and self._ref[table[i]] >= 2:
                n += min(bs, context_len - i * bs)
        return n

    def shared_read_frac(self) -> float:
        """Fraction of all useful gathered decode tokens that came out
        of shared blocks (0.0 before any decode)."""
        if self._gather_useful_tokens == 0:
            return 0.0
        return self._shared_read_tokens / self._gather_useful_tokens
