"""Paged KV cache bookkeeping: a fixed population of fixed-size blocks,
allocated to requests as their context grows (vLLM, Kwon et al. 2023).

The device side is dumb on purpose — per-layer pools
``[num_blocks, block_size, heads, head_dim]`` plus the gather/scatter
addressing in ``ops.attention`` — so ALL allocation policy lives here in
plain host Python where it is unit-testable without a backend:

- :class:`BlockManager` owns the free list. Block 0 is reserved as the
  **null block**: inactive decode slots scatter their (discarded) step
  writes there, which is what lets the engine's jitted step keep fully
  static shapes with no per-step masking of the write path.
- memory scales with tokens actually resident: a request holds
  ``ceil(context / block_size)`` blocks, not ``max_model_len`` slots.
  Fragmentation is bounded by ``block_size - 1`` tokens per request
  (the partially-filled last block) — the quantity
  :meth:`BlockManager.fragmentation` reports and the tests pin.
- the READ side wastes separately: every decode step gathers a full
  context-width bucket per slot regardless of how much context the slot
  actually holds. :meth:`BlockManager.note_gather` accounts that
  bucket-padded read waste (peak + token-weighted mean) so the serve
  report can show what width bucketing saves.

Prefix caching (ISSUE 8) adds block-level SHARING on top: every block
carries a refcount, and full ``block_size``-aligned prompt-prefix
chunks are indexed by a rolling hash chain (block N's key includes
blocks 0..N-1's tokens) so identical prompt prefixes across requests
map onto the SAME physical blocks. Lifecycle:

- :meth:`match_prefix` walks the chain for a new prompt, increfs every
  hit, and returns the shared block ids — the engine points the
  request's block table at them and skips their prefill compute.
- :meth:`register_prefix` (at prefill completion) publishes a request's
  full prompt blocks into the index; registered blocks are READ-ONLY.
- :meth:`release` (replacing raw ``free``) decrefs; a zero-ref
  REGISTERED block parks in an LRU of cached blocks — still reusable
  by future lookups, reclaimed oldest-first by :meth:`allocate` only
  under pool pressure. Unregistered zero-ref blocks return to the free
  list immediately.
- :meth:`privatize` is copy-on-write: a request about to scatter into
  a block with refcount > 1 gets a fresh private copy (the caller
  applies the returned (src, dst) device copies); a sole-owner
  registered block is unpublished and written in place instead.

Every entry stores its chunk's actual tokens and its parent key, and
lookup verifies both per level — a hash collision degrades to a cache
miss, never to serving another prompt's KV.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple, Optional, Sequence

#: chain seed for block 0's key (any fixed odd 64-bit constant)
_CHAIN_ROOT = 0x9E3779B97F4A7C15


def prefix_chain_keys(tokens, block_size: int):
    """Yield ``(chain_key, chunk_tokens)`` per FULL ``block_size``-sized
    chunk of ``tokens``, lazily — a consumer that stops at the first
    index miss never hashes the rest of the prompt. Key N hashes
    (key N-1, chunk N), so a key commits to the whole token prefix
    through its chunk.

    This is THE prefix fingerprint of the serving stack, shared by two
    consumers on purpose: :meth:`BlockManager.chain_keys` builds the
    block-level prefix-cache index from it, and the multi-replica
    router (``serve/router.py``, ISSUE 14) builds its replica-affinity
    index from the SAME chain values — so "the replica holding this
    prompt's longest cached prefix" and "the blocks this prompt would
    hit" are answers to one question asked at two granularities, and
    the two indexes can never disagree about what counts as a shared
    prefix. The chain value is a pure function of the tokens (no block
    ids, no engine state), which is what lets a router-level entry
    outlive any replica's physical blocks."""
    bs = int(block_size)
    h = _CHAIN_ROOT
    for i in range(len(tokens) // bs):
        chunk = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
        h = hash((h, chunk))
        yield h, chunk


class CachedBlock(NamedTuple):
    """One prefix-index entry: the physical block plus the exact chunk
    tokens and parent chain key the lookup re-verifies (collision
    safety — see module docstring)."""

    block: int
    parent: int
    chunk: tuple


class PoolExhausted(Exception):
    """Raised by :meth:`BlockManager.allocate` when the pool cannot
    satisfy a request — the scheduler catches it and preempts."""


class BlockManager:
    """Free-list allocator over ``num_blocks`` blocks of ``block_size``
    token slots each. Block 0 is the reserved null block and is never
    handed out."""

    def __init__(self, num_blocks: int, block_size: int,
                 token_bytes: int = 0):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (block 0 is the reserved "
                             f"null block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # bytes one resident token costs across every pool this manager
        # allocates for (int8 KV halves it vs fp; the fp32 scale planes
        # ride along) — the KV-element-size parameterization that lets
        # capacity be reasoned about (and pools be sized) in BYTES:
        # ``ServeEngine(kv_pool_bytes=...)`` divides a memory budget by
        # ``block_bytes``, so int8 pools hold ~2x the blocks — and
        # admit ~2x the requests — of fp pools on the same budget.
        # Under a tensor-parallel engine (ISSUE 13) this is each
        # SHARD's bytes/token (the model's figure / tp), making the
        # budget — and every byte-denominated gauge derived here —
        # per DEVICE: same per-chip budget, tp× the blocks.
        self.token_bytes = int(token_bytes)
        # LIFO free list: recently-freed (cache-warm) blocks are reused
        # first; block 0 excluded for good
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # per-block refcount: 0 = free or cached, >= 1 = held by that
        # many block tables (prefix sharing makes > 1 possible)
        self._ref = [0] * self.num_blocks
        self._used = 0
        # prefix cache: chain key -> CachedBlock, the reverse block ->
        # key map, and the LRU of zero-ref registered blocks (oldest
        # first — the eviction order under pool pressure)
        self._index: dict[int, CachedBlock] = {}
        self._block_key: dict[int, int] = {}
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # sharing accounting: how many block tables hold a ref BEYOND
        # the first (the allocation the cache deduplicates), peak
        # count of distinct ref>=2 blocks, COW copies performed, and
        # decode reads served out of shared blocks
        self._extra_refs = 0
        self._shared_blocks = 0      # distinct blocks at ref >= 2, live
        self.peak_shared_blocks = 0
        self.peak_blocks_saved = 0
        self.cow_copies = 0
        self.prefix_evictions = 0
        self._shared_read_tokens = 0
        self.peak_used = 0
        # bucket-padded READ waste (decode-side, orthogonal to the
        # allocation fragmentation below): latched by note_gather()
        self.peak_gather_waste = 0.0
        self._gather_read_tokens = 0
        self._gather_useful_tokens = 0
        # width-(k+1) verify-window padding (speculative decode),
        # counted SEPARATELY from bucket padding: latched by
        # note_verify()
        self.peak_verify_waste = 0.0
        self._verify_window_tokens = 0
        self._verify_useful_tokens = 0

    # -- capacity arithmetic -------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` context tokens."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    @property
    def block_bytes(self) -> int:
        """Pool bytes one block occupies (0 when the manager was built
        without a ``token_bytes`` figure)."""
        return self.block_size * self.token_bytes

    def bytes_for(self, n_tokens: int) -> int:
        """Pool bytes ``n_tokens`` of resident context occupies
        (block-granular — the allocation, not the useful payload)."""
        return self.blocks_for(n_tokens) * self.block_bytes

    @property
    def pool_bytes(self) -> int:
        """Total pool footprint in ``token_bytes`` terms — under a
        tensor-parallel engine this is the PER-DEVICE figure (the
        engine hands this manager each shard's bytes/token), which is
        the point: the same token capacity costs ``1/tp`` the HBM per
        chip, or equivalently the same per-chip budget holds ``tp``×
        the blocks. 0 when built without a ``token_bytes`` figure."""
        return self.num_blocks * self.block_bytes

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        """Blocks held by at least one block table (refcount >= 1)."""
        return self._used

    @property
    def num_cached(self) -> int:
        """Zero-ref registered blocks parked in the reuse LRU — free
        CAPACITY (evictable on demand) that is still a prefix-cache
        hit until reclaimed."""
        return len(self._lru)

    def can_allocate(self, n_blocks: int) -> bool:
        """Cached LRU blocks count as allocatable capacity: they are
        evicted (oldest first) the moment a real allocation needs
        them."""
        return n_blocks <= len(self._free) + len(self._lru)

    def utilization(self) -> float:
        """Fraction of allocatable blocks currently held by requests."""
        return self.num_used / max(self.num_blocks - 1, 1)

    def fragmentation(self, context_lens) -> float:
        """Fraction of HELD token slots that are padding inside
        partially-filled last blocks — the paged design's only waste
        (≤ ``(block_size - 1) / block_size`` per request; a contiguous
        ``max_len`` cache wastes ``1 - context/max_len`` instead)."""
        held_tokens = sum(self.blocks_for(c) * self.block_size
                          for c in context_lens)
        if held_tokens == 0:
            return 0.0
        used_tokens = sum(int(c) for c in context_lens)
        return 1.0 - used_tokens / held_tokens

    def note_gather(self, context_lens, width: int) -> float:
        """Record one decode step's bucket-padded KV READ: the gather
        materializes ``width`` token slots per ACTIVE slot while only
        that slot's context is useful, so the step's read waste is
        ``1 - sum(context) / (slots * width)``. This is the decode-side
        counterpart of :meth:`fragmentation` (which accounts allocation
        padding): bucketing exists precisely to shrink it, and the
        engine surfaces both the PEAK (``peak_gather_waste``, latched
        here) and the token-weighted run mean (:meth:`gather_waste`) in
        its ``serve`` report event and the bench detail line. Returns
        the step's waste fraction (0.0 for an empty step)."""
        read = len(context_lens) * int(width)
        if read == 0:
            return 0.0
        useful = sum(min(int(c), int(width)) for c in context_lens)
        waste = 1.0 - useful / read
        self.peak_gather_waste = max(self.peak_gather_waste, waste)
        self._gather_read_tokens += read
        self._gather_useful_tokens += useful
        return waste

    def gather_waste(self) -> float:
        """Token-weighted mean bucket-padded read waste across every
        :meth:`note_gather`-recorded decode step (0.0 before any)."""
        if self._gather_read_tokens == 0:
            return 0.0
        return 1.0 - self._gather_useful_tokens / self._gather_read_tokens

    def note_verify(self, committed, window: int) -> float:
        """Record one speculative VERIFY dispatch's window padding: each
        active slot computes ``window`` (= k+1) query positions but only
        its ``committed`` tokens (accepted prefix + bonus, post EOS /
        budget truncation) were useful — the rejected tail is the
        width-(k+1) analogue of bucket padding, and it is accounted
        SEPARATELY from :meth:`note_gather` (which this dispatch also
        feeds, for its KV read) so the serve report can tell "we read
        too wide" from "we speculated too deep". Returns the dispatch's
        waste fraction (0.0 for an empty step)."""
        total = len(committed) * int(window)
        if total == 0:
            return 0.0
        useful = sum(min(int(c), int(window)) for c in committed)
        waste = 1.0 - useful / total
        self.peak_verify_waste = max(self.peak_verify_waste, waste)
        self._verify_window_tokens += total
        self._verify_useful_tokens += useful
        return waste

    def verify_waste(self) -> float:
        """Token-weighted mean verify-window waste across every
        :meth:`note_verify`-recorded dispatch (0.0 before any)."""
        if self._verify_window_tokens == 0:
            return 0.0
        return 1.0 - self._verify_useful_tokens / self._verify_window_tokens

    # -- alloc/release -------------------------------------------------------

    def allocate(self, n_blocks: int) -> list[int]:
        """Pop ``n_blocks`` physical block ids (each handed out at
        refcount 1); raises :class:`PoolExhausted` (allocating nothing)
        when short. The free list is consumed first; zero-ref cached
        blocks are evicted from the LRU — oldest first, unpublishing
        their prefix-index entries — only once the free list runs
        dry."""
        if n_blocks > len(self._free) + len(self._lru):
            raise PoolExhausted(
                f"need {n_blocks} blocks, {len(self._free)} free + "
                f"{len(self._lru)} cached "
                f"(pool {self.num_blocks - 1} allocatable)")
        out = []
        for _ in range(n_blocks):
            if not self._free:
                self._evict_cached()
            b = self._free.pop()
            self._ref[b] = 1
            out.append(b)
        self._used += n_blocks
        self.peak_used = max(self.peak_used, self._used)
        return out

    def _evict_cached(self) -> None:
        """Reclaim the least-recently-released cached block: drop its
        index entry (future lookups of that prefix miss from this level
        on) and put the block on the free list."""
        b, _ = self._lru.popitem(last=False)
        key = self._block_key.pop(b)
        del self._index[key]
        self.prefix_evictions += 1
        self._free.append(b)

    def grow(self, table: list[int], n_tokens: int) -> list[int]:
        """Extend ``table`` (a request's block table) to cover
        ``n_tokens`` of context; returns the newly-allocated ids (empty
        when the table already covers it). All-or-nothing on
        :class:`PoolExhausted`."""
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return []
        fresh = self.allocate(need)
        table.extend(fresh)
        return fresh

    def trim(self, table: list[int], n_tokens: int) -> None:
        """Release table blocks beyond what ``n_tokens`` needs (chunked
        prefill pads the prompt to a chunk multiple; the pad tail's
        blocks come back here once the real length is known)."""
        keep = self.blocks_for(n_tokens)
        while len(table) > keep:
            self.release([table.pop()])

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per listed block. A block reaching
        refcount 0 returns to the free list — unless it is registered
        in the prefix index, in which case it parks in the cached-block
        LRU (reusable by future :meth:`match_prefix` hits, reclaimable
        by :meth:`allocate` under pressure). Releasing a block that is
        not held (already free or cached) raises — the double-free
        guard that keeps the free list corruption-proof."""
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"releasing block {b} outside the pool")
            if self._ref[b] == 0:
                raise ValueError(f"double free of block {b} (not held "
                                 "by any table)")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._used -= 1
                if b in self._block_key:
                    self._lru[b] = None     # newest at the end
                else:
                    self._free.append(b)
            else:
                self._extra_refs -= 1
                if self._ref[b] == 1:
                    self._shared_blocks -= 1

    #: legacy name — release() IS the free of the refcounted pool
    free = release

    # -- prefix cache --------------------------------------------------------

    def chain_keys(self, tokens):
        """Yield ``(chain_key, chunk_tokens)`` per FULL block-sized
        chunk of ``tokens`` (:func:`prefix_chain_keys` at this pool's
        ``block_size``) — lazy, and a pure function of the tokens, the
        property that makes index entries reusable even after their
        physical parent blocks were evicted and re-prefilled
        elsewhere."""
        return prefix_chain_keys(tokens, self.block_size)

    def peek_prefix(self, tokens, max_blocks: Optional[int] = None
                    ) -> tuple[list[int], int]:
        """Read-only longest-cached-prefix probe: ``(block_ids,
        n_revivals)`` where ``n_revivals`` counts matched blocks that
        are currently zero-ref (parked in the LRU — committing the
        match removes them from evictable capacity, so an admission
        capacity check must charge for them). Verifies each level's
        stored chunk AND parent key (collision => miss, never wrong
        KV). Mutates NOTHING: a failed admission probe re-run every
        engine iteration must not touch refcounts or perturb LRU
        order. ``max_blocks`` caps the walk — the engine passes
        ``(prompt_len - 1) // block_size`` so at least the final
        prompt token is always recomputed (its logits seed
        generation)."""
        out: list[int] = []
        revivals = 0
        parent = _CHAIN_ROOT
        for key, chunk in self.chain_keys(tokens):
            if max_blocks is not None and len(out) >= max_blocks:
                break
            entry = self._index.get(key)
            if entry is None or entry.chunk != chunk \
                    or entry.parent != parent:
                break
            out.append(entry.block)
            if self._ref[entry.block] == 0:
                revivals += 1
            parent = key
        return out, revivals

    def commit_match(self, blocks: Sequence[int]) -> None:
        """Take one reference on every peeked block (reviving zero-ref
        ones out of the LRU) — the write half of :meth:`peek_prefix`,
        called once admission capacity is assured."""
        for b in blocks:
            if self._ref[b] == 0:
                del self._lru[b]
                self._used += 1
            else:
                self._extra_refs += 1
                if self._ref[b] == 1:
                    self._shared_blocks += 1
            self._ref[b] += 1
        if blocks:
            self.peak_used = max(self.peak_used, self._used)
            self.peak_shared_blocks = max(self.peak_shared_blocks,
                                          self._shared_blocks)
            self.peak_blocks_saved = max(self.peak_blocks_saved,
                                         self._extra_refs)

    def match_prefix(self, tokens, max_blocks: Optional[int] = None
                     ) -> list[int]:
        """Longest cached prefix of ``tokens`` in full blocks, with the
        references taken: peek + commit in one call. The caller owns
        the returned references (release them like any allocated
        block)."""
        out, _ = self.peek_prefix(tokens, max_blocks)
        self.commit_match(out)
        return out

    def register_prefix(self, tokens, table: Sequence[int]) -> int:
        """Publish the full-block prefix of ``tokens`` (whose KV lives
        in ``table``'s leading blocks) into the index; returns how many
        blocks were newly registered. Levels already present keep their
        existing entry — the first writer wins, later identical blocks
        stay private and flow back to the free list on release."""
        registered = 0
        parent = _CHAIN_ROOT
        for i, (key, chunk) in enumerate(self.chain_keys(tokens)):
            if i >= len(table):
                break
            if key not in self._index:
                b = int(table[i])
                if b not in self._block_key:
                    self._index[key] = CachedBlock(b, parent, chunk)
                    self._block_key[b] = key
                    registered += 1
            parent = key
        return registered

    def privatize(self, table: list[int], lo: int, hi: int
                  ) -> list[tuple[int, int]]:
        """Copy-on-write for table blocks ``[lo, hi)`` that a request
        is about to scatter into: a block with refcount > 1 is swapped
        for a freshly-allocated private copy — the returned
        ``(src, dst)`` pairs are the device-side pool copies the CALLER
        must apply (to every pool addressed by this table, target and
        draft alike) before the write dispatch; a sole-owner block that
        is merely registered is unpublished and written in place (no
        copy — nobody else can be reading it). Raises
        :class:`PoolExhausted` if a copy target cannot be allocated."""
        copies: list[tuple[int, int]] = []
        for i in range(lo, min(hi, len(table))):
            b = table[i]
            if self._ref[b] > 1:
                [dst] = self.allocate(1)
                self._ref[b] -= 1
                self._extra_refs -= 1
                if self._ref[b] == 1:
                    self._shared_blocks -= 1
                table[i] = dst
                copies.append((b, dst))
                self.cow_copies += 1
            elif b in self._block_key:
                key = self._block_key.pop(b)
                del self._index[key]
        return copies

    def is_private(self, block: int) -> bool:
        """True when exactly one table holds ``block`` and it is not
        published in the prefix index — the only state a scatter may
        write without :meth:`privatize`."""
        return self._ref[block] == 1 and block not in self._block_key

    def ensure_private(self, table: Sequence[int], lo: int, hi: int) -> None:
        """Assert-style guard: every table block in ``[lo, hi)`` must be
        writable. Decode/verify write spans are private by construction
        (they sit past the cached prompt prefix); a shared block here
        means allocator-state corruption, so fail loudly instead of
        silently clobbering another request's KV."""
        for i in range(lo, min(hi, len(table))):
            if not self.is_private(table[i]):
                raise RuntimeError(
                    f"block {table[i]} (table index {i}) is shared or "
                    f"registered but sits in a write span — allocator "
                    f"state corrupted")

    def blocks_saved(self) -> int:
        """Block allocations the prefix cache is deduplicating RIGHT
        NOW: total extra references beyond each shared block's first
        (= blocks a cache-off run would additionally hold resident)."""
        return self._extra_refs

    def note_shared_reads(self, n_tokens: int) -> None:
        """Account decode/verify KV reads served out of shared
        (refcount >= 2) blocks — the read-side extension of the waste
        accounting: these tokens are resident ONCE but read by several
        requests' gathers."""
        self._shared_read_tokens += int(n_tokens)

    def shared_read_tokens(self, table: Sequence[int],
                           context_len: int) -> int:
        """How many of one slot's ``context_len`` resident tokens live
        in shared blocks (the per-step input to
        :meth:`note_shared_reads`)."""
        bs = self.block_size
        n = 0
        for i in range(self.blocks_for(context_len)):
            if i < len(table) and self._ref[table[i]] >= 2:
                n += min(bs, context_len - i * bs)
        return n

    def shared_read_frac(self) -> float:
        """Fraction of all useful gathered decode tokens that came out
        of shared blocks (0.0 before any decode)."""
        if self._gather_useful_tokens == 0:
            return 0.0
        return self._shared_read_tokens / self._gather_useful_tokens
