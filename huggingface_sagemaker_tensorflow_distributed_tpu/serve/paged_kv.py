"""Paged KV cache bookkeeping: a fixed population of fixed-size blocks,
allocated to requests as their context grows (vLLM, Kwon et al. 2023).

The device side is dumb on purpose — per-layer pools
``[num_blocks, block_size, heads, head_dim]`` plus the gather/scatter
addressing in ``ops.attention`` — so ALL allocation policy lives here in
plain host Python where it is unit-testable without a backend:

- :class:`BlockManager` owns the free list. Block 0 is reserved as the
  **null block**: inactive decode slots scatter their (discarded) step
  writes there, which is what lets the engine's jitted step keep fully
  static shapes with no per-step masking of the write path.
- memory scales with tokens actually resident: a request holds
  ``ceil(context / block_size)`` blocks, not ``max_model_len`` slots.
  Fragmentation is bounded by ``block_size - 1`` tokens per request
  (the partially-filled last block) — the quantity
  :meth:`BlockManager.fragmentation` reports and the tests pin.
- the READ side wastes separately: every decode step gathers a full
  context-width bucket per slot regardless of how much context the slot
  actually holds. :meth:`BlockManager.note_gather` accounts that
  bucket-padded read waste (peak + token-weighted mean) so the serve
  report can show what width bucketing saves.

The engine frees a finished/preempted request's blocks immediately;
there is no refcounting/copy-on-write (no beam forking through the
serve path yet), so a block is owned by exactly one request.
"""

from __future__ import annotations


class PoolExhausted(Exception):
    """Raised by :meth:`BlockManager.allocate` when the pool cannot
    satisfy a request — the scheduler catches it and preempts."""


class BlockManager:
    """Free-list allocator over ``num_blocks`` blocks of ``block_size``
    token slots each. Block 0 is the reserved null block and is never
    handed out."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (block 0 is the reserved "
                             f"null block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed (cache-warm) blocks are reused
        # first; block 0 excluded for good
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self.peak_used = 0
        # bucket-padded READ waste (decode-side, orthogonal to the
        # allocation fragmentation below): latched by note_gather()
        self.peak_gather_waste = 0.0
        self._gather_read_tokens = 0
        self._gather_useful_tokens = 0
        # width-(k+1) verify-window padding (speculative decode),
        # counted SEPARATELY from bucket padding: latched by
        # note_verify()
        self.peak_verify_waste = 0.0
        self._verify_window_tokens = 0
        self._verify_useful_tokens = 0

    # -- capacity arithmetic -------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` context tokens."""
        return -(-max(int(n_tokens), 0) // self.block_size)

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def can_allocate(self, n_blocks: int) -> bool:
        return n_blocks <= len(self._free)

    def utilization(self) -> float:
        """Fraction of allocatable blocks currently held by requests."""
        return self.num_used / max(self.num_blocks - 1, 1)

    def fragmentation(self, context_lens) -> float:
        """Fraction of HELD token slots that are padding inside
        partially-filled last blocks — the paged design's only waste
        (≤ ``(block_size - 1) / block_size`` per request; a contiguous
        ``max_len`` cache wastes ``1 - context/max_len`` instead)."""
        held_tokens = sum(self.blocks_for(c) * self.block_size
                          for c in context_lens)
        if held_tokens == 0:
            return 0.0
        used_tokens = sum(int(c) for c in context_lens)
        return 1.0 - used_tokens / held_tokens

    def note_gather(self, context_lens, width: int) -> float:
        """Record one decode step's bucket-padded KV READ: the gather
        materializes ``width`` token slots per ACTIVE slot while only
        that slot's context is useful, so the step's read waste is
        ``1 - sum(context) / (slots * width)``. This is the decode-side
        counterpart of :meth:`fragmentation` (which accounts allocation
        padding): bucketing exists precisely to shrink it, and the
        engine surfaces both the PEAK (``peak_gather_waste``, latched
        here) and the token-weighted run mean (:meth:`gather_waste`) in
        its ``serve`` report event and the bench detail line. Returns
        the step's waste fraction (0.0 for an empty step)."""
        read = len(context_lens) * int(width)
        if read == 0:
            return 0.0
        useful = sum(min(int(c), int(width)) for c in context_lens)
        waste = 1.0 - useful / read
        self.peak_gather_waste = max(self.peak_gather_waste, waste)
        self._gather_read_tokens += read
        self._gather_useful_tokens += useful
        return waste

    def gather_waste(self) -> float:
        """Token-weighted mean bucket-padded read waste across every
        :meth:`note_gather`-recorded decode step (0.0 before any)."""
        if self._gather_read_tokens == 0:
            return 0.0
        return 1.0 - self._gather_useful_tokens / self._gather_read_tokens

    def note_verify(self, committed, window: int) -> float:
        """Record one speculative VERIFY dispatch's window padding: each
        active slot computes ``window`` (= k+1) query positions but only
        its ``committed`` tokens (accepted prefix + bonus, post EOS /
        budget truncation) were useful — the rejected tail is the
        width-(k+1) analogue of bucket padding, and it is accounted
        SEPARATELY from :meth:`note_gather` (which this dispatch also
        feeds, for its KV read) so the serve report can tell "we read
        too wide" from "we speculated too deep". Returns the dispatch's
        waste fraction (0.0 for an empty step)."""
        total = len(committed) * int(window)
        if total == 0:
            return 0.0
        useful = sum(min(int(c), int(window)) for c in committed)
        waste = 1.0 - useful / total
        self.peak_verify_waste = max(self.peak_verify_waste, waste)
        self._verify_window_tokens += total
        self._verify_useful_tokens += useful
        return waste

    def verify_waste(self) -> float:
        """Token-weighted mean verify-window waste across every
        :meth:`note_verify`-recorded dispatch (0.0 before any)."""
        if self._verify_window_tokens == 0:
            return 0.0
        return 1.0 - self._verify_useful_tokens / self._verify_window_tokens

    # -- alloc/free ----------------------------------------------------------

    def allocate(self, n_blocks: int) -> list[int]:
        """Pop ``n_blocks`` physical block ids; raises
        :class:`PoolExhausted` (allocating nothing) when short."""
        if n_blocks > len(self._free):
            raise PoolExhausted(
                f"need {n_blocks} blocks, {len(self._free)} free "
                f"(pool {self.num_blocks - 1} allocatable)")
        out = [self._free.pop() for _ in range(n_blocks)]
        self.peak_used = max(self.peak_used, self.num_used)
        return out

    def grow(self, table: list[int], n_tokens: int) -> list[int]:
        """Extend ``table`` (a request's block table) to cover
        ``n_tokens`` of context; returns the newly-allocated ids (empty
        when the table already covers it). All-or-nothing on
        :class:`PoolExhausted`."""
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return []
        fresh = self.allocate(need)
        table.extend(fresh)
        return fresh

    def trim(self, table: list[int], n_tokens: int) -> None:
        """Free table blocks beyond what ``n_tokens`` needs (chunked
        prefill pads the prompt to a chunk multiple; the pad tail's
        blocks come back here once the real length is known)."""
        keep = self.blocks_for(n_tokens)
        while len(table) > keep:
            self.free([table.pop()])

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"freeing block {b} outside the pool")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
