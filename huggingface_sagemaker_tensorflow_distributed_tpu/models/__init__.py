from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (  # noqa: F401
    EncoderConfig,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (  # noqa: F401
    MODEL_REGISTRY,
    build_model,
    from_pretrained,
    save_pretrained,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (  # noqa: F401
    T5Config,
    T5ForConditionalGeneration,
)
# the submodule is the API: models.generate.generate(...); importing the
# function here would shadow the module with the same name
