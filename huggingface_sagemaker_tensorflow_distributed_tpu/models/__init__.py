from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (  # noqa: F401
    EncoderConfig,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.auto import (  # noqa: F401
    MODEL_REGISTRY,
    build_model,
    from_pretrained,
    save_pretrained,
)
