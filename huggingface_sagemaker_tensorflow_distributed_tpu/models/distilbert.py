"""DistilBERT models + task heads.

Covers the BASELINE.json parity config "DistilBERT-base seq-classification
on IMDb (CPU)" — the single-node baseline mirroring reference
``scripts/singe_node_train.py``. Structure: BERT layers without token-type
embeddings or pooler; seq-cls head is pre_classifier(+ReLU) → dropout →
classifier on the CLS token (HF ``DistilBertForSequenceClassification``
parity).
"""

from __future__ import annotations

import flax.linen as nn
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderBackbone,
    EncoderConfig,
    _dense,
    MlmHead,
)


def distilbert_config_from_hf(hf_config: dict, **overrides) -> EncoderConfig:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["dim"],
        num_layers=hf_config["n_layers"],
        num_heads=hf_config["n_heads"],
        intermediate_size=hf_config["hidden_dim"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        hidden_act=hf_config.get("activation", "gelu"),
        layer_norm_eps=1e-12,
        hidden_dropout=hf_config.get("dropout", 0.1),
        attention_dropout=hf_config.get("attention_dropout", 0.1),
        pad_token_id=hf_config.get("pad_token_id", 0),
        initializer_range=hf_config.get("initializer_range", 0.02),
        use_token_type=False,
        use_pooler=False,
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


class DistilBertForSequenceClassification(nn.Module):
    """CLS → pre_classifier → ReLU → dropout → classifier (HF parity)."""

    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq, _ = EncoderBackbone(cfg, name="backbone")(
            input_ids, attention_mask, None, deterministic=deterministic)
        x = seq[:, 0]
        x = jax.nn.relu(_dense(cfg, cfg.hidden_size, "pre_classifier")(x))
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return _dense(cfg, self.num_labels, "classifier")(x)


class DistilBertForTokenClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 9

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, None, deterministic=deterministic)
        x = nn.Dropout(self.config.hidden_dropout)(seq, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class DistilBertForQuestionAnswering(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        import jax.numpy as jnp
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, None, deterministic=deterministic)
        logits = _dense(self.config, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class DistilBertForMaskedLM(nn.Module):
    """Masked-LM head tied to the word embeddings (HF
    ``DistilBertForMaskedLM`` parity; covers whole-word-masking pretraining —
    the reference's default checkpoint is
    ``bert-large-uncased-whole-word-masking``, reference ``launch.py:17``)."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        table = self.variables["params"]["backbone"]["embeddings"][
            "word_embeddings"]["embedding"]
        head = MlmHead(self.config, name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)
