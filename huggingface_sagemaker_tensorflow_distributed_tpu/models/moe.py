"""Mixture-of-Experts feed-forward with expert parallelism.

Beyond-parity capability (the reference has no MoE or expert
parallelism, SURVEY.md §2 parallelism inventory): a GShard/Switch-style
token-routed MoE FFN designed TPU-first —

- **Dense dispatch/combine einsums**, no scatter/gather: routing is
  expressed as one-hot dispatch tensors contracted on the MXU, the only
  MoE formulation that maps onto XLA's static-shape compilation model.
- **Expert parallelism via sharding annotations**: expert weights carry
  ``PartitionSpec("expert", ...)`` (``parallel/sharding.py``) and the
  dispatched activations are constrained expert-major, so XLA inserts
  the token all-to-alls over the ``expert`` mesh axis — no hand-written
  collectives, same ambient-distribution stance as the rest of the
  framework.
- **Static capacity**: each expert processes a fixed ``capacity`` slots
  per group (batch row); over-capacity tokens fall through on the
  residual path (standard GShard semantics, no dynamic shapes).

The router computes in fp32 (softmax over expert logits is precision
-sensitive); expert matmuls run in the model compute dtype (bf16 on
TPU). The Switch load-balance auxiliary loss is sowed into the
``losses`` collection; the Trainer adds every sowed value to the task
loss (``train/trainer.py``).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import ACT2FN, EncoderConfig


def expert_capacity(cfg: EncoderConfig, seq_len: int) -> int:
    """Static per-group expert capacity: ceil(k·S·factor / E), rounded up
    to a multiple of 4 so the slot dim tiles onto the VPU lanes."""
    raw = cfg.expert_capacity_factor * cfg.expert_top_k * seq_len / cfg.num_experts
    return max(4, 4 * math.ceil(raw / 4))


def _constrain(x, *spec):
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
        constrain_if_mesh,
    )

    return constrain_if_mesh(x, *spec)


def topk_dispatch(probs, k: int, C: int, causal: bool):
    """Greedy top-k routing → capacity-slot dispatch, shared by every
    MoE flavor (Switch/GShard encoder FFN and Mixtral SwiGLU).

    Returns ``(combine [B,S,E,C] fp32, top1_mask [B,S,E])`` where
    ``combine`` carries each kept token→slot assignment weighted by its
    gate, normalized per token over its total selected top-k mass
    (Mixtral/HF convention — capacity-dropped choices keep zero
    dispatch and the token rides the residual). Slot priority is
    round-major (GShard) or position-major (``causal=True``, see
    ``MoeFeedForward`` docstring for why causal LMs need it).
    """
    B, S, E = probs.shape
    remaining = probs
    masks, gates = [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                   # [B,S]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)       # [B,S,E]
        gates.append(jnp.sum(remaining * mask, axis=-1))       # [B,S]
        remaining = remaining * (1.0 - mask)
        masks.append(mask)
    top1_mask = masks[0]

    if causal:
        # position-major: slot = #assignments to the chosen expert
        # from strictly-earlier tokens (any round). Rounds of one
        # token hit distinct experts, so slots stay collision-free,
        # and nothing about token i depends on tokens j > i.
        total = sum(masks)                                     # [B,S,E]
        prefix = jnp.cumsum(total, axis=1) - total
        slot_pos = [prefix] * k
    else:
        # round-major (GShard): all round-r slots precede round-r+1
        slot_pos = []
        counts = jnp.zeros((B, E), jnp.float32)
        for mask in masks:
            slot_pos.append(
                jnp.cumsum(mask, axis=1) - 1.0 + counts[:, None, :])
            counts = counts + jnp.sum(mask, axis=1)

    combine = jnp.zeros((B, S, E, C), jnp.float32)
    gate_total = jnp.zeros((B, S), jnp.float32)
    for mask, gate, pos in zip(masks, gates, slot_pos):
        slot = jnp.sum(pos * mask, axis=-1)                    # [B,S]
        kept = (slot < C) & (gate > 0.0)
        slot_oh = jax.nn.one_hot(jnp.where(kept, slot, 0).astype(jnp.int32),
                                 C, dtype=jnp.float32)         # [B,S,C]
        disp = (mask[..., None] * slot_oh[:, :, None, :]
                * kept[:, :, None, None].astype(jnp.float32))  # [B,S,E,C]
        combine = combine + gate[:, :, None, None] * disp
        gate_total = gate_total + gate

    denom = jnp.where(gate_total > 0.0, gate_total, 1.0)
    return combine / denom[:, :, None, None], top1_mask


def _route_and_dispatch(module: nn.Module, hidden, cfg, causal: bool):
    """The scaffolding every MoE flavor shares: fp32 router + softmax,
    :func:`topk_dispatch`, the Switch aux-loss sow, and the token→expert
    all-to-all (dispatch einsum + expert-major sharding constraint).
    Returns ``(expert_in [E,B,C,H], combine [B,S,E,C] fp32,
    non_expert_axes)``; the caller runs its expert FFN on ``expert_in``
    and combines with ``combine``. One implementation so router
    precision, the aux formula, and the sharding constraints cannot
    drift between the encoder MoE and Mixtral."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_EXPERT,
        data_axis_names,
    )

    E, k = cfg.num_experts, cfg.expert_top_k
    _, S, H = hidden.shape
    C = expert_capacity(cfg, S)

    router = module.param(
        "router", nn.initializers.normal(cfg.initializer_range), (H, E),
        jnp.float32)
    # fp32 router: logits/softmax precision decides routing stability
    logits = jnp.einsum("bsh,he->bse", hidden.astype(jnp.float32), router)
    probs = jax.nn.softmax(logits, axis=-1)                    # [B,S,E]

    combine, top1_mask = topk_dispatch(probs, k, C, causal)
    dispatch = (combine > 0.0).astype(cfg.dtype)               # [B,S,E,C]

    # Switch load-balance loss (top-1 fractions × mean probs)
    frac = jnp.mean(top1_mask, axis=(0, 1))                    # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1))                   # [E]
    aux = cfg.router_aux_coef * E * jnp.sum(frac * mean_prob)
    module.sow("losses", "moe_aux", aux)

    # [E,B,C,H]: E sharded over ``expert``, B over the other data
    # axes — the resharding from token-major is the all-to-all
    non_expert_axes = tuple(a for a in data_axis_names()
                            if a != AXIS_EXPERT)
    expert_in = jnp.einsum("bsec,bsh->ebch", dispatch,
                           hidden.astype(cfg.dtype))
    expert_in = _constrain(expert_in, AXIS_EXPERT, non_expert_axes)
    return expert_in, combine, non_expert_axes


class MoeFeedForward(nn.Module):
    """Drop-in replacement for ``FeedForward`` on MoE layers.

    Input/output: [batch, seq, hidden]. Each batch row is a routing
    group (tokens compete for expert slots within their own row — keeps
    the dispatch tensor O(S·E·C) per row and routing independent of the
    data sharding).

    Capacity-slot priority has two modes:

    - bidirectional (default): round-major, GShard-style — every top-1
      choice outranks any top-2 choice, so congestion preferentially
      drops second choices.
    - ``causal=True``: position-major — a token's slot index counts only
      assignments from strictly-earlier tokens (any round). Required for
      causal LMs: under round-major priority, whether token i's
      second-choice slot survives depends on the top-1 routing of tokens
      j > i, which leaks future-token information through the capacity
      drop pattern. (Capacity drops themselves remain a train-time-only
      phenomenon: incremental decode processes one token with no slot
      competition — the standard capacity-MoE asymmetry.)

    ``out_init_std`` overrides the output-projection init so residual
    -flow conventions (e.g. GPT-2's 1/sqrt(2·n_layer) scaling on every
    residual write) carry over to the expert bank.
    """

    config: EncoderConfig
    causal: bool = False
    out_init_std: float | None = None

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
            AXIS_EXPERT,
        )

        cfg = self.config
        E = cfg.num_experts
        _, _, H = hidden.shape
        F = cfg.intermediate_size

        expert_in, combine, non_expert_axes = _route_and_dispatch(
            self, hidden, cfg, self.causal)

        wi = self.param("wi", nn.initializers.normal(cfg.initializer_range),
                        (E, H, F), cfg.param_dtype)
        wo = self.param(
            "wo",
            nn.initializers.normal(self.out_init_std
                                   if self.out_init_std is not None
                                   else cfg.initializer_range),
            (E, F, H), cfg.param_dtype)
        h = jnp.einsum("ebch,ehf->ebcf", expert_in, wi.astype(cfg.dtype))
        h = ACT2FN[cfg.hidden_act](h)
        out = jnp.einsum("ebcf,efh->ebch", h, wo.astype(cfg.dtype))
        out = _constrain(out, AXIS_EXPERT, non_expert_axes)

        y = jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), out)
        y = nn.Dropout(cfg.hidden_dropout)(y, deterministic=deterministic)
        return y


class MixtralMoeBlock(nn.Module):
    """Mixtral-style sparse MoE for the Llama family: SwiGLU experts
    (``w2(silu(w1 x) * w3 x)``, HF ``MixtralBlockSparseTop2MLP`` naming)
    behind the same dense-dispatch top-k router as ``MoeFeedForward``.

    HF parity notes (``MixtralSparseMoeBlock``):
    - the router (``gate``) computes in fp32 and gates are the full
      softmax renormalized over the selected top-k (HF's
      ``routing_weights /= routing_weights.sum``) — exactly what
      ``topk_dispatch`` produces;
    - HF processes every routed token; this block keeps the framework's
      static expert capacity (GShard semantics), so over-capacity tokens
      ride the residual during training — at parity-test capacity
      (factor >= E/k) the two are numerically identical;
    - slot priority is always position-major (``causal=True``): this is
      a causal-LM family, and round-major priority leaks future-token
      information through the capacity drop pattern (see
      ``MoeFeedForward`` docstring).

    No dropout (the Llama family has none). The Switch aux loss sows
    into ``losses`` like the encoder MoE.
    """

    config: object  # LlamaConfig (annotated loosely to avoid a cycle)

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
            AXIS_EXPERT,
        )

        cfg = self.config
        E = cfg.num_experts
        _, _, H = hidden.shape
        F = cfg.intermediate_size

        expert_in, combine, non_expert_axes = _route_and_dispatch(
            self, hidden, cfg, causal=True)

        init = nn.initializers.normal(cfg.initializer_range)
        w1 = self.param("w1", init, (E, H, F), cfg.param_dtype)    # gate
        w3 = self.param("w3", init, (E, H, F), cfg.param_dtype)    # up
        w2 = self.param("w2", init, (E, F, H), cfg.param_dtype)    # down
        act = ACT2FN[cfg.hidden_act]
        g = act(jnp.einsum("ebch,ehf->ebcf", expert_in, w1.astype(cfg.dtype)))
        u = jnp.einsum("ebch,ehf->ebcf", expert_in, w3.astype(cfg.dtype))
        out = jnp.einsum("ebcf,efh->ebch", g * u, w2.astype(cfg.dtype))
        out = _constrain(out, AXIS_EXPERT, non_expert_axes)

        return jnp.einsum("bsec,ebch->bsh", combine.astype(cfg.dtype), out)
