"""Shared transformer building blocks (Flax).

TPU-native replacement for the model architectures the reference
delegates entirely to ``transformers`` TF models (reference
``scripts/train.py:117``; SURVEY.md component D7). One set of blocks
serves BERT / RoBERTa / DistilBERT; module names are chosen so parameter
paths line up with the tensor-parallel sharding rules in
``parallel/sharding.py`` (query/key/value/attention_out, intermediate/
ffn_out, embedding, pooler, classifier).

Numerics: parameters live in ``param_dtype`` (fp32), compute runs in
``dtype`` (bf16 on TPU for MXU throughput), layernorm statistics and
attention softmax in fp32 — the bf16 discipline SURVEY.md §7 hard-part 5
calls for.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
import jax

from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    dot_product_attention,
    make_attention_mask,
)

ACT2FN: dict[str, Callable] = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),  # HF "gelu" is erf-exact
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    # HF's name for the same tanh approximation (Gemma's default)
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "tanh": jnp.tanh,
}


@dataclass(frozen=True)
class EncoderConfig:
    """Architecture hyperparameters shared by the BERT-family encoders."""

    vocab_size: int = 30522
    hidden_size: int = 768
    # ELECTRA-style factorized embeddings: embed at this width, project
    # to hidden_size in the backbone. None = hidden_size (BERT/RoBERTa).
    embedding_size: Optional[int] = None
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_act: str = "gelu"
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    # task-head dropout; None = hidden_dropout (BERT/ELECTRA semantics).
    # ALBERT's HF default genuinely differs (classifier_dropout_prob=0.1
    # with hidden_dropout_prob=0.0), so it needs its own knob.
    classifier_dropout: Optional[float] = None
    pad_token_id: int = 0
    position_offset: int = 0      # RoBERTa: pad_token_id + 1
    use_token_type: bool = True   # DistilBERT: False
    use_pooler: bool = True       # DistilBERT: False
    initializer_range: float = 0.02
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"   # xla | flash (pallas)
    remat: bool = False           # rematerialize encoder layers (trade FLOPs for HBM)
    # With remat=True, WHAT is recomputed vs saved at layer boundaries
    # ("full" = classic save-nothing remat). "dots" saves every matmul
    # output and recomputes only the cheap elementwise/VPU ops — far
    # fewer recompute FLOPs for most of the HBM win; "dots_no_batch"
    # additionally refuses to save batch-dim matmul results (the XLA
    # offloading-friendly policy). Candidates for the >=0.45-MFU push:
    # remat buys batch headroom past the spill wall without full
    # recompute cost.
    remat_policy: str = "full"    # full | dots | dots_no_batch
    # Mixture-of-Experts (models/moe.py): 0 = dense FFN everywhere.
    # When > 0, every ``moe_every``-th layer (the 2nd, 4th, ... — GShard
    # placement) swaps its FFN for a token-routed expert bank sharded
    # over the ``expert`` mesh axis.
    num_experts: int = 0
    expert_top_k: int = 2
    expert_capacity_factor: float = 1.25
    moe_every: int = 2
    router_aux_coef: float = 0.01
    # Pipeline parallelism (models/pipeline.py): 0 = dense Encoder.
    # When > 0 the encoder runs a GPipe schedule over layer-stacked
    # params sharded over the ``pipe`` mesh axis.
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0   # 0 → = pipeline_stages
    # Rematerialize the attention core only: the fp32 [B,H,S,S] softmax
    # residuals XLA otherwise saves (and copies) for backward dominate HBM
    # traffic at seq 512 — recomputing them in backward is measurably
    # faster on TPU (and far lighter on memory). Independent of ``remat``.
    remat_attention: bool = True


def remat_policy(name: str):
    """jax.checkpoint saveable-op policy for ``EncoderConfig.remat_policy``
    (None = save nothing, the classic full remat)."""
    if name == "full":
        return None
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if name == "dots_no_batch":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    raise ValueError(f"unknown remat_policy {name!r} "
                     "(full | dots | dots_no_batch)")


def _dense(cfg: EncoderConfig, features: int, name: str) -> nn.Dense:
    return nn.Dense(
        features,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.initializers.normal(cfg.initializer_range),
        name=name,
    )


def head_dropout_rate(cfg: EncoderConfig) -> float:
    """Dropout rate for task heads (classifier_dropout falling back to
    hidden_dropout, HF semantics)."""
    return (cfg.classifier_dropout if cfg.classifier_dropout is not None
            else cfg.hidden_dropout)


def _layernorm(cfg: EncoderConfig, name: str) -> nn.LayerNorm:
    # stats in fp32 even under bf16 compute
    return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name=name)


class Embeddings(nn.Module):
    """Word + learned-position (+ token-type) embeddings with LN/dropout.

    Parity target: HF ``BertEmbeddings`` / ``RobertaEmbeddings`` /
    ``DistilBert Embeddings`` as exercised via reference
    ``scripts/train.py:117``. RoBERTa's position ids start at
    ``position_offset`` past-pad convention is reproduced via the config.
    """

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, position_ids=None,
                 attention_mask=None, deterministic: bool = True):
        cfg = self.config
        emb = cfg.embedding_size or cfg.hidden_size
        word = nn.Embed(cfg.vocab_size, emb,
                        embedding_init=nn.initializers.normal(cfg.initializer_range),
                        dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        name="word_embeddings")(input_ids)
        if position_ids is None:
            seq_len = input_ids.shape[-1]
            if cfg.position_offset and attention_mask is not None:
                # RoBERTa convention: positions count only non-pad tokens,
                # starting at position_offset (= pad_token_id + 1).
                position_ids = jnp.cumsum(attention_mask, axis=-1) * attention_mask
                position_ids = position_ids + cfg.position_offset - 1
                position_ids = position_ids * attention_mask + cfg.pad_token_id * (1 - attention_mask)
            else:
                position_ids = jnp.arange(cfg.position_offset,
                                          seq_len + cfg.position_offset)[None, :]
        pos = nn.Embed(cfg.max_position_embeddings, emb,
                       embedding_init=nn.initializers.normal(cfg.initializer_range),
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="position_embeddings")(position_ids)
        x = word + pos
        if cfg.use_token_type:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + nn.Embed(cfg.type_vocab_size, emb,
                             embedding_init=nn.initializers.normal(cfg.initializer_range),
                             dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             name="token_type_embeddings")(token_type_ids)
        x = _layernorm(cfg, "embeddings_ln")(x)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return x


class SelfAttention(nn.Module):
    """Multi-head self-attention (post-LN residual handled by caller).

    QKV projections are column-parallel and the output projection
    row-parallel under the ``tensor`` mesh axis (see
    ``parallel/sharding.py``); with tensor parallelism XLA inserts a
    single all-reduce after ``attention_out`` — the Megatron pattern.
    """

    config: EncoderConfig

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_heads

        def split(x):
            b, s, _ = x.shape
            return x.reshape(b, s, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)

        q = split(_dense(cfg, cfg.hidden_size, "query")(hidden))
        k = split(_dense(cfg, cfg.hidden_size, "key")(hidden))
        v = split(_dense(cfg, cfg.hidden_size, "value")(hidden))

        attn_fn = dot_product_attention
        if cfg.remat_attention and cfg.attention_impl == "xla":
            attn_fn = jax.checkpoint(
                lambda q, k, v, mask: dot_product_attention(q, k, v, mask=mask,
                                                            impl="xla"))
            ctx = attn_fn(q, k, v, attn_mask)
        else:
            ctx = attn_fn(q, k, v, mask=attn_mask, impl=cfg.attention_impl)
        b, h, s, d = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        out = _dense(cfg, cfg.hidden_size, "attention_out")(ctx)
        out = nn.Dropout(cfg.hidden_dropout)(out, deterministic=deterministic)
        return out


class FeedForward(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cfg = self.config
        x = _dense(cfg, cfg.intermediate_size, "intermediate")(hidden)
        x = ACT2FN[cfg.hidden_act](x)
        x = _dense(cfg, cfg.hidden_size, "ffn_out")(x)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return x


class EncoderLayer(nn.Module):
    """Post-LN transformer layer (BERT family ordering). ``use_moe``
    swaps the dense FFN for the expert-parallel MoE bank."""

    config: EncoderConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        attn = SelfAttention(cfg, name="attention")(hidden, attn_mask, deterministic)
        hidden = _layernorm(cfg, "attention_ln")(hidden + attn)
        if self.use_moe:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.moe import (
                MoeFeedForward,
            )

            ffn = MoeFeedForward(cfg, name="moe")(hidden, deterministic)
        else:
            ffn = FeedForward(cfg, name="ffn")(hidden, deterministic)
        hidden = _layernorm(cfg, "ffn_ln")(hidden + ffn)
        return hidden


def is_moe_layer(cfg: EncoderConfig, layer_index: int) -> bool:
    """GShard placement: every ``moe_every``-th layer, starting with the
    2nd (index 1 when moe_every=2)."""
    return (cfg.num_experts > 0
            and layer_index % cfg.moe_every == cfg.moe_every - 1)


class Encoder(nn.Module):
    """Stack of encoder layers; optional per-layer rematerialization."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        layer_cls = EncoderLayer
        if cfg.remat:
            layer_cls = nn.remat(EncoderLayer, static_argnums=(3,),
                                 policy=remat_policy(cfg.remat_policy))
        for i in range(cfg.num_layers):
            hidden = layer_cls(cfg, use_moe=is_moe_layer(cfg, i),
                               name=f"layer_{i}")(hidden, attn_mask, deterministic)
        return hidden


class Pooler(nn.Module):
    """CLS-token pooler (tanh dense), as in HF BERT."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        cls = hidden[:, 0]
        return jnp.tanh(_dense(cfg, cfg.hidden_size, "pooler")(cls))


class MlmHead(nn.Module):
    """Masked-LM prediction head: transform dense + activation + LN,
    then a decoder TIED to the word-embedding table (passed in by the
    family model, which reads it from its own bound variables) plus an
    output bias — HF ``BertLMPredictionHead`` / ``RobertaLMHead`` /
    DistilBERT ``vocab_transform``+``vocab_projector`` parity.
    ``act`` overrides the config activation for heads HF hardcodes
    (ELECTRA's generator always uses gelu)."""

    config: EncoderConfig
    act: Optional[str] = None

    @nn.compact
    def __call__(self, hidden, embedding_table, return_transform: bool = False):
        cfg = self.config
        x = _dense(cfg, embedding_table.shape[1], "transform")(hidden)
        x = ACT2FN[self.act or cfg.hidden_act](x)
        x = _layernorm(cfg, "ln")(x)
        bias = self.param("bias", nn.initializers.zeros,
                          (embedding_table.shape[0],), cfg.param_dtype)
        if return_transform:
            # fused vocab-CE path: hand back the post-transform activations
            # + decoder bias so the [B, S, V] logits never materialize
            # (ops/pallas_vocab_ce.py; train/trainer.py::make_fused_mlm_loss)
            return x, bias
        logits = jnp.einsum("bsh,vh->bsv", x,
                            embedding_table.astype(cfg.dtype))
        return (logits + bias.astype(cfg.dtype)).astype(jnp.float32)


class EncoderBackbone(nn.Module):
    """Embeddings + encoder (+ pooler): the shared trunk for all heads."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic: bool = True,
                 segment_ids=None):
        cfg = self.config
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        # segment_ids (token-packed batches): block-diagonal mask so
        # packed examples never attend across segment boundaries
        additive_mask = make_attention_mask(attention_mask,
                                            segment_ids=segment_ids)
        x = Embeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, position_ids, attention_mask, deterministic)
        if cfg.embedding_size and cfg.embedding_size != cfg.hidden_size:
            # ELECTRA factorized-embedding projection (HF
            # ``ElectraModel.embeddings_project``)
            x = _dense(cfg, cfg.hidden_size, "embeddings_project")(x)
        if cfg.pipeline_stages:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                PipelinedEncoder,
            )

            x = PipelinedEncoder(cfg, name="pipelined_encoder")(
                x, additive_mask, deterministic)
        else:
            x = Encoder(cfg, name="encoder")(x, additive_mask, deterministic)
        pooled = Pooler(cfg, name="pooler")(x) if cfg.use_pooler else None
        return x, pooled
