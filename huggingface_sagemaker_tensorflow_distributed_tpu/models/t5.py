"""T5 encoder-decoder models (Flax).

TPU-native replacement for the seq2seq slice of the capability surface the
reference delegates to HF ``transformers`` (reference
``scripts/train.py:117`` loads any ``TFAutoModel*`` checkpoint; SURVEY.md
D7 lists T5 encoder-decoder + seq2seq-LM head as the breadth target).

Architecture parity with HF T5: RMSNorm (no mean subtraction, no bias),
pre-LN residual blocks, relative-position-bucket attention bias held by
the first block of each stack and shared down the stack, no attention
scaling (folded into init), ReLU or gated-GeLU FFN (t5 v1.0 / v1.1),
tied input/output embeddings with the ``d_model**-0.5`` logit scale.

Decode path: every attention module supports an incremental KV cache
(``"cache"`` variable collection, grown with ``lax.dynamic_update_slice``)
so autoregressive generation is O(T) per step with static shapes — the
XLA-friendly form of generation (no Python control flow inside the loop;
see ``models/generate.py``).

Module names (``query``/``key``/``value``/``attention_out``, ``wi``/``wo``,
``shared``) line up with the tensor-parallel rules in
``parallel/sharding.py`` — T5 shards over the same mesh axes as the
encoder-only families.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    remat_policy,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    relative_position_bias,
    relative_position_bucket,  # bucket math shared with the ring kernel
    xla_attention,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.ring_attention import (
    ring_attention_or_fallback,
)

NEG_INF = -1e9


@dataclass(frozen=True)
class T5Config:
    """T5 architecture hyperparameters (HF ``T5Config`` field parity)."""

    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"      # "relu" (t5) | "gated-gelu" (t5 v1.1)
    tie_word_embeddings: bool = True
    pad_token_id: int = 0
    eos_token_id: int = 1
    decoder_start_token_id: int = 0
    initializer_factor: float = 1.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str = "full"           # full | dots | dots_no_batch
    # "xla" (default) or "ring": with a seq mesh axis the ENCODER
    # self-attention runs sequence-parallel ring attention, re-tiling the
    # relative-position bias per ring step from global positions (the
    # full [S, S] bias never materializes). Decoder/cross/KV-cache paths
    # materialize the bias from the same table and run XLA —
    # numerics-identical (tests/test_t5_ring.py).
    attention_impl: str = "xla"
    # GPipe pipeline parallelism over BOTH stacks (models/pipeline.py::
    # PipelinedT5Stack): 0 = dense. Training/scoring path; generation
    # (KV cache) reloads dense like GPT-2's pipelined stack.
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    # int8 weight-only dense kernels for generation (models/quant.py)
    weight_quant: str = "none"           # none | int8

    @property
    def is_gated_act(self) -> bool:
        return self.feed_forward_proj.startswith("gated-")

    @property
    def act_fn(self):
        act = self.feed_forward_proj.split("-")[-1]
        return {"relu": jax.nn.relu,
                "gelu": lambda x: jax.nn.gelu(x, approximate=True),
                "silu": jax.nn.silu}[act]


def t5_config_from_hf(hf_config: dict, **overrides) -> T5Config:
    """Map an HF T5Config dict (config.json) to our T5Config."""
    ff_proj = hf_config.get("feed_forward_proj", "relu")
    if hf_config.get("is_gated_act") and not ff_proj.startswith("gated-"):
        ff_proj = "gated-" + ff_proj
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        d_model=hf_config["d_model"],
        d_kv=hf_config["d_kv"],
        d_ff=hf_config["d_ff"],
        num_layers=hf_config["num_layers"],
        num_decoder_layers=hf_config.get("num_decoder_layers",
                                         hf_config["num_layers"]),
        num_heads=hf_config["num_heads"],
        relative_attention_num_buckets=hf_config.get(
            "relative_attention_num_buckets", 32),
        relative_attention_max_distance=hf_config.get(
            "relative_attention_max_distance", 128),
        dropout_rate=hf_config.get("dropout_rate", 0.1),
        layer_norm_epsilon=hf_config.get("layer_norm_epsilon", 1e-6),
        feed_forward_proj=ff_proj,
        tie_word_embeddings=hf_config.get("tie_word_embeddings", True),
        pad_token_id=hf_config.get("pad_token_id", 0),
        eos_token_id=hf_config.get("eos_token_id", 1),
        decoder_start_token_id=hf_config.get("decoder_start_token_id", 0),
        initializer_factor=hf_config.get("initializer_factor", 1.0),
    )
    kw.update(overrides)
    return T5Config(**kw)


class RMSNorm(nn.Module):
    """T5 layernorm: scale-only RMS normalization, statistics in fp32."""

    config: T5Config

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           cfg.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        x32 = x32 * lax.rsqrt(var + cfg.layer_norm_epsilon)
        return (x32 * scale.astype(jnp.float32)).astype(cfg.dtype)




def _t5_dense(cfg, features: int, std: float, name: str) -> nn.Module:
    """T5's bias-free dense — fp or int8 via the shared chokepoint
    (``models/quant.py::make_dense``) — used by attention and FFN."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
        make_dense,
    )
    return make_dense(cfg, features, nn.initializers.normal(std),
                      use_bias=False, name=name)


class T5Attention(nn.Module):
    """Multi-head attention, T5 flavor: no bias, no sqrt(d) scaling,
    optional relative-position bias, optional incremental KV cache."""

    config: T5Config
    causal: bool = False
    has_rel_bias: bool = False

    def _dense(self, features: int, name: str) -> nn.Module:
        cfg = self.config
        # HF init: q scaled by (d_model * d_kv)^-0.5, k/v/o by d_model^-0.5;
        # the fine-tune path overwrites these with checkpoint weights anyway.
        return _t5_dense(cfg, features,
                         cfg.initializer_factor * cfg.d_model ** -0.5, name)

    def _rel_bias_embed(self) -> nn.Embed:
        """The ONE construction of the rel_bias embedding — xla mode
        gathers dense bias through it, ring mode materializes its raw
        table; both modes must create the identical param
        (tests/test_t5_ring.py::test_t5_ring_param_tree_matches_xla)."""
        cfg = self.config
        return nn.Embed(cfg.relative_attention_num_buckets, cfg.num_heads,
                        embedding_init=nn.initializers.normal(
                            cfg.initializer_factor * cfg.d_model ** -0.5),
                        dtype=jnp.float32, param_dtype=cfg.param_dtype,
                        name="rel_bias")

    def _position_bias(self, q_len: int, kv_len: int, offset=None):
        """[1, heads, q_len, kv_len] learned bias from bucketed relative
        positions. ``offset`` shifts query positions (decode with cache);
        a PER-ROW [B] offset (rows at different depths under speculative
        decode) yields a [B, heads, q_len, kv_len] bias. Uniform decode
        (generate/beam) also takes the per-row branch since cache_index
        is stored [B]; the extra cost is a batched bucket computation +
        embed gather at decode shapes (q=1, kv=target_len) — noise next
        to the step's matmuls, so no scalar fast path is kept."""
        cfg = self.config
        ctx = jnp.arange(q_len)[:, None]
        if offset is not None:
            off = jnp.asarray(offset)
            ctx = (ctx + off if off.ndim == 0
                   else ctx[None] + off[:, None, None])       # [B, q, 1]
        mem = jnp.arange(kv_len)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, bidirectional=not self.causal,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance)
        values = self._rel_bias_embed()(buckets)
        if values.ndim == 4:                                  # [B, q, kv, h]
            return values.transpose(0, 3, 1, 2)
        return values.transpose(2, 0, 1)[None]

    @nn.compact
    def __call__(self, hidden, kv_hidden=None, mask=None, position_bias=None,
                 deterministic: bool = True, decode: bool = False):
        """Returns (output, position_bias). ``mask`` is additive,
        broadcastable to [batch, heads, q_len, kv_len]."""
        cfg = self.config
        inner = cfg.num_heads * cfg.d_kv
        source = hidden if kv_hidden is None else kv_hidden

        def split(x):
            b, s, _ = x.shape
            return x.reshape(b, s, cfg.num_heads, cfg.d_kv).transpose(0, 2, 1, 3)

        q = split(self._dense(inner, "query")(hidden))
        k = split(self._dense(inner, "key")(source))
        v = split(self._dense(inner, "value")(source))

        cache_offset = None
        if decode and kv_hidden is None:
            # Incremental self-attention cache: full-length zero buffers are
            # created on the init pass; each decode step writes its k/v slice
            # at cache_index and attends to positions <= its own. Write
            # indices are PER-ROW [B] (the shared decoder-family protocol,
            # models/llama.py::write_kv_cache): rows may sit at different
            # depths under speculative decode.
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
                write_kv_cache,
            )

            B = q.shape[0]
            is_init = self.has_variable("cache", "cached_key")
            cached_k = self.variable("cache", "cached_key", jnp.zeros, k.shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, v.shape, v.dtype)
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.zeros((B,), jnp.int32))
            if is_init:
                cur = cache_index.value                       # [B]
                max_len = cached_k.value.shape[2]
                q_len = q.shape[2]
                k, v = write_kv_cache(cached_k, cached_v, None, k, v, cur,
                                      k.dtype)
                cache_index.value = cur + q_len
                valid = jnp.arange(max_len)[None, None, :] <= (
                    cur[:, None, None] + jnp.arange(q_len)[None, :, None])
                step_mask = jnp.where(valid, 0.0, NEG_INF)[:, None]
                mask = step_mask if mask is None else mask + step_mask
                cache_offset = cur

        # ring mode (sequence parallelism, VERDICT r1 weak #7): the first
        # block threads the RAW [num_buckets, heads] bias table (ndim 2)
        # instead of a materialized [1, h, q, k] bias, and the encoder
        # self-attention recomputes per-step bias tiles inside the ring —
        # the full [S, S] bias never exists. Decoder/cross/decode paths
        # (short target sequences, KV cache) materialize from the same
        # table and run XLA attention, numerics-identical.
        ring = cfg.attention_impl == "ring"
        if ring and position_bias is None and self.has_rel_bias:
            position_bias = self._rel_bias_embed()(
                jnp.arange(cfg.relative_attention_num_buckets))

        if ring and kv_hidden is None and not decode and not self.causal:
            # encoder self-attention: padding mask rides the ring, the
            # bias table is re-tiled per step from global positions
            rel_spec = (True, cfg.relative_attention_num_buckets,
                        cfg.relative_attention_max_distance)
            ctx = ring_attention_or_fallback(
                q, k, v, mask=mask, scale=1.0,
                rel_bias_table=position_bias,
                rel_bias_spec=rel_spec if position_bias is not None else None)
        else:
            if ring and position_bias is not None and position_bias.ndim == 2:
                # decoder self-attention block 0: densify the table ONCE
                # and thread the dense bias, exactly like xla mode (later
                # blocks and the per-decode-step offset reuse it as-is)
                ctx_pos = jnp.arange(q.shape[2])[:, None]
                if cache_offset is not None:
                    # per-row offsets don't reach this branch (ring decode
                    # advances uniformly); collapse [B] to its max — all
                    # equal on this path
                    ctx_pos = ctx_pos + jnp.max(cache_offset)
                position_bias = relative_position_bias(
                    position_bias, ctx_pos, jnp.arange(k.shape[2])[None, :],
                    bidirectional=not self.causal,
                    num_buckets=cfg.relative_attention_num_buckets,
                    max_distance=cfg.relative_attention_max_distance)
            if position_bias is None:
                if self.has_rel_bias and not ring:
                    position_bias = self._position_bias(
                        q.shape[2], k.shape[2], offset=cache_offset)
                else:
                    position_bias = jnp.zeros(
                        (1, cfg.num_heads, q.shape[2], k.shape[2]),
                        jnp.float32)
            bias = position_bias if mask is None else position_bias + mask
            ctx = xla_attention(q, k, v, mask=bias, scale=1.0)  # no sqrt(d)

        b, h, s, d = ctx.shape
        out = self._dense(cfg.d_model, "attention_out")(
            ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d))
        return out, position_bias


class T5FeedForward(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        std_in = cfg.initializer_factor * cfg.d_model ** -0.5
        std_out = cfg.initializer_factor * cfg.d_ff ** -0.5

        def dense(features, std, name):
            return _t5_dense(cfg, features, std, name)

        if cfg.is_gated_act:
            gate = cfg.act_fn(dense(cfg.d_ff, std_in, "wi_0")(x))
            x = gate * dense(cfg.d_ff, std_in, "wi_1")(x)
        else:
            x = cfg.act_fn(dense(cfg.d_ff, std_in, "wi")(x))
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=deterministic)
        return dense(cfg.d_model, std_out, "wo")(x)


class T5Block(nn.Module):
    """Pre-LN residual block: self-attn (+ cross-attn in decoder) + FFN."""

    config: T5Config
    is_decoder: bool = False
    has_rel_bias: bool = False

    @nn.compact
    def __call__(self, hidden, attn_mask=None, enc_hidden=None, enc_mask=None,
                 position_bias=None, deterministic: bool = True,
                 decode: bool = False):
        cfg = self.config
        drop = nn.Dropout(cfg.dropout_rate)

        x = RMSNorm(cfg, name="attn_ln")(hidden)
        attn, position_bias = T5Attention(
            cfg, causal=self.is_decoder, has_rel_bias=self.has_rel_bias,
            name="self_attn")(x, mask=attn_mask, position_bias=position_bias,
                              deterministic=deterministic, decode=decode)
        hidden = hidden + drop(attn, deterministic=deterministic)

        if self.is_decoder:
            x = RMSNorm(cfg, name="cross_ln")(hidden)
            cross, _ = T5Attention(cfg, causal=False, has_rel_bias=False,
                                   name="cross_attn")(
                x, kv_hidden=enc_hidden, mask=enc_mask,
                deterministic=deterministic)
            hidden = hidden + drop(cross, deterministic=deterministic)

        x = RMSNorm(cfg, name="ffn_ln")(hidden)
        ff = T5FeedForward(cfg, name="ffn")(x, deterministic)
        hidden = hidden + drop(ff, deterministic=deterministic)
        return hidden, position_bias


class T5Stack(nn.Module):
    """Encoder or decoder stack over embedded inputs.

    The relative-position bias is computed by block 0 and threaded through
    the remaining blocks (HF parity: ``has_relative_attention_bias`` only
    on the first block of each stack).
    """

    config: T5Config
    is_decoder: bool = False

    @nn.compact
    def __call__(self, embeds, attn_mask=None, enc_hidden=None, enc_mask=None,
                 deterministic: bool = True, decode: bool = False):
        cfg = self.config
        hidden = nn.Dropout(cfg.dropout_rate)(embeds, deterministic=deterministic)
        n_layers = cfg.num_decoder_layers if self.is_decoder else cfg.num_layers
        block_cls = T5Block
        if cfg.remat:
            # bound module is arg 0: deterministic=6, decode=7
            block_cls = nn.remat(T5Block, static_argnums=(6, 7),
                                 policy=remat_policy(cfg.remat_policy))
        position_bias = None
        for i in range(n_layers):
            hidden, position_bias = block_cls(
                cfg, is_decoder=self.is_decoder, has_rel_bias=(i == 0),
                name=f"block_{i}")(
                hidden, attn_mask, enc_hidden, enc_mask, position_bias,
                deterministic, decode)
        hidden = RMSNorm(cfg, name="final_ln")(hidden)
        return nn.Dropout(cfg.dropout_rate)(hidden, deterministic=deterministic)


def _padding_mask(attention_mask, dtype=jnp.float32):
    """{0,1} [batch, kv_len] → additive [batch, 1, 1, kv_len]."""
    m = attention_mask[:, None, None, :].astype(dtype)
    return (1.0 - m) * NEG_INF


class T5ForConditionalGeneration(nn.Module):
    """Encoder-decoder LM: the seq2seq task head (summarization,
    translation — the reference's capability surface via HF TF T5).

    ``encode`` / ``decode`` are exposed as separate apply methods so
    generation runs the encoder once and the decoder incrementally with a
    KV cache (``models/generate.py``).
    """

    config: T5Config

    is_encoder_decoder = True

    def setup(self):
        cfg = self.config
        self.shared = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(cfg.initializer_factor),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="shared")
        if cfg.pipeline_stages:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                PipelinedT5Stack,
            )
            self.encoder = PipelinedT5Stack(cfg, is_decoder=False,
                                            name="encoder")
            self.decoder = PipelinedT5Stack(cfg, is_decoder=True,
                                            name="decoder")
        else:
            self.encoder = T5Stack(cfg, is_decoder=False, name="encoder")
            self.decoder = T5Stack(cfg, is_decoder=True, name="decoder")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(cfg.initializer_factor),
                name="lm_head")

    def encode(self, input_ids, attention_mask=None, deterministic: bool = True):
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        return self.encoder(self.shared(input_ids),
                            attn_mask=_padding_mask(attention_mask),
                            deterministic=deterministic)

    def _lm_logits(self, hidden):
        cfg = self.config
        if cfg.tie_word_embeddings:
            hidden = hidden * (cfg.d_model ** -0.5)
            return self.shared.attend(hidden.astype(cfg.dtype))
        return self.lm_head(hidden)

    def decode(self, decoder_input_ids, encoder_hidden, encoder_attention_mask=None,
               decoder_attention_mask=None, deterministic: bool = True,
               decode: bool = False):
        """Decoder forward → vocab logits. ``decode=True`` uses/updates the
        incremental cache (mask built from the cache index internally)."""
        if decode:
            self_mask = None  # cache supplies causal masking
        else:
            self_mask = self._teacher_forcing_mask(decoder_input_ids,
                                                   decoder_attention_mask)
        enc_mask = None
        if encoder_attention_mask is not None:
            enc_mask = _padding_mask(encoder_attention_mask)
        hidden = self.decoder(self.shared(decoder_input_ids),
                              attn_mask=self_mask, enc_hidden=encoder_hidden,
                              enc_mask=enc_mask, deterministic=deterministic,
                              decode=decode)
        return self._lm_logits(hidden)

    def __call__(self, input_ids, attention_mask=None, decoder_input_ids=None,
                 decoder_attention_mask=None, deterministic: bool = True):
        enc = self.encode(input_ids, attention_mask, deterministic)
        return self.decode(decoder_input_ids, enc, attention_mask,
                           decoder_attention_mask, deterministic)

    def seq2seq_hidden_and_embedding(self, input_ids, attention_mask=None,
                                     decoder_input_ids=None,
                                     decoder_attention_mask=None,
                                     deterministic: bool = True):
        """(pre-head decoder hidden [B, T, H] with the tied-head scaling
        already applied, LM weight [V, H]) — the fused vocab-CE path
        (``train/trainer.py::make_fused_seq2seq_loss``): ``hidden·Wᵀ``
        equals ``__call__``'s logits, but [B, T, V] never materializes."""
        cfg = self.config
        enc = self.encode(input_ids, attention_mask, deterministic)
        hidden = self.decoder(
            self.shared(decoder_input_ids),
            attn_mask=self._teacher_forcing_mask(decoder_input_ids,
                                                 decoder_attention_mask),
            enc_hidden=enc,
            enc_mask=_padding_mask(attention_mask)
            if attention_mask is not None else None,
            deterministic=deterministic)
        if cfg.tie_word_embeddings:
            return hidden * (cfg.d_model ** -0.5), self.shared.embedding
        return hidden, self.lm_head.variables["params"]["kernel"].T

    def _teacher_forcing_mask(self, decoder_input_ids,
                              decoder_attention_mask):
        dec_len = decoder_input_ids.shape[1]
        i = jnp.arange(dec_len)[:, None]
        j = jnp.arange(dec_len)[None, :]
        causal = jnp.where(j <= i, 0.0, NEG_INF)[None, None]
        if decoder_attention_mask is not None:
            return causal + _padding_mask(decoder_attention_mask)
        return causal


def shift_right(labels, decoder_start_token_id: int, pad_token_id: int = 0,
                ignore_id: int = -100):
    """Teacher-forcing inputs: [start, y_0, ..., y_{T-2}] with ignore-index
    labels mapped back to pad (HF ``_shift_right`` parity)."""
    labels = jnp.where(labels == ignore_id, pad_token_id, labels)
    start = jnp.full(labels.shape[:-1] + (1,), decoder_start_token_id,
                     labels.dtype)
    return jnp.concatenate([start, labels[..., :-1]], axis=-1)
