"""LoRA (low-rank adaptation) for parameter-efficient fine-tuning.

Beyond-parity capability: the reference fine-tunes every weight of the
model (``/root/reference/scripts/train.py:117`` — full Adam state for
all of BERT-large), which on a 16G TPU chip means the optimizer mirrors
dominate HBM. LoRA freezes the base model and trains rank-``r`` factors
``A·B`` added onto targeted kernels — Adam m/v exist only for the
adapters (<1% of params), freeing the HBM that fp32 optimizer state
would have pinned and shrinking checkpoints to megabytes.

TPU-first design: the merge ``W_eff = W + (alpha/r)·A·B`` happens
*inside* the jitted train step as a handful of tiny matmuls that XLA
fuses ahead of the big forward matmuls — there is no Python-side weight
surgery, no module rewriting, and the base params stay donated device
buffers. Gradients flow through ``W_eff`` to A/B only (the base tree is
``stop_gradient``-ed), so XLA dead-code-eliminates the full-size grad
tree entirely.

Works on 2-D kernels (``.../kernel``) and on layer-stacked 3-D kernels
(``pipelined_*/..._kernel`` — [L, in, out]); adapter factors for the
stacked form are themselves stacked and inherit the stage sharding via
the ``pipelined_*`` path rules. MoE expert banks (``moe/wi|wo``) are
deliberately not targeted — expert weights are already the sparse path.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax.traverse_util import flatten_dict, unflatten_dict

# preset -> kernel-leaf regex (searched against the "/"-joined param
# path). Both naming schemes appear in the zoo: per-layer modules end in
# ".../<name>/kernel", pipelined stacked params in ".../<name>_kernel".
TARGET_PRESETS = {
    "attention": r"(query|key|value|qkv|attention_out|attn_out"
                 r"|q_proj|k_proj|v_proj|o_proj)(/kernel|_kernel)$",
    "mlp": r"(intermediate|ffn_out|fc_in|fc_out|wi|wi_0|wi_1|wo|fc1|fc2"
           r"|gate_proj|up_proj|down_proj)(/kernel|_kernel)$",
    "all": r"(/kernel|_kernel)$",
}


# task heads are fresh-initialized on fine-tunes (reference semantics:
# from_pretrained attaches a new classification head, train.py:117) —
# freezing them would leave the model unable to learn the task, so they
# stay fully trainable by default (PEFT's ``modules_to_save`` analogue).
# The value lives in config.py (the TrainConfig field default must not
# drag model imports into config); re-exported here under the name the
# adapter code and tests use.
from huggingface_sagemaker_tensorflow_distributed_tpu.config import (
    LORA_HEAD_REGEX_DEFAULT as HEAD_REGEX_DEFAULT,
)


def target_regex(targets: str) -> str:
    """Resolve a preset name or pass a custom regex through."""
    return TARGET_PRESETS.get(targets, targets)


def freeze_except(params: Any, head_regex: str) -> Any:
    """``stop_gradient`` every leaf whose path does NOT match
    ``head_regex`` (empty regex → freeze everything). Used inside the
    jitted loss so task heads keep real gradients while the backbone's
    grad tree is dead code to XLA."""
    if not head_regex:
        return jax.lax.stop_gradient(params)
    rx = re.compile(head_regex)

    def one(path, leaf):
        path_s = "/".join(str(getattr(p, "key", p)) for p in path)
        return leaf if rx.search(path_s) else jax.lax.stop_gradient(leaf)

    return jax.tree_util.tree_map_with_path(one, params)


def trainable_labels(params: Any, head_regex: str,
                     train: str = "train", freeze: str = "freeze") -> Any:
    """Label tree for ``optax.multi_transform``: heads train, the rest
    of the base model is frozen (no optimizer state allocated)."""
    rx = re.compile(head_regex) if head_regex else None

    def one(path, _):
        path_s = "/".join(str(getattr(p, "key", p)) for p in path)
        return train if rx is not None and rx.search(path_s) else freeze

    return jax.tree_util.tree_map_with_path(one, params)


def lora_scaling(rank: int, alpha: float) -> float:
    return alpha / rank


def _targeted_paths(params: Any, pattern: str) -> list[tuple]:
    flat = flatten_dict(params)
    rx = re.compile(pattern)
    out = []
    for path, leaf in flat.items():
        if not hasattr(leaf, "shape") or leaf.ndim not in (2, 3):
            continue
        if rx.search("/".join(str(p) for p in path)):
            out.append(path)
    return out


def init_lora_params(params: Any, rank: int, targets: str = "attention",
                     seed: int = 0) -> Any:
    """Adapter tree mirroring the targeted kernels: each matched
    ``.../kernel`` leaf becomes ``.../kernel/{a, b}`` with
    A ~ N(0, 1/sqrt(in)) [in, r] and B = 0 [r, out] (delta starts at
    exactly zero, so step 0 reproduces the base model bit-for-bit).
    Stacked 3-D kernels [L, in, out] get stacked factors."""
    paths = _targeted_paths(params, target_regex(targets))
    if not paths:
        raise ValueError(
            f"lora target {targets!r} matched no kernels in the param tree")
    flat = flatten_dict(params)
    key = jax.random.PRNGKey(seed)
    lora = {}
    for i, path in enumerate(paths):
        w = flat[path]
        sub = jax.random.fold_in(key, i)
        if w.ndim == 2:
            fan_in, fan_out = w.shape
            a = jax.random.normal(sub, (fan_in, rank),
                                  jnp.float32) / np.sqrt(fan_in)
            b = jnp.zeros((rank, fan_out), jnp.float32)
        else:  # [L, in, out] stacked
            layers, fan_in, fan_out = w.shape
            a = jax.random.normal(sub, (layers, fan_in, rank),
                                  jnp.float32) / np.sqrt(fan_in)
            b = jnp.zeros((layers, rank, fan_out), jnp.float32)
        lora[path + ("a",)] = a
        lora[path + ("b",)] = b
    return unflatten_dict(lora)


def merge_lora(params: Any, lora: Any, scaling: float) -> Any:
    """``W_eff = W + scaling * A @ B`` on every adapted kernel. Pure
    function of jax arrays — safe inside jit; everything else is
    passed through untouched (same tree structure as ``params``)."""
    flat_p = dict(flatten_dict(params))
    flat_l = flatten_dict(lora)
    for path in sorted({p[:-1] for p in flat_l}):
        a, b = flat_l[path + ("a",)], flat_l[path + ("b",)]
        w = flat_p[path]
        if a.ndim == 2:
            delta = a @ b
        else:
            delta = jnp.einsum("lir,lro->lio", a, b)
        flat_p[path] = (w + scaling * delta.astype(w.dtype)).astype(w.dtype)
    return unflatten_dict(flat_p)


def count_params(tree: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree)
               if hasattr(x, "shape"))


def save_adapters(path: str, lora: Any, *, rank: int, alpha: float,
                  targets: str) -> None:
    """Adapter-only artifact: ``adapter.safetensors`` (flat "/"-joined
    names) + ``adapter_config.json``. A few MB instead of the full
    model — the deployment story is either this sidecar or the merged
    export ``models/auto.py::save_pretrained`` writes."""
    from safetensors.numpy import save_file

    flat = {"/".join(map(str, k)): np.asarray(jax.device_get(v))
            for k, v in flatten_dict(lora).items()}
    os.makedirs(path, exist_ok=True)
    save_file(flat, os.path.join(path, "adapter.safetensors"))
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump({"lora_rank": rank, "lora_alpha": alpha,
                   "lora_targets": targets}, f, indent=2)


def load_adapters(path: str) -> tuple[Any, dict]:
    """Inverse of :func:`save_adapters` → (lora tree, config dict)."""
    from safetensors.numpy import load_file

    flat = load_file(os.path.join(path, "adapter.safetensors"))
    with open(os.path.join(path, "adapter_config.json")) as f:
        cfg = json.load(f)
    tree = unflatten_dict(
        {tuple(k.split("/")): jnp.asarray(v) for k, v in flat.items()})
    return tree, cfg
