"""GPT-2: decoder-only causal language model.

Extends the model zoo beyond the reference's BERT-family surface
(reference ``scripts/train.py:117`` loads any
``TFAutoModelForSequenceClassification``; the HF ecosystem the reference
rides also ships decoder-only LMs — this is the TPU-native equivalent,
SURVEY.md D7). Architecture: HF ``GPT2LMHeadModel`` parity —

- learned token (wte) + position (wpe) embeddings, embedding dropout;
- pre-LN blocks: ``x + attn(ln_1(x))`` then ``x + mlp(ln_2(x))``;
- fused qkv projection (HF ``c_attn``; kept fused so the checkpoint
  converts 1:1 — HF Conv1D stores [in, out], NO transpose on load);
- gelu_new MLP, final ``ln_f``, LM head tied to wte.

Causal masking runs through ``ops.attention.dot_product_attention``
(causal=True), so training uses the Pallas flash kernel's
diagonal-tile-skipping path on TPU. Decode uses the same incremental KV
cache pattern as T5 (``"cache"`` collection, ``dynamic_update_slice``),
driving ``models/generate.py::generate_causal``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    ACT2FN,
    is_moe_layer,
    remat_policy,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    dot_product_attention,
    make_attention_mask,
)

NEG_INF = -1e9

_flash_dropout_warned = False


def _warn_flash_dropout_fallback():
    """One-time trace-time warning: attention_impl='flash' with
    attn_pdrop > 0 in training falls back to the unfused O(S²) softmax
    (the flash kernel has no probability-dropout hook)."""
    global _flash_dropout_warned
    if not _flash_dropout_warned:
        _flash_dropout_warned = True
        import logging
        logging.getLogger(__name__).warning(
            "gpt2: attention_impl='flash' requested but attention_dropout "
            "> 0 in training has no flash hook — using the unfused O(S^2) "
            "softmax for this step. Set attention_dropout=0.0 to keep the "
            "flash kernel (HF fine-tunes commonly do).")


@dataclass(frozen=True)
class Gpt2Config:
    vocab_size: int = 50257
    max_position_embeddings: int = 1024   # HF n_positions
    hidden_size: int = 768                # n_embd
    num_layers: int = 12                  # n_layer
    num_heads: int = 12                   # n_head
    intermediate_size: int = 3072         # n_inner (4*n_embd default)
    hidden_act: str = "gelu_new"
    layer_norm_eps: float = 1e-5
    hidden_dropout: float = 0.1           # resid_pdrop
    embd_dropout: float = 0.1             # embd_pdrop
    attention_dropout: float = 0.1        # attn_pdrop
    initializer_range: float = 0.02
    bos_token_id: int = 50256
    eos_token_id: int = 50256
    pad_token_id: int = 50256             # GPT-2 has no pad; HF uses eos
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"
    remat: bool = False
    remat_policy: str = "full"            # full | dots | dots_no_batch
    # Mixture-of-Experts (models/moe.py, shared with the encoder
    # families): every moe_every-th block's MLP becomes a token-routed
    # expert bank (Mixtral-style decoder MoE). 0 = dense everywhere.
    num_experts: int = 0
    expert_top_k: int = 2
    moe_every: int = 2
    expert_capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # GPipe pipeline parallelism over the block stack (models/pipeline.py;
    # training/scoring path only — decode keeps the dense stack)
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    # int8 weight-only dense kernels for generation (models/quant.py;
    # load via quantize_gpt2 — never trained in this form)
    weight_quant: str = "none"            # none | int8
    # Decode KV cache storage (same contract as LlamaConfig): "int8"
    # stores symmetric per-(head, slot) int8 + fp32 scales — halves the
    # cache bytes read per decode step vs bf16
    kv_cache_dtype: str = "fp"            # fp | int8

    def __post_init__(self):
        if self.kv_cache_dtype not in ("fp", "int8"):
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r} "
                "(fp | int8)")


def gpt2_config_from_hf(hf_config: dict, **overrides) -> Gpt2Config:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        max_position_embeddings=hf_config.get("n_positions", 1024),
        hidden_size=hf_config["n_embd"],
        num_layers=hf_config["n_layer"],
        num_heads=hf_config["n_head"],
        intermediate_size=hf_config.get("n_inner") or 4 * hf_config["n_embd"],
        hidden_act=hf_config.get("activation_function", "gelu_new"),
        layer_norm_eps=hf_config.get("layer_norm_epsilon", 1e-5),
        hidden_dropout=hf_config.get("resid_pdrop", 0.1),
        embd_dropout=hf_config.get("embd_pdrop", 0.1),
        attention_dropout=hf_config.get("attn_pdrop", 0.1),
        initializer_range=hf_config.get("initializer_range", 0.02),
        bos_token_id=hf_config.get("bos_token_id", 50256),
        eos_token_id=hf_config.get("eos_token_id", 50256),
        # explicit pad id 0 is valid — only None falls back to EOS
        pad_token_id=(hf_config["pad_token_id"]
                      if hf_config.get("pad_token_id") is not None
                      else hf_config.get("eos_token_id", 50256)),
    )
    kw.update(overrides)
    # pooler is an encoder-family knob; MoE IS supported (decoder MoE)
    kw.pop("use_pooler", None)
    return Gpt2Config(**kw)


def _dense(cfg: Gpt2Config, features: int, name: str,
           std: Optional[float] = None) -> nn.Module:
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
        make_dense,
    )
    return make_dense(
        cfg, features,
        nn.initializers.normal(std or cfg.initializer_range), name=name)


def _layernorm(cfg: Gpt2Config, name: str) -> nn.LayerNorm:
    return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name=name)


class Gpt2Attention(nn.Module):
    """Fused-qkv causal self-attention with optional incremental cache."""

    config: Gpt2Config

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True,
                 decode: bool = False):
        cfg = self.config
        H = cfg.hidden_size
        head_dim = H // cfg.num_heads

        qkv = _dense(cfg, 3 * H, "qkv")(hidden)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def split_heads(x):
            b, s, _ = x.shape
            return x.reshape(b, s, cfg.num_heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = split_heads(q), split_heads(k), split_heads(v)

        causal = True
        if decode:
            B = q.shape[0]
            int8_kv = cfg.kv_cache_dtype == "int8"
            kv_store = jnp.int8 if int8_kv else k.dtype
            is_init = self.has_variable("cache", "cached_key")
            cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                     k.shape, kv_store)
            cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                     v.shape, kv_store)
            if int8_kv:
                scale_shape = k.shape[:3] + (1,)
                k_scale = self.variable("cache", "cached_key_scale",
                                        jnp.zeros, scale_shape, jnp.float32)
                v_scale = self.variable("cache", "cached_value_scale",
                                        jnp.zeros, scale_shape, jnp.float32)
            # per-row write indices [B] — rows may sit at different
            # depths under speculative decode (models/generate.py)
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.zeros((B,), jnp.int32))
            if self.has_variable("cache", "block_tables"):
                # serve paged-pool decode: the cache vars hold BLOCK
                # POOLS and a per-row block table (the engine's fused
                # kernel path) — scatter the new K/V, then fused paged
                # attention walks the tables directly (masking derives
                # from the context lengths, not attn_mask)
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
                    write_paged_kv,
                )
                from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
                    paged_attention,
                )

                if q.shape[2] != 1:
                    raise ValueError(
                        "paged decode is single-token (the fused kernel "
                        f"takes one query per slot, got q_len {q.shape[2]})")
                tables = self.get_variable("cache", "block_tables")
                cur = cache_index.value                   # [B]
                write_paged_kv(cached_k, cached_v,
                               (k_scale, v_scale) if int8_kv else None,
                               tables, k, v, cur)
                cache_index.value = cur + 1
                ctx = paged_attention(
                    q[:, :, 0, :], cached_k.value, cached_v.value,
                    tables, cur + 1, impl="pallas",
                    k_scale_pool=k_scale.value if int8_kv else None,
                    v_scale_pool=v_scale.value if int8_kv else None)
                ctx = ctx.astype(hidden.dtype).reshape(B, 1, H)
                out = _dense(cfg, H, "attn_out",
                             std=cfg.initializer_range
                             / (2 * cfg.num_layers) ** 0.5)(ctx)
                return nn.Dropout(cfg.hidden_dropout)(
                    out, deterministic=deterministic)
            if is_init:
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
                    write_kv_cache,
                )

                cur = cache_index.value                       # [B]
                max_len = cached_k.value.shape[2]
                q_len = q.shape[2]
                k, v = write_kv_cache(
                    cached_k, cached_v,
                    (k_scale, v_scale) if int8_kv else None, k, v, cur,
                    cfg.dtype)
                cache_index.value = cur + q_len
                valid = jnp.arange(max_len)[None, None, :] <= (
                    cur[:, None, None] + jnp.arange(q_len)[None, :, None])
                step_mask = jnp.where(valid, 0.0, NEG_INF)[:, None]
                attn_mask = step_mask if attn_mask is None else attn_mask + step_mask
                causal = False   # the step mask already encodes causality

        if cfg.attention_dropout > 0 and not deterministic:
            if cfg.attention_impl == "ring":
                # the unfused fallback below attends over the LOCAL
                # sequence shard only — under sp>1 that is shard-local
                # garbage (config.py sp notes), and ring attention has
                # no probability-dropout hook
                raise ValueError(
                    "attention_dropout > 0 cannot combine with "
                    "attention_impl='ring' (sequence parallelism): set "
                    "attention_dropout=0.0 for sp training")
            if cfg.attention_impl == "flash":
                _warn_flash_dropout_fallback()
            # HF applies dropout to the attention probabilities during
            # training (attn_pdrop); the fused attention paths have no
            # hook for it, so mirror BartAttention's unfused softmax
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(
                jnp.float32) * head_dim ** -0.5
            if attn_mask is not None:
                logits = logits + attn_mask.astype(jnp.float32)
            if causal:
                sq, sk = logits.shape[-2], logits.shape[-1]
                keep = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
                logits = jnp.where(keep, logits, NEG_INF)
            probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
            probs = nn.Dropout(cfg.attention_dropout)(probs,
                                                      deterministic=False)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        else:
            ctx = dot_product_attention(q, k, v, mask=attn_mask,
                                        impl=cfg.attention_impl, causal=causal)
        b, h, s, d = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        # HF init: c_proj scaled by 1/sqrt(2*n_layer) (residual-flow init)
        out = _dense(cfg, H, "attn_out",
                     std=cfg.initializer_range / (2 * cfg.num_layers) ** 0.5)(ctx)
        out = nn.Dropout(cfg.hidden_dropout)(out, deterministic=deterministic)
        return out


class Gpt2Mlp(nn.Module):
    config: Gpt2Config

    @nn.compact
    def __call__(self, hidden, deterministic: bool = True):
        cfg = self.config
        x = _dense(cfg, cfg.intermediate_size, "fc_in")(hidden)
        x = ACT2FN[cfg.hidden_act](x)
        x = _dense(cfg, cfg.hidden_size, "fc_out",
                   std=cfg.initializer_range / (2 * cfg.num_layers) ** 0.5)(x)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return x


class Gpt2Block(nn.Module):
    """Pre-LN transformer block (GPT-2 ordering). On MoE placements
    (``is_moe_layer``) the MLP is the shared token-routed expert bank
    (``models/moe.py::MoeFeedForward`` — duck-typed on the config's
    num_experts/intermediate_size/hidden_act fields)."""

    config: Gpt2Config
    layer_index: int = 0

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True,
                 decode: bool = False):
        cfg = self.config
        attn = Gpt2Attention(cfg, name="attention")(
            _layernorm(cfg, "ln_1")(hidden), attn_mask, deterministic, decode)
        hidden = hidden + attn
        x = _layernorm(cfg, "ln_2")(hidden)
        if is_moe_layer(cfg, self.layer_index):
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.moe import (
                MoeFeedForward,
            )
            # causal slot priority (no future-token influence on drops);
            # wo follows the 1/sqrt(2*n_layer) residual-flow init like
            # every other residual write in the model
            mlp = MoeFeedForward(
                cfg, causal=True,
                out_init_std=cfg.initializer_range / (2 * cfg.num_layers) ** 0.5,
                name="moe")(x, deterministic)
        else:
            mlp = Gpt2Mlp(cfg, name="mlp")(x, deterministic)
        return hidden + mlp


class Gpt2Model(nn.Module):
    """Backbone: embeddings + blocks + final LN. Returns (hidden, wte)
    so the LM head can tie logits to the token embedding."""

    config: Gpt2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 deterministic: bool = True, decode: bool = False,
                 segment_ids=None):
        cfg = self.config
        B, S = input_ids.shape

        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                       embedding_init=nn.initializers.normal(cfg.initializer_range),
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wte")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                       embedding_init=nn.initializers.normal(cfg.initializer_range),
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="wpe")

        if position_ids is None:
            offset = 0
            if decode:
                # physical write position tracked alongside the KV caches
                is_init = self.has_variable("cache", "position_index")
                idx = self.variable("cache", "position_index",
                                    lambda: jnp.array(0, jnp.int32))
                if is_init:
                    offset = idx.value
                    idx.value = offset + S
            position_ids = offset + jnp.arange(S)[None, :]

        # training/prefill: [B, S] padding mask; decode: kv-buffer
        # validity [B, max_len] — both become the additive form.
        # segment_ids (token-packed pretraining batches): block-diagonal
        # instead, so packed documents never attend across boundaries
        additive_mask = (
            make_attention_mask(attention_mask, segment_ids=segment_ids)
            if attention_mask is not None or segment_ids is not None
            else None)

        x = wte(input_ids) + wpe(position_ids)
        x = nn.Dropout(cfg.embd_dropout)(x, deterministic=deterministic)

        if cfg.pipeline_stages:
            if decode:
                raise ValueError(
                    "pipeline_stages and incremental decode cannot combine: "
                    "the KV cache is stage-local state the dense stack owns; "
                    "export the pipelined checkpoint and reload it dense "
                    "(pipeline_stages=0) for generation")
            if cfg.num_experts:
                raise ValueError("pipeline_stages and num_experts cannot "
                                 "combine (pipelined MoE is not supported)")
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                PipelinedGpt2Stack,
            )
            x = PipelinedGpt2Stack(cfg, name="pipelined_h")(
                x, additive_mask, deterministic)
        else:
            block_cls = Gpt2Block
            if cfg.remat:
                block_cls = nn.remat(Gpt2Block, static_argnums=(3, 4),
                                     policy=remat_policy(cfg.remat_policy))
            for i in range(cfg.num_layers):
                x = block_cls(cfg, name=f"h_{i}", layer_index=i)(
                    x, additive_mask, deterministic, decode)
        x = _layernorm(cfg, "ln_f")(x)
        return x, wte.embedding


class Gpt2LMHeadModel(nn.Module):
    """GPT-2 with the tied LM head (HF ``GPT2LMHeadModel`` parity).

    ``hidden_and_embedding`` exposes the pre-head activations so the
    fused vocab-CE loss (``ops/pallas_vocab_ce.py``) can skip the
    [B, S, V] logits materialisation entirely."""

    config: Gpt2Config

    def setup(self):
        self.backbone = Gpt2Model(self.config)

    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic: bool = True,
                 decode: bool = False, segment_ids=None):
        # token_type_ids accepted for trainer-signature parity; GPT-2 has
        # no segment embeddings. segment_ids/position_ids: token-packed
        # batches (data.pipeline.pack_examples)
        hidden, embedding = self.backbone(
            input_ids, attention_mask, position_ids, deterministic, decode,
            segment_ids=segment_ids)
        logits = jnp.einsum("bsh,vh->bsv", hidden,
                            embedding.astype(self.config.dtype))
        return logits.astype(jnp.float32)

    def hidden_and_embedding(self, input_ids, attention_mask=None,
                             token_type_ids=None, position_ids=None,
                             deterministic: bool = True, segment_ids=None):
        """(hidden [B, S, H], tied embedding [V, H]) — the fused-CE path."""
        return self.backbone(input_ids, attention_mask, position_ids,
                             deterministic, False, segment_ids=segment_ids)
