"""Pipeline parallelism: a GPipe-scheduled encoder over the ``pipe``
mesh axis.

Beyond-parity capability (the reference has no pipeline parallelism,
SURVEY.md §2 parallelism inventory), designed as *dense SPMD* rather
than per-stage programs: the encoder's layers live in ONE layer-stacked
param tree (leading dim = num_layers, sharded over ``pipe``), and the
GPipe schedule is expressed as compiler-friendly array code —

    lax.scan over ticks
      └─ vmap over stages (each applies its layers_per_stage layers)
      └─ jnp.roll along the stage dim (stage s → stage s+1 handoff)

Under ``jit`` with the stage dim sharded over ``pipe``, XLA lowers the
roll to a collective-permute along the pipe axis and the vmap body runs
concurrently on every stage — the classic SPMD pipelining formulation
(MaxText/praxis lineage), with no hand-written send/recv and no
per-stage program divergence. Single-device meshes execute the same
schedule (bit-identical math, just no overlap), so pipelined models run
everywhere the dense ones do.

Schedule shape: M microbatches over S stages take M + S - 1 ticks; the
fill/drain bubble computes on zero padding and its outputs are dropped.
Backward is plain autodiff through the scan/roll — the standard GPipe
recomputation trade is available via ``EncoderConfig.remat``.

Conversion helpers map between the per-layer tree of the dense
``Encoder`` (``layer_{i}/attention/query/kernel``) and the stacked tree
here (``query_kernel`` with leading layer dim), so HF checkpoints load
into pipelined models and pipelined models export back to HF layout.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
    EncoderLayer,
)

# stacked-name ↔ per-layer-path map: last two path components joined by
# "_" (attention/query/kernel → query_kernel, ffn_ln/scale → ffn_ln_scale)
_LAYER_LEAVES = (
    ("attention", "query", "kernel"), ("attention", "query", "bias"),
    ("attention", "key", "kernel"), ("attention", "key", "bias"),
    ("attention", "value", "kernel"), ("attention", "value", "bias"),
    ("attention", "attention_out", "kernel"), ("attention", "attention_out", "bias"),
    ("attention_ln", "scale",), ("attention_ln", "bias",),
    ("ffn", "intermediate", "kernel"), ("ffn", "intermediate", "bias"),
    ("ffn", "ffn_out", "kernel"), ("ffn", "ffn_out", "bias"),
    ("ffn_ln", "scale",), ("ffn_ln", "bias",),
)


def _stacked_name(path: tuple) -> str:
    return "_".join(path[-2:])


def stack_layer_params(encoder_params: dict, num_layers: int) -> dict:
    """Dense ``Encoder`` params (``layer_{i}/...``) → the stacked flat
    tree ``PipelinedEncoder`` declares (leading dim = num_layers)."""
    out: dict[str, Any] = {}
    for path in _LAYER_LEAVES:
        leaves = []
        for i in range(num_layers):
            node = encoder_params[f"layer_{i}"]
            for key in path:
                node = node[key]
            leaves.append(np.asarray(node))
        out[_stacked_name(path)] = np.stack(leaves, axis=0)
    return out


def unstack_layer_params(stacked: dict, num_layers: int) -> dict:
    """Inverse of :func:`stack_layer_params` (for HF-layout export)."""
    out: dict[str, Any] = {}
    for i in range(num_layers):
        layer: dict[str, Any] = {}
        for path in _LAYER_LEAVES:
            node = layer
            for key in path[:-1]:
                node = node.setdefault(key, {})
            node[path[-1]] = np.asarray(stacked[_stacked_name(path)])[i]
        out[f"layer_{i}"] = layer
    return out


def _layer_tree(flat: dict, index) -> dict:
    """One layer's EncoderLayer-structured params from the stacked tree."""
    tree: dict[str, Any] = {}
    for path in _LAYER_LEAVES:
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = flat[_stacked_name(path)][index]
    return tree


class PipelinedEncoder(nn.Module):
    """Drop-in replacement for ``Encoder`` when
    ``config.pipeline_stages > 0``. Same math, layer-stacked params,
    GPipe schedule (see module docstring)."""

    config: EncoderConfig

    def _declare_stacked(self) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        kernel = nn.initializers.normal(cfg.initializer_range)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        shapes = {
            "query_kernel": ((L, H, H), kernel), "query_bias": ((L, H), zeros),
            "key_kernel": ((L, H, H), kernel), "key_bias": ((L, H), zeros),
            "value_kernel": ((L, H, H), kernel), "value_bias": ((L, H), zeros),
            "attention_out_kernel": ((L, H, H), kernel),
            "attention_out_bias": ((L, H), zeros),
            "attention_ln_scale": ((L, H), ones), "attention_ln_bias": ((L, H), zeros),
            "intermediate_kernel": ((L, H, F), kernel),
            "intermediate_bias": ((L, F), zeros),
            "ffn_out_kernel": ((L, F, H), kernel), "ffn_out_bias": ((L, H), zeros),
            "ffn_ln_scale": ((L, H), ones), "ffn_ln_bias": ((L, H), zeros),
        }
        return {name: self.param(name, init, shape, self.config.param_dtype)
                for name, (shape, init) in shapes.items()}

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
            AXIS_PIPE,
            data_axis_names,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
            constrain_if_mesh,
        )

        cfg = self.config
        pp = cfg.pipeline_stages
        L = cfg.num_layers
        if pp < 1 or L % pp:
            raise ValueError(
                f"pipeline_stages={pp} must be >= 1 and divide num_layers={L}")
        if cfg.num_experts:
            raise ValueError("pipeline_stages and num_experts cannot combine "
                             "(pipelined MoE is not supported)")
        lps = L // pp
        B, S, H = hidden.shape
        # The schedule's outputs are M-invariant (same math, different
        # overlap), so a batch that doesn't divide the requested
        # microbatch count degrades to gcd(B, M) instead of failing —
        # init traces (batch 1) and ragged eval tails stay runnable.
        M = math.gcd(B, cfg.pipeline_microbatches or pp)
        mb = B // M
        batch_axes = data_axis_names()

        flat = self._declare_stacked()
        # [L, ...] → [pp, lps, ...]: stage-major so the stored dim-0
        # sharding over ``pipe`` aligns stages with pipe ranks
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        layer = EncoderLayer(cfg)
        base_key = (None if deterministic
                    else self.make_rng("dropout"))

        def stage_fn(p_stage, x, m, key):
            for i in range(lps):
                p_i = _layer_tree(p_stage, i)
                if deterministic:
                    x = layer.apply({"params": p_i}, x, m, True)
                else:
                    x = layer.apply({"params": p_i}, x, m, False,
                                    rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(stage_fn)

        x_mb = hidden.reshape(M, mb, S, H)
        m_mb = attn_mask.reshape(M, mb, 1, 1, S)
        pad_x = jnp.zeros((pp - 1, mb, S, H), hidden.dtype)
        pad_m = jnp.zeros((pp - 1, mb, 1, 1, S), attn_mask.dtype)
        xs_feed = jnp.concatenate([x_mb, pad_x], axis=0)    # [T, ...]
        ms_feed = jnp.concatenate([m_mb, pad_m], axis=0)

        state_x = jnp.zeros((pp, mb, S, H), hidden.dtype)
        state_m = jnp.zeros((pp, mb, 1, 1, S), attn_mask.dtype)

        def tick(carry, feed):
            sx, sm, t = carry
            in_x, in_m = feed
            # stage 0 ingests the next microbatch; the rolled-in garbage
            # at slot 0 is overwritten
            sx = sx.at[0].set(in_x)
            sm = sm.at[0].set(in_m)
            sx = constrain_if_mesh(sx, AXIS_PIPE, batch_axes)
            if deterministic:
                out = jax.vmap(lambda p, x, m: stage_fn(p, x, m, None))(
                    staged, sx, sm)
            else:
                tick_key = jax.random.fold_in(base_key, t)
                keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(
                    jnp.arange(pp))
                out = jax.vmap(stage_fn)(staged, sx, sm, keys)
            out = constrain_if_mesh(out, AXIS_PIPE, batch_axes)
            y = out[-1]                     # last stage's finished microbatch
            sx = jnp.roll(out, 1, axis=0)   # stage s → stage s+1
            sm = jnp.roll(sm, 1, axis=0)
            return (sx, sm, t + 1), y

        (_, _, _), ys = jax.lax.scan(
            tick, (state_x, state_m, jnp.zeros((), jnp.int32)),
            (xs_feed, ms_feed))
        # first pp-1 tick outputs are fill-bubble garbage
        return ys[pp - 1:].reshape(B, S, H)
