"""Pipeline parallelism: a GPipe-scheduled encoder over the ``pipe``
mesh axis.

Beyond-parity capability (the reference has no pipeline parallelism,
SURVEY.md §2 parallelism inventory), designed as *dense SPMD* rather
than per-stage programs: the encoder's layers live in ONE layer-stacked
param tree (leading dim = num_layers, sharded over ``pipe``), and the
GPipe schedule is expressed as compiler-friendly array code —

    lax.scan over ticks
      └─ vmap over stages (each applies its layers_per_stage layers)
      └─ jnp.roll along the stage dim (stage s → stage s+1 handoff)

Under ``jit`` with the stage dim sharded over ``pipe``, XLA lowers the
roll to a collective-permute along the pipe axis and the vmap body runs
concurrently on every stage — the classic SPMD pipelining formulation
(MaxText/praxis lineage), with no hand-written send/recv and no
per-stage program divergence. Single-device meshes execute the same
schedule (bit-identical math, just no overlap), so pipelined models run
everywhere the dense ones do.

Schedule shape: M microbatches over S stages take M + S - 1 ticks; the
fill/drain bubble computes on zero padding and its outputs are dropped.
Backward is plain autodiff through the scan/roll — the standard GPipe
recomputation trade is available via ``EncoderConfig.remat``.

Conversion helpers map between the per-layer tree of the dense
``Encoder`` (``layer_{i}/attention/query/kernel``) and the stacked tree
here (``query_kernel`` with leading layer dim), so HF checkpoints load
into pipelined models and pipelined models export back to HF layout.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    remat_policy,
    EncoderConfig,
    EncoderLayer,
)

# stacked-name ↔ per-layer-path map: last two path components joined by
# "_" (attention/query/kernel → query_kernel, ffn_ln/scale → ffn_ln_scale)
_LAYER_LEAVES = (
    ("attention", "query", "kernel"), ("attention", "query", "bias"),
    ("attention", "key", "kernel"), ("attention", "key", "bias"),
    ("attention", "value", "kernel"), ("attention", "value", "bias"),
    ("attention", "attention_out", "kernel"), ("attention", "attention_out", "bias"),
    ("attention_ln", "scale",), ("attention_ln", "bias",),
    ("ffn", "intermediate", "kernel"), ("ffn", "intermediate", "bias"),
    ("ffn", "ffn_out", "kernel"), ("ffn", "ffn_out", "bias"),
    ("ffn_ln", "scale",), ("ffn_ln", "bias",),
)

# same contract for the GPT-2 block (``Gpt2Block``: ln_1 → fused-qkv
# attention → ln_2 → mlp), used by ``PipelinedGpt2Stack``
GPT2_LAYER_LEAVES = (
    ("ln_1", "scale"), ("ln_1", "bias"),
    ("attention", "qkv", "kernel"), ("attention", "qkv", "bias"),
    ("attention", "attn_out", "kernel"), ("attention", "attn_out", "bias"),
    ("ln_2", "scale"), ("ln_2", "bias"),
    ("mlp", "fc_in", "kernel"), ("mlp", "fc_in", "bias"),
    ("mlp", "fc_out", "kernel"), ("mlp", "fc_out", "bias"),
)


def _stacked_name(path: tuple) -> str:
    return "_".join(path[-2:])


def llama_layer_leaves(qkv_bias: bool) -> tuple:
    """Per-layer leaf paths of ``LlamaBlock`` (bias-free except Qwen2's
    hardcoded q/k/v biases; RMS scales only — no LN biases)."""
    leaves = [("input_ln", "scale")]
    for proj in ("q_proj", "k_proj", "v_proj"):
        leaves.append(("self_attn", proj, "kernel"))
        if qkv_bias:
            leaves.append(("self_attn", proj, "bias"))
    leaves += [
        ("self_attn", "o_proj", "kernel"),
        ("post_attn_ln", "scale"),
        ("mlp", "gate_proj", "kernel"),
        ("mlp", "up_proj", "kernel"),
        ("mlp", "down_proj", "kernel"),
    ]
    return tuple(leaves)


def full_stacked_name(path: tuple) -> str:
    """T5 needs the FULL path joined: self_attn and cross_attn share
    query/key/value/attention_out leaf names, so the two-component name
    would collide. The ``pipelined_`` prefix keys the sharding rules."""
    return "pipelined_" + "_".join(path)


def t5_layer_leaves(is_decoder: bool, gated: bool) -> tuple:
    """Per-block leaf paths of ``T5Block`` (bias-free by design; RMS
    scales only). Decoder blocks add cross-attention."""
    leaves = [
        ("attn_ln", "scale"),
        ("self_attn", "query", "kernel"), ("self_attn", "key", "kernel"),
        ("self_attn", "value", "kernel"),
        ("self_attn", "attention_out", "kernel"),
    ]
    if is_decoder:
        leaves += [
            ("cross_ln", "scale"),
            ("cross_attn", "query", "kernel"),
            ("cross_attn", "key", "kernel"),
            ("cross_attn", "value", "kernel"),
            ("cross_attn", "attention_out", "kernel"),
        ]
    leaves.append(("ffn_ln", "scale"))
    if gated:
        leaves += [("ffn", "wi_0", "kernel"), ("ffn", "wi_1", "kernel")]
    else:
        leaves.append(("ffn", "wi", "kernel"))
    leaves.append(("ffn", "wo", "kernel"))
    return tuple(leaves)


def bart_layer_leaves(is_decoder: bool) -> tuple:
    """Per-layer leaf paths of ``BartEncoderLayer``/``BartDecoderLayer``
    (biased projections, scale+bias LayerNorms)."""
    def attn(prefix, ln_name):
        return [
            (ln_name, "scale"), (ln_name, "bias"),
            (prefix, "query", "kernel"), (prefix, "query", "bias"),
            (prefix, "key", "kernel"), (prefix, "key", "bias"),
            (prefix, "value", "kernel"), (prefix, "value", "bias"),
            (prefix, "attention_out", "kernel"),
            (prefix, "attention_out", "bias"),
        ]

    leaves = attn("self_attn", "self_attn_ln")
    if is_decoder:
        leaves += attn("cross_attn", "cross_ln")
    leaves += [
        ("ffn_ln", "scale"), ("ffn_ln", "bias"),
        ("fc1", "kernel"), ("fc1", "bias"),
        ("fc2", "kernel"), ("fc2", "bias"),
    ]
    return tuple(leaves)


def stack_layer_params(layer_params: dict, num_layers: int,
                       leaves: tuple = _LAYER_LEAVES,
                       layer_fmt: str = "layer_{}",
                       name_fn=_stacked_name) -> dict:
    """Per-layer dense params (``layer_{i}/...``) → the stacked flat
    tree the pipelined modules declare (leading dim = num_layers)."""
    out: dict[str, Any] = {}
    for path in leaves:
        stacked = []
        for i in range(num_layers):
            node = layer_params[layer_fmt.format(i)]
            for key in path:
                node = node[key]
            stacked.append(np.asarray(node))
        out[name_fn(path)] = np.stack(stacked, axis=0)
    return out


def unstack_layer_params(stacked: dict, num_layers: int,
                         leaves: tuple = _LAYER_LEAVES,
                         layer_fmt: str = "layer_{}",
                         name_fn=_stacked_name) -> dict:
    """Inverse of :func:`stack_layer_params` (for HF-layout export)."""
    out: dict[str, Any] = {}
    for i in range(num_layers):
        layer: dict[str, Any] = {}
        for path in leaves:
            node = layer
            for key in path[:-1]:
                node = node.setdefault(key, {})
            node[path[-1]] = np.asarray(stacked[name_fn(path)])[i]
        out[layer_fmt.format(i)] = layer
    return out


def _layer_tree(flat: dict, index, leaves: tuple = _LAYER_LEAVES,
                name_fn=_stacked_name) -> dict:
    """One layer's block-structured params from the stacked tree."""
    tree: dict[str, Any] = {}
    for path in leaves:
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = flat[name_fn(path)][index]
    return tree


def gpipe_schedule(stage_fn, staged, hidden, riders, *, pp: int,
                   microbatches: int, deterministic: bool, base_key):
    """The scan/vmap/roll GPipe schedule (module docstring), shared by
    every pipelined family. ``stage_fn(p_stage, x, *riders, key) -> x``
    applies one stage's layers; ``staged`` is the [pp, lps, ...] param
    tree; ``riders`` is a tuple of [B, ...] arrays that travel WITH each
    microbatch through the stages — attention masks, and for
    encoder-decoder stacks the per-microbatch encoder outputs/masks that
    cross-attention consumes at every stage."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_PIPE,
        data_axis_names,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
        constrain_if_mesh,
    )

    B, S, H = hidden.shape
    # The schedule's outputs are M-invariant (same math, different
    # overlap), so a batch that doesn't divide the requested
    # microbatch count degrades to gcd(B, M) instead of failing —
    # init traces (batch 1) and ragged eval tails stay runnable.
    M = math.gcd(B, microbatches or pp)
    mb = B // M
    batch_axes = data_axis_names()

    def to_feed(a):
        # [B, ...] → [M + pp - 1, mb, ...] with zero fill-bubble padding
        a_mb = a.reshape(M, mb, *a.shape[1:])
        pad = jnp.zeros((pp - 1, mb, *a.shape[1:]), a.dtype)
        return jnp.concatenate([a_mb, pad], axis=0)

    def state0(a):
        return jnp.zeros((pp, mb, *a.shape[1:]), a.dtype)

    feeds = (to_feed(hidden),) + tuple(to_feed(r) for r in riders)
    states = (state0(hidden),) + tuple(state0(r) for r in riders)

    def tick(carry, feed):
        state, t = carry
        # stage 0 ingests the next microbatch; the rolled-in garbage
        # at slot 0 is overwritten
        state = tuple(s.at[0].set(f) for s, f in zip(state, feed))
        sx, *srs = state
        sx = constrain_if_mesh(sx, AXIS_PIPE, batch_axes)
        if deterministic:
            out = jax.vmap(lambda p, x, *rs: stage_fn(p, x, *rs, None))(
                staged, sx, *srs)
        else:
            tick_key = jax.random.fold_in(base_key, t)
            keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(
                jnp.arange(pp))
            out = jax.vmap(stage_fn)(staged, sx, *srs, keys)
        out = constrain_if_mesh(out, AXIS_PIPE, batch_axes)
        y = out[-1]                     # last stage's finished microbatch
        state = (jnp.roll(out, 1, axis=0),) + tuple(
            jnp.roll(s, 1, axis=0) for s in srs)  # stage s → stage s+1
        return (state, t + 1), y

    (_, _), ys = jax.lax.scan(
        tick, (states, jnp.zeros((), jnp.int32)), feeds)
    # first pp-1 tick outputs are fill-bubble garbage
    return ys[pp - 1:].reshape(B, S, H)


def convert_encdec_stacks(tree: dict, family: str, config,
                          to_stacked: bool) -> dict:
    """Per-layer ↔ stacked conversion of BOTH stacks of a pipelined
    encoder-decoder checkpoint tree (T5: ``block_{i}`` + the block-0
    rel_bias ↔ stack-level embed move; BART/mBART: ``layer_{i}``). One
    helper for the four call sites in ``auto.from_pretrained`` /
    ``auto.save_pretrained`` so the two directions cannot drift."""
    if family == "t5":
        stacks = (("encoder", config.num_layers, False),
                  ("decoder", config.num_decoder_layers, True))
        layer_fmt = "block_{}"

        def leaves_fn(dec):
            return t5_layer_leaves(dec, config.is_gated_act)
        rel_move = True
    else:
        stacks = (("encoder", config.encoder_layers, False),
                  ("decoder", config.decoder_layers, True))
        layer_fmt = "layer_{}"
        leaves_fn = bart_layer_leaves
        rel_move = False
    prefix = layer_fmt.split("{")[0]
    tree = dict(tree)
    for stack, n, dec in stacks:
        if stack not in tree:
            continue
        st = dict(tree[stack])
        leaves = leaves_fn(dec)
        if to_stacked:
            blocks = {k: st.pop(k) for k in list(st)
                      if k.startswith(prefix)}
            if rel_move:
                blk0 = dict(blocks[layer_fmt.format(0)])
                blk0["self_attn"] = dict(blk0["self_attn"])
                st["rel_bias"] = blk0["self_attn"].pop("rel_bias")
                blocks[layer_fmt.format(0)] = blk0
            st.update(stack_layer_params(blocks, n, leaves, layer_fmt,
                                         full_stacked_name))
        else:
            stacked = {full_stacked_name(p): st.pop(full_stacked_name(p))
                       for p in leaves}
            st.update(unstack_layer_params(stacked, n, leaves, layer_fmt,
                                           full_stacked_name))
            if rel_move:
                blk0 = dict(st[layer_fmt.format(0)])
                blk0["self_attn"] = dict(blk0["self_attn"])
                blk0["self_attn"]["rel_bias"] = st.pop("rel_bias")
                st[layer_fmt.format(0)] = blk0
        tree[stack] = st
    return tree


def _encdec_schedule_inputs(is_decoder: bool, B: int, S: int, attn_mask,
                            enc_hidden, enc_mask, decode: bool,
                            family: str):
    """Shared encoder-decoder schedule plumbing: the loud decode guard,
    the attn-mask default/broadcast, and the rider assembly (decoder
    cross-attention inputs travel per microbatch)."""
    if decode:
        raise ValueError(
            "pipeline_stages and incremental decode cannot combine: "
            "the KV cache is stage-local state. Export the pipelined "
            "checkpoint and reload it dense (pipeline_stages=0) for "
            "generation")
    if attn_mask is None:
        attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
    attn_mask = jnp.broadcast_to(
        attn_mask, jnp.broadcast_shapes(attn_mask.shape, (B, 1, 1, S)))
    riders = [attn_mask]
    if is_decoder:
        if enc_hidden is None:
            raise ValueError(f"pipelined {family} decoder needs enc_hidden")
        if enc_mask is None:
            enc_mask = jnp.zeros((B, 1, 1, enc_hidden.shape[1]), jnp.float32)
        enc_mask = jnp.broadcast_to(enc_mask,
                                    (B, 1, 1, enc_hidden.shape[1]))
        riders += [enc_hidden, enc_mask]
    return tuple(riders)


def _check_pipeline_shape(pp: int, num_layers: int) -> int:
    if pp < 1 or num_layers % pp:
        raise ValueError(
            f"pipeline_stages={pp} must be >= 1 and divide "
            f"num_layers={num_layers}")
    return num_layers // pp


class PipelinedEncoder(nn.Module):
    """Drop-in replacement for ``Encoder`` when
    ``config.pipeline_stages > 0``. Same math, layer-stacked params,
    GPipe schedule (see module docstring)."""

    config: EncoderConfig

    def _declare_stacked(self) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        kernel = nn.initializers.normal(cfg.initializer_range)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        shapes = {
            "query_kernel": ((L, H, H), kernel), "query_bias": ((L, H), zeros),
            "key_kernel": ((L, H, H), kernel), "key_bias": ((L, H), zeros),
            "value_kernel": ((L, H, H), kernel), "value_bias": ((L, H), zeros),
            "attention_out_kernel": ((L, H, H), kernel),
            "attention_out_bias": ((L, H), zeros),
            "attention_ln_scale": ((L, H), ones), "attention_ln_bias": ((L, H), zeros),
            "intermediate_kernel": ((L, H, F), kernel),
            "intermediate_bias": ((L, F), zeros),
            "ffn_out_kernel": ((L, F, H), kernel), "ffn_out_bias": ((L, H), zeros),
            "ffn_ln_scale": ((L, H), ones), "ffn_ln_bias": ((L, H), zeros),
        }
        return {name: self.param(name, init, shape, self.config.param_dtype)
                for name, (shape, init) in shapes.items()}

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        pp = cfg.pipeline_stages
        lps = _check_pipeline_shape(pp, cfg.num_layers)
        if cfg.num_experts:
            raise ValueError("pipeline_stages and num_experts cannot combine "
                             "(pipelined MoE is not supported)")
        B, S, _ = hidden.shape

        flat = self._declare_stacked()
        # [L, ...] → [pp, lps, ...]: stage-major so the stored dim-0
        # sharding over ``pipe`` aligns stages with pipe ranks
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        layer = EncoderLayer(cfg)
        base_key = (None if deterministic
                    else self.make_rng("dropout"))

        def stage_fn(p_stage, x, m, key):
            for i in range(lps):
                p_i = _layer_tree(p_stage, i)
                if deterministic:
                    x = layer.apply({"params": p_i}, x, m, True)
                else:
                    x = layer.apply({"params": p_i}, x, m, False,
                                    rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=remat_policy(cfg.remat_policy))

        return gpipe_schedule(
            stage_fn, staged, hidden, (attn_mask,), pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)


class PipelinedLlamaStack(nn.Module):
    """The Llama-family block stack under the GPipe schedule — pipeline
    parallelism for the modern decoder lineage (training/scoring path;
    generation's KV cache is stage-local state, enforced loudly by
    ``LlamaModel``). Two structural simplifications relative to the
    other pipelined families:

    - Llama has NO dropout anywhere, so the schedule always runs its
      deterministic branch (no per-stage rng plumbing);
    - RoPE tables depend only on positions, and the pipelined path is
      the default-positions training path (``LlamaModel`` rejects custom
      ``position_ids`` under pp), so the [1, 1, S, D] cos/sin tables are
      microbatch-invariant — computed once outside the schedule and
      closed over by every stage, broadcasting against each microbatch
      (exactly how ``PipelinedT5Stack`` treats its relative-position
      bias).

    Sliding-window variants (Mistral/Qwen2) are rejected by
    ``LlamaModel`` under pp: the per-layer window policy
    (``sliding_window_start_layer``) makes stages heterogeneous, which
    the vmap-over-stages formulation cannot express.
    """

    config: Any  # LlamaConfig (annotated loosely to avoid a cycle)

    def _declare_stacked(self, leaves) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        inner = cfg.num_heads * cfg.resolved_head_dim
        kv_inner = cfg.num_kv_heads * cfg.resolved_head_dim
        kernel = nn.initializers.normal(cfg.initializer_range)
        # Gemma RMSNorm stores (scale - 1): zeros init (models/llama.py)
        ln_init = (nn.initializers.zeros if cfg.rms_unit_offset
                   else nn.initializers.ones)
        out = {}
        for path in leaves:
            name = _stacked_name(path)
            if path[-1] == "scale":
                shape, init = (L, H), ln_init
            elif path[-1] == "bias":
                width = inner if path[-2] == "q_proj" else kv_inner
                shape, init = (L, width), nn.initializers.zeros
            elif path[-2] == "q_proj":
                shape, init = (L, H, inner), kernel
            elif path[-2] in ("k_proj", "v_proj"):
                shape, init = (L, H, kv_inner), kernel
            elif path[-2] == "o_proj":
                shape, init = (L, inner, H), kernel
            elif path[-2] in ("gate_proj", "up_proj"):
                shape, init = (L, H, F), kernel
            else:  # down_proj
                shape, init = (L, F, H), kernel
            out[name] = self.param(name, init, shape, cfg.param_dtype)
        return out

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
            LlamaBlock,
            rope_tables,
        )

        cfg = self.config
        pp = cfg.pipeline_stages
        lps = _check_pipeline_shape(pp, cfg.num_layers)
        leaves = llama_layer_leaves(cfg.qkv_bias)
        B, S, _ = hidden.shape

        flat = self._declare_stacked(leaves)
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        # microbatch-invariant: default positions are arange for every
        # row, so the [1, 1, S, D] tables broadcast over each microbatch
        rope = rope_tables(jnp.arange(S)[None, :], cfg.resolved_head_dim,
                           cfg.rope_theta, cfg.rope_scaling_dict)
        block = LlamaBlock(cfg)

        def stage_fn(p_stage, x, m, key):
            del key  # Llama has no dropout; schedule runs deterministic
            for i in range(lps):
                p_i = _layer_tree(p_stage, i, leaves)
                x = block.apply({"params": p_i}, x, (m, None), rope, None,
                                True, False)
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=remat_policy(cfg.remat_policy))

        return gpipe_schedule(
            stage_fn, staged, hidden, (attn_mask,), pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=True, base_key=None)


class PipelinedT5Stack(nn.Module):
    """T5 encoder OR decoder stack under the GPipe schedule — pipeline
    parallelism for the encoder-decoder family (training/scoring path;
    generation's KV cache is stage-local state, so decode reloads dense,
    enforced loudly like ``PipelinedGpt2Stack``).

    The two heterogeneities that kept T5 out of the r3 pipelined matrix
    are handled structurally:

    - the relative-position bias lives ONLY on block 0 in the dense
      stack (HF parity) — here its embed is declared at STACK level and
      the [1, heads, q, k] bias is computed once outside the schedule,
      then closed over by every stage (it is microbatch-invariant, so it
      doesn't ride the pipeline). Blocks run ``has_rel_bias=False`` with
      the bias passed in — bitwise the dense math.
    - decoder cross-attention consumes per-microbatch encoder outputs —
      ``enc_hidden``/``enc_mask`` travel as schedule RIDERS alongside
      the hidden state, so each stage sees the right microbatch's
      encoder context.
    """

    config: Any  # T5Config (annotated loosely to avoid a cycle)
    is_decoder: bool = False

    def _declare_stacked(self, leaves) -> dict:
        cfg = self.config
        L = cfg.num_decoder_layers if self.is_decoder else cfg.num_layers
        H, F = cfg.d_model, cfg.d_ff
        inner = cfg.num_heads * cfg.d_kv
        std_in = cfg.initializer_factor * cfg.d_model ** -0.5
        std_out = cfg.initializer_factor * cfg.d_ff ** -0.5
        ones = nn.initializers.ones
        shape_by_leaf = {
            ("attn_ln", "scale"): ((L, H), ones),
            ("cross_ln", "scale"): ((L, H), ones),
            ("ffn_ln", "scale"): ((L, H), ones),
            ("ffn", "wi", "kernel"): ((L, H, F), nn.initializers.normal(std_in)),
            ("ffn", "wi_0", "kernel"): ((L, H, F), nn.initializers.normal(std_in)),
            ("ffn", "wi_1", "kernel"): ((L, H, F), nn.initializers.normal(std_in)),
            ("ffn", "wo", "kernel"): ((L, F, H), nn.initializers.normal(std_out)),
        }
        out = {}
        for path in leaves:
            if path in shape_by_leaf:
                shape, init = shape_by_leaf[path]
            elif path[-2] == "attention_out":
                shape, init = (L, inner, H), nn.initializers.normal(std_in)
            else:  # query/key/value projections
                shape, init = (L, H, inner), nn.initializers.normal(std_in)
            name = full_stacked_name(path)
            out[name] = self.param(name, init, shape, cfg.param_dtype)
        return out

    @nn.compact
    def __call__(self, embeds, attn_mask=None, enc_hidden=None,
                 enc_mask=None, deterministic: bool = True,
                 decode: bool = False):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
            T5Block,
        )
        from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
            relative_position_bucket,
        )

        cfg = self.config
        if cfg.attention_impl == "ring":
            # the pipelined stack threads a DENSE [1,h,S,S] bias, which
            # the ring branch would misread as a raw bias table — reject
            # loudly like the other invalid combos (pp+MoE, flash+sp)
            raise ValueError(
                "pipeline_stages cannot combine with attention_impl="
                "'ring' (sequence parallelism) for T5: scale long "
                "sequences with sp OR pipeline with pp, not both")
        pp = cfg.pipeline_stages
        n_layers = cfg.num_decoder_layers if self.is_decoder else cfg.num_layers
        lps = _check_pipeline_shape(pp, n_layers)
        leaves = t5_layer_leaves(self.is_decoder, cfg.is_gated_act)

        hidden = nn.Dropout(cfg.dropout_rate)(embeds,
                                              deterministic=deterministic)
        B, S, _ = hidden.shape
        riders = _encdec_schedule_inputs(
            self.is_decoder, B, S, attn_mask, enc_hidden, enc_mask,
            decode, "T5")

        flat = self._declare_stacked(leaves)
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        # stack-level relative-position bias (same init/name semantics as
        # T5Attention._rel_bias_embed; conversion moves it from/to the
        # dense block_0/self_attn/rel_bias) — microbatch-invariant
        rel = nn.Embed(cfg.relative_attention_num_buckets, cfg.num_heads,
                       embedding_init=nn.initializers.normal(
                           cfg.initializer_factor * cfg.d_model ** -0.5),
                       dtype=jnp.float32, param_dtype=cfg.param_dtype,
                       name="rel_bias")
        ctx = jnp.arange(S)[:, None]
        mem = jnp.arange(S)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, bidirectional=not self.is_decoder,
            num_buckets=cfg.relative_attention_num_buckets,
            max_distance=cfg.relative_attention_max_distance)
        position_bias = rel(buckets).transpose(2, 0, 1)[None]

        block = T5Block(cfg, is_decoder=self.is_decoder, has_rel_bias=False)
        base_key = None if deterministic else self.make_rng("dropout")

        def stage_fn(p_stage, x, *args):
            *rs, key = args
            m = rs[0]
            eh = rs[1] if self.is_decoder else None
            em = rs[2] if self.is_decoder else None
            for i in range(lps):
                p_i = _layer_tree(p_stage, i, leaves, full_stacked_name)
                if deterministic:
                    x, _ = block.apply({"params": p_i}, x, m, eh, em,
                                       position_bias, True, False)
                else:
                    x, _ = block.apply(
                        {"params": p_i}, x, m, eh, em, position_bias,
                        False, False,
                        rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=remat_policy(cfg.remat_policy))

        hidden = gpipe_schedule(
            stage_fn, staged, hidden, riders, pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)

        from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
            RMSNorm,
        )
        hidden = RMSNorm(cfg, name="final_ln")(hidden)
        return nn.Dropout(cfg.dropout_rate)(hidden,
                                            deterministic=deterministic)


class PipelinedBartStack(nn.Module):
    """BART/mBART encoder OR decoder layers under the GPipe schedule.
    Simpler than T5 (uniform layers, no relative bias): the decoder's
    cross-attention inputs ride the schedule per microbatch exactly as
    in ``PipelinedT5Stack``. Embeddings + learned positions + embed_ln
    (and mBART's per-stack final_ln) stay at stack level; generation's
    KV cache reloads dense, enforced loudly."""

    config: Any  # BartConfig (annotated loosely to avoid a cycle)
    is_decoder: bool = False

    def _declare_stacked(self, leaves) -> dict:
        cfg = self.config
        L = cfg.decoder_layers if self.is_decoder else cfg.encoder_layers
        H = cfg.d_model
        F = cfg.decoder_ffn_dim if self.is_decoder else cfg.encoder_ffn_dim
        kernel = nn.initializers.normal(cfg.init_std)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        out = {}
        for path in leaves:
            name = full_stacked_name(path)
            if path[-1] == "scale":
                shape, init = (L, H), ones
            elif path[-1] == "bias":
                if path[0] == "fc1":
                    shape, init = (L, F), zeros
                else:
                    shape, init = (L, H), zeros
            elif path[0] == "fc1":
                shape, init = (L, H, F), kernel
            elif path[0] == "fc2":
                shape, init = (L, F, H), kernel
            else:  # attention projections, all [H, H] in BART
                shape, init = (L, H, H), kernel
            out[name] = self.param(name, init, shape, cfg.param_dtype)
        return out

    @nn.compact
    def __call__(self, embeds, attn_mask=None, enc_hidden=None,
                 enc_mask=None, deterministic: bool = True,
                 decode: bool = False):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
            _POS_OFFSET,
            BartDecoderLayer,
            BartEncoderLayer,
            _ln,
        )

        cfg = self.config
        pp = cfg.pipeline_stages
        n_layers = cfg.decoder_layers if self.is_decoder else cfg.encoder_layers
        lps = _check_pipeline_shape(pp, n_layers)
        leaves = bart_layer_leaves(self.is_decoder)

        positions = nn.Embed(
            cfg.max_position_embeddings + _POS_OFFSET, cfg.d_model,
            embedding_init=nn.initializers.normal(cfg.init_std),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="embed_positions")
        pos_ids = jnp.arange(embeds.shape[1])[None, :] + _POS_OFFSET
        hidden = _ln(cfg, "embed_ln")(embeds + positions(pos_ids))
        hidden = nn.Dropout(cfg.dropout)(hidden, deterministic=deterministic)
        B, S, _ = hidden.shape

        flat = self._declare_stacked(leaves)
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        riders = _encdec_schedule_inputs(
            self.is_decoder, B, S, attn_mask, enc_hidden, enc_mask,
            decode, "BART")
        layer = (BartDecoderLayer(cfg) if self.is_decoder
                 else BartEncoderLayer(cfg))
        base_key = None if deterministic else self.make_rng("dropout")

        def stage_fn(p_stage, x, *args):
            *rs, key = args
            m = rs[0]
            for i in range(lps):
                p_i = _layer_tree(p_stage, i, leaves, full_stacked_name)
                rngs = (None if key is None
                        else {"dropout": jax.random.fold_in(key, i)})
                if self.is_decoder:
                    x = layer.apply({"params": p_i}, x, m, rs[1], rs[2],
                                    deterministic, False,
                                    **({"rngs": rngs} if rngs else {}))
                else:
                    x = layer.apply({"params": p_i}, x, m, deterministic,
                                    **({"rngs": rngs} if rngs else {}))
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=remat_policy(cfg.remat_policy))

        hidden = gpipe_schedule(
            stage_fn, staged, hidden, riders, pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)
        if cfg.stack_final_ln:
            hidden = _ln(cfg, "final_ln")(hidden)
        return hidden


class PipelinedGpt2Stack(nn.Module):
    """The GPT-2 block stack under the same GPipe schedule — pipeline
    parallelism for the decoder-only family (training/scoring path; the
    incremental-decode KV cache is stage-local state the dense stack
    owns, so generation runs the dense path — ``Gpt2Model`` enforces
    this). Same math as the ``h_{i}`` loop in ``Gpt2Model``: causal
    masking is applied inside each block via ``dot_product_attention
    (causal=True)``, so only the padding mask rides the schedule."""

    config: Any  # Gpt2Config (annotated loosely to avoid a cycle)

    def _declare_stacked(self) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        kernel = nn.initializers.normal(cfg.initializer_range)
        # HF residual-flow init for the two output projections
        resid = nn.initializers.normal(
            cfg.initializer_range / (2 * cfg.num_layers) ** 0.5)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        shapes = {
            "ln_1_scale": ((L, H), ones), "ln_1_bias": ((L, H), zeros),
            "qkv_kernel": ((L, H, 3 * H), kernel), "qkv_bias": ((L, 3 * H), zeros),
            "attn_out_kernel": ((L, H, H), resid),
            "attn_out_bias": ((L, H), zeros),
            "ln_2_scale": ((L, H), ones), "ln_2_bias": ((L, H), zeros),
            "fc_in_kernel": ((L, H, F), kernel), "fc_in_bias": ((L, F), zeros),
            "fc_out_kernel": ((L, F, H), resid), "fc_out_bias": ((L, H), zeros),
        }
        return {name: self.param(name, init, shape, self.config.param_dtype)
                for name, (shape, init) in shapes.items()}

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import Gpt2Block

        cfg = self.config
        pp = cfg.pipeline_stages
        lps = _check_pipeline_shape(pp, cfg.num_layers)
        B, S, _ = hidden.shape

        flat = self._declare_stacked()
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        block = Gpt2Block(cfg)
        base_key = (None if deterministic
                    else self.make_rng("dropout"))

        def stage_fn(p_stage, x, m, key):
            for i in range(lps):
                p_i = _layer_tree(p_stage, i, GPT2_LAYER_LEAVES)
                if deterministic:
                    x = block.apply({"params": p_i}, x, m, True)
                else:
                    x = block.apply({"params": p_i}, x, m, False,
                                    rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(
                stage_fn, policy=remat_policy(cfg.remat_policy))

        return gpipe_schedule(
            stage_fn, staged, hidden, (attn_mask,), pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)
