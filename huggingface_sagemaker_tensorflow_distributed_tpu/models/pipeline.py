"""Pipeline parallelism: a GPipe-scheduled encoder over the ``pipe``
mesh axis.

Beyond-parity capability (the reference has no pipeline parallelism,
SURVEY.md §2 parallelism inventory), designed as *dense SPMD* rather
than per-stage programs: the encoder's layers live in ONE layer-stacked
param tree (leading dim = num_layers, sharded over ``pipe``), and the
GPipe schedule is expressed as compiler-friendly array code —

    lax.scan over ticks
      └─ vmap over stages (each applies its layers_per_stage layers)
      └─ jnp.roll along the stage dim (stage s → stage s+1 handoff)

Under ``jit`` with the stage dim sharded over ``pipe``, XLA lowers the
roll to a collective-permute along the pipe axis and the vmap body runs
concurrently on every stage — the classic SPMD pipelining formulation
(MaxText/praxis lineage), with no hand-written send/recv and no
per-stage program divergence. Single-device meshes execute the same
schedule (bit-identical math, just no overlap), so pipelined models run
everywhere the dense ones do.

Schedule shape: M microbatches over S stages take M + S - 1 ticks; the
fill/drain bubble computes on zero padding and its outputs are dropped.
Backward is plain autodiff through the scan/roll — the standard GPipe
recomputation trade is available via ``EncoderConfig.remat``.

Conversion helpers map between the per-layer tree of the dense
``Encoder`` (``layer_{i}/attention/query/kernel``) and the stacked tree
here (``query_kernel`` with leading layer dim), so HF checkpoints load
into pipelined models and pipelined models export back to HF layout.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
    EncoderLayer,
)

# stacked-name ↔ per-layer-path map: last two path components joined by
# "_" (attention/query/kernel → query_kernel, ffn_ln/scale → ffn_ln_scale)
_LAYER_LEAVES = (
    ("attention", "query", "kernel"), ("attention", "query", "bias"),
    ("attention", "key", "kernel"), ("attention", "key", "bias"),
    ("attention", "value", "kernel"), ("attention", "value", "bias"),
    ("attention", "attention_out", "kernel"), ("attention", "attention_out", "bias"),
    ("attention_ln", "scale",), ("attention_ln", "bias",),
    ("ffn", "intermediate", "kernel"), ("ffn", "intermediate", "bias"),
    ("ffn", "ffn_out", "kernel"), ("ffn", "ffn_out", "bias"),
    ("ffn_ln", "scale",), ("ffn_ln", "bias",),
)

# same contract for the GPT-2 block (``Gpt2Block``: ln_1 → fused-qkv
# attention → ln_2 → mlp), used by ``PipelinedGpt2Stack``
GPT2_LAYER_LEAVES = (
    ("ln_1", "scale"), ("ln_1", "bias"),
    ("attention", "qkv", "kernel"), ("attention", "qkv", "bias"),
    ("attention", "attn_out", "kernel"), ("attention", "attn_out", "bias"),
    ("ln_2", "scale"), ("ln_2", "bias"),
    ("mlp", "fc_in", "kernel"), ("mlp", "fc_in", "bias"),
    ("mlp", "fc_out", "kernel"), ("mlp", "fc_out", "bias"),
)


def _stacked_name(path: tuple) -> str:
    return "_".join(path[-2:])


def stack_layer_params(layer_params: dict, num_layers: int,
                       leaves: tuple = _LAYER_LEAVES,
                       layer_fmt: str = "layer_{}") -> dict:
    """Per-layer dense params (``layer_{i}/...``) → the stacked flat
    tree the pipelined modules declare (leading dim = num_layers)."""
    out: dict[str, Any] = {}
    for path in leaves:
        stacked = []
        for i in range(num_layers):
            node = layer_params[layer_fmt.format(i)]
            for key in path:
                node = node[key]
            stacked.append(np.asarray(node))
        out[_stacked_name(path)] = np.stack(stacked, axis=0)
    return out


def unstack_layer_params(stacked: dict, num_layers: int,
                         leaves: tuple = _LAYER_LEAVES,
                         layer_fmt: str = "layer_{}") -> dict:
    """Inverse of :func:`stack_layer_params` (for HF-layout export)."""
    out: dict[str, Any] = {}
    for i in range(num_layers):
        layer: dict[str, Any] = {}
        for path in leaves:
            node = layer
            for key in path[:-1]:
                node = node.setdefault(key, {})
            node[path[-1]] = np.asarray(stacked[_stacked_name(path)])[i]
        out[layer_fmt.format(i)] = layer
    return out


def _layer_tree(flat: dict, index, leaves: tuple = _LAYER_LEAVES) -> dict:
    """One layer's block-structured params from the stacked tree."""
    tree: dict[str, Any] = {}
    for path in leaves:
        node = tree
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = flat[_stacked_name(path)][index]
    return tree


def gpipe_schedule(stage_fn, staged, hidden, attn_mask, *, pp: int,
                   microbatches: int, deterministic: bool, base_key):
    """The scan/vmap/roll GPipe schedule (module docstring), shared by
    every pipelined family. ``stage_fn(p_stage, x, m, key) -> x`` applies
    one stage's layers; ``staged`` is the [pp, lps, ...] param tree;
    ``attn_mask`` is the additive [B, 1, 1, S] mask (never None here)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_PIPE,
        data_axis_names,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.parallel.sharding import (
        constrain_if_mesh,
    )

    B, S, H = hidden.shape
    # The schedule's outputs are M-invariant (same math, different
    # overlap), so a batch that doesn't divide the requested
    # microbatch count degrades to gcd(B, M) instead of failing —
    # init traces (batch 1) and ragged eval tails stay runnable.
    M = math.gcd(B, microbatches or pp)
    mb = B // M
    batch_axes = data_axis_names()

    x_mb = hidden.reshape(M, mb, S, H)
    m_mb = attn_mask.reshape(M, mb, 1, 1, attn_mask.shape[-1])
    pad_x = jnp.zeros((pp - 1, mb, S, H), hidden.dtype)
    pad_m = jnp.zeros((pp - 1, mb, 1, 1, attn_mask.shape[-1]),
                      attn_mask.dtype)
    xs_feed = jnp.concatenate([x_mb, pad_x], axis=0)    # [T, ...]
    ms_feed = jnp.concatenate([m_mb, pad_m], axis=0)

    state_x = jnp.zeros((pp, mb, S, H), hidden.dtype)
    state_m = jnp.zeros((pp, mb, 1, 1, attn_mask.shape[-1]),
                        attn_mask.dtype)

    def tick(carry, feed):
        sx, sm, t = carry
        in_x, in_m = feed
        # stage 0 ingests the next microbatch; the rolled-in garbage
        # at slot 0 is overwritten
        sx = sx.at[0].set(in_x)
        sm = sm.at[0].set(in_m)
        sx = constrain_if_mesh(sx, AXIS_PIPE, batch_axes)
        if deterministic:
            out = jax.vmap(lambda p, x, m: stage_fn(p, x, m, None))(
                staged, sx, sm)
        else:
            tick_key = jax.random.fold_in(base_key, t)
            keys = jax.vmap(lambda s: jax.random.fold_in(tick_key, s))(
                jnp.arange(pp))
            out = jax.vmap(stage_fn)(staged, sx, sm, keys)
        out = constrain_if_mesh(out, AXIS_PIPE, batch_axes)
        y = out[-1]                     # last stage's finished microbatch
        sx = jnp.roll(out, 1, axis=0)   # stage s → stage s+1
        sm = jnp.roll(sm, 1, axis=0)
        return (sx, sm, t + 1), y

    (_, _, _), ys = jax.lax.scan(
        tick, (state_x, state_m, jnp.zeros((), jnp.int32)),
        (xs_feed, ms_feed))
    # first pp-1 tick outputs are fill-bubble garbage
    return ys[pp - 1:].reshape(B, S, H)


def _check_pipeline_shape(pp: int, num_layers: int) -> int:
    if pp < 1 or num_layers % pp:
        raise ValueError(
            f"pipeline_stages={pp} must be >= 1 and divide "
            f"num_layers={num_layers}")
    return num_layers // pp


class PipelinedEncoder(nn.Module):
    """Drop-in replacement for ``Encoder`` when
    ``config.pipeline_stages > 0``. Same math, layer-stacked params,
    GPipe schedule (see module docstring)."""

    config: EncoderConfig

    def _declare_stacked(self) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        kernel = nn.initializers.normal(cfg.initializer_range)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        shapes = {
            "query_kernel": ((L, H, H), kernel), "query_bias": ((L, H), zeros),
            "key_kernel": ((L, H, H), kernel), "key_bias": ((L, H), zeros),
            "value_kernel": ((L, H, H), kernel), "value_bias": ((L, H), zeros),
            "attention_out_kernel": ((L, H, H), kernel),
            "attention_out_bias": ((L, H), zeros),
            "attention_ln_scale": ((L, H), ones), "attention_ln_bias": ((L, H), zeros),
            "intermediate_kernel": ((L, H, F), kernel),
            "intermediate_bias": ((L, F), zeros),
            "ffn_out_kernel": ((L, F, H), kernel), "ffn_out_bias": ((L, H), zeros),
            "ffn_ln_scale": ((L, H), ones), "ffn_ln_bias": ((L, H), zeros),
        }
        return {name: self.param(name, init, shape, self.config.param_dtype)
                for name, (shape, init) in shapes.items()}

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        pp = cfg.pipeline_stages
        lps = _check_pipeline_shape(pp, cfg.num_layers)
        if cfg.num_experts:
            raise ValueError("pipeline_stages and num_experts cannot combine "
                             "(pipelined MoE is not supported)")
        B, S, _ = hidden.shape

        flat = self._declare_stacked()
        # [L, ...] → [pp, lps, ...]: stage-major so the stored dim-0
        # sharding over ``pipe`` aligns stages with pipe ranks
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        layer = EncoderLayer(cfg)
        base_key = (None if deterministic
                    else self.make_rng("dropout"))

        def stage_fn(p_stage, x, m, key):
            for i in range(lps):
                p_i = _layer_tree(p_stage, i)
                if deterministic:
                    x = layer.apply({"params": p_i}, x, m, True)
                else:
                    x = layer.apply({"params": p_i}, x, m, False,
                                    rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(stage_fn)

        return gpipe_schedule(
            stage_fn, staged, hidden, attn_mask, pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)


class PipelinedGpt2Stack(nn.Module):
    """The GPT-2 block stack under the same GPipe schedule — pipeline
    parallelism for the decoder-only family (training/scoring path; the
    incremental-decode KV cache is stage-local state the dense stack
    owns, so generation runs the dense path — ``Gpt2Model`` enforces
    this). Same math as the ``h_{i}`` loop in ``Gpt2Model``: causal
    masking is applied inside each block via ``dot_product_attention
    (causal=True)``, so only the padding mask rides the schedule."""

    config: Any  # Gpt2Config (annotated loosely to avoid a cycle)

    def _declare_stacked(self) -> dict:
        cfg = self.config
        L, H, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        kernel = nn.initializers.normal(cfg.initializer_range)
        # HF residual-flow init for the two output projections
        resid = nn.initializers.normal(
            cfg.initializer_range / (2 * cfg.num_layers) ** 0.5)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones
        shapes = {
            "ln_1_scale": ((L, H), ones), "ln_1_bias": ((L, H), zeros),
            "qkv_kernel": ((L, H, 3 * H), kernel), "qkv_bias": ((L, 3 * H), zeros),
            "attn_out_kernel": ((L, H, H), resid),
            "attn_out_bias": ((L, H), zeros),
            "ln_2_scale": ((L, H), ones), "ln_2_bias": ((L, H), zeros),
            "fc_in_kernel": ((L, H, F), kernel), "fc_in_bias": ((L, F), zeros),
            "fc_out_kernel": ((L, F, H), resid), "fc_out_bias": ((L, H), zeros),
        }
        return {name: self.param(name, init, shape, self.config.param_dtype)
                for name, (shape, init) in shapes.items()}

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import Gpt2Block

        cfg = self.config
        pp = cfg.pipeline_stages
        lps = _check_pipeline_shape(pp, cfg.num_layers)
        B, S, _ = hidden.shape

        flat = self._declare_stacked()
        staged = jax.tree.map(
            lambda a: a.reshape(pp, lps, *a.shape[1:]), flat)

        if attn_mask is None:
            attn_mask = jnp.zeros((B, 1, 1, S), jnp.float32)
        attn_mask = jnp.broadcast_to(attn_mask, (B, 1, 1, S))

        block = Gpt2Block(cfg)
        base_key = (None if deterministic
                    else self.make_rng("dropout"))

        def stage_fn(p_stage, x, m, key):
            for i in range(lps):
                p_i = _layer_tree(p_stage, i, GPT2_LAYER_LEAVES)
                if deterministic:
                    x = block.apply({"params": p_i}, x, m, True)
                else:
                    x = block.apply({"params": p_i}, x, m, False,
                                    rngs={"dropout": jax.random.fold_in(key, i)})
            return x

        if cfg.remat:
            stage_fn = jax.checkpoint(stage_fn)

        return gpipe_schedule(
            stage_fn, staged, hidden, attn_mask, pp=pp,
            microbatches=cfg.pipeline_microbatches,
            deterministic=deterministic, base_key=base_key)
