"""ALBERT models + task heads.

Model-zoo breadth (SURVEY.md D7; the reference reaches any HF encoder
through ``TFAutoModelForSequenceClassification``, reference
``scripts/train.py:117``). ALBERT = a BERT-shaped post-LN encoder with
two twists, both natural here:

- factorized embeddings: embed at ``embedding_size`` then project to
  ``hidden_size`` (``embedding_hidden_mapping_in`` — ALBERT puts the
  projection in the encoder, unlike ELECTRA's backbone projection);
- cross-layer parameter sharing: ONE ``EncoderLayer`` module instance
  applied ``num_layers`` times — in Flax, repeated calls to the same
  bound submodule share parameters, so sharing costs one line (the HF
  torch version needs layer-group machinery for the same thing).

Only the common deployment shape is supported: ``num_hidden_groups=1``,
``inner_group_num=1`` (every public ALBERT v1/v2 checkpoint).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderConfig,
    EncoderLayer,
    Embeddings,
    Pooler,
    _dense,
    head_dropout_rate,
    MlmHead,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    make_attention_mask,
)


def albert_config_from_hf(hf_config: dict, **overrides) -> EncoderConfig:
    if hf_config.get("num_hidden_groups", 1) != 1 or \
            hf_config.get("inner_group_num", 1) != 1:
        raise ValueError(
            "ALBERT with num_hidden_groups/inner_group_num != 1 is not "
            "supported (no public checkpoint uses it)")
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        embedding_size=hf_config.get("embedding_size", 128),
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 2),
        hidden_act=hf_config.get("hidden_act", "gelu_new"),
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-12),
        hidden_dropout=hf_config.get("hidden_dropout_prob", 0.0),
        classifier_dropout=hf_config.get("classifier_dropout_prob", 0.1),
        attention_dropout=hf_config.get("attention_probs_dropout_prob", 0.0),
        pad_token_id=hf_config.get("pad_token_id", 0),
        initializer_range=hf_config.get("initializer_range", 0.02),
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


class AlbertBackbone(nn.Module):
    """Embeddings → hidden projection → one shared layer × num_layers
    (+ pooler)."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic: bool = True):
        cfg = self.config
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        additive_mask = make_attention_mask(attention_mask)
        x = Embeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, position_ids, attention_mask,
            deterministic)
        x = _dense(cfg, cfg.hidden_size, "embedding_hidden_mapping_in")(x)
        shared = EncoderLayer(cfg, name="shared_layer")
        for _ in range(cfg.num_layers):
            x = shared(x, additive_mask, deterministic)
        pooled = Pooler(cfg, name="pooler")(x) if cfg.use_pooler else None
        return x, pooled


class AlbertForSequenceClassification(nn.Module):
    """pooled → dropout → classifier (HF head parity)."""

    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        _, pooled = AlbertBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        x = nn.Dropout(head_dropout_rate(self.config))(
            pooled, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class AlbertForTokenClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 9

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = AlbertBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        x = nn.Dropout(head_dropout_rate(self.config))(
            seq, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class AlbertForQuestionAnswering(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = AlbertBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids,
            deterministic=deterministic)
        logits = _dense(self.config, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class AlbertForMaskedLM(nn.Module):
    """Masked-LM head tied to the factorized word embeddings (HF
    ``AlbertMLMHead`` parity: dense hidden→embedding_size, activation,
    LN, tied decoder + bias)."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False):
        seq, _ = AlbertBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        table = self.variables["params"]["backbone"]["embeddings"][
            "word_embeddings"]["embedding"]
        head = MlmHead(self.config, name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)
