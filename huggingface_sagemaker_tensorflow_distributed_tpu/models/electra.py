"""ELECTRA models + task heads.

Widens the model zoo the reference reaches implicitly through
``TFAutoModelForSequenceClassification.from_pretrained`` accepting any
HF encoder name (reference ``scripts/train.py:117``; SURVEY.md D7).

ELECTRA's discriminator is a BERT-shaped encoder with two differences
reproduced here: factorized embeddings (``embedding_size`` may be
smaller than ``hidden_size``, with a learned ``embeddings_project``
dense in the backbone — ``models/layers.py``), and no pooler — the
seq-cls head is dense→GeLU→out_proj on the CLS token
(HF ``ElectraClassificationHead``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    ACT2FN,
    EncoderBackbone,
    EncoderConfig,
    _dense,
    MlmHead,
)


def electra_config_from_hf(hf_config: dict, **overrides) -> EncoderConfig:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        embedding_size=hf_config.get("embedding_size",
                                     hf_config["hidden_size"]),
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 2),
        hidden_act=hf_config.get("hidden_act", "gelu"),
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-12),
        hidden_dropout=hf_config.get("hidden_dropout_prob", 0.1),
        attention_dropout=hf_config.get("attention_probs_dropout_prob", 0.1),
        pad_token_id=hf_config.get("pad_token_id", 0),
        initializer_range=hf_config.get("initializer_range", 0.02),
        use_pooler=False,
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


class ElectraClassificationHead(nn.Module):
    """dropout → dense → GeLU → dropout → out_proj on CLS (HF parity)."""

    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, seq, deterministic: bool = True):
        cfg = self.config
        x = seq[:, 0]
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        x = jax.nn.gelu(_dense(cfg, cfg.hidden_size, "head_dense")(x),
                        approximate=False)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return _dense(cfg, self.num_labels, "classifier")(x)


class ElectraForSequenceClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        return ElectraClassificationHead(self.config, self.num_labels,
                                         name="head")(seq, deterministic)


class ElectraForTokenClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 9

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        x = nn.Dropout(self.config.hidden_dropout)(seq, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class ElectraForQuestionAnswering(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        logits = _dense(self.config, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class ElectraForPreTraining(nn.Module):
    """Replaced-token-detection discriminator (HF
    ``ElectraForPreTraining`` parity): per-token binary logit saying
    whether the token was replaced — ELECTRA's pretraining objective."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq, _ = EncoderBackbone(cfg, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        x = _dense(cfg, cfg.hidden_size, "disc_dense")(seq)
        x = ACT2FN[cfg.hidden_act](x)
        return _dense(cfg, 1, "disc_prediction")(x)[..., 0].astype(jnp.float32)


class ElectraForMaskedLM(nn.Module):
    """Generator MLM head (HF ``ElectraForMaskedLM``:
    ``generator_predictions`` dense→gelu→LN + ``generator_lm_head`` tied
    to the factorized word embeddings) — the generator half of ELECTRA
    pretraining; the discriminator half is ``ElectraForPreTraining``."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        table = self.variables["params"]["backbone"]["embeddings"][
            "word_embeddings"]["embedding"]
        # HF ElectraGeneratorPredictions hardcodes gelu regardless of
        # config.hidden_act
        head = MlmHead(self.config, act="gelu", name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)
