"""Llama-family decoder: RoPE + GQA + SwiGLU + RMSNorm
(+ the Mistral and Qwen2 variants of the same layout).

Beyond-parity model family: the reference fine-tunes the BERT-era HF
zoo (reference ``scripts/train.py:117``); this adds the modern
decoder-only lineage — the Llama/Llama-2/3 layout, Mistral (sliding
-window attention, banded mask from logical positions so padded
prompts window correctly), and Qwen2 (hardcoded q/k/v biases,
per-layer window policy via ``max_window_layers``) — with HF
checkpoint parity — and it composes with the
framework's existing machinery for free: the causal-lm task loss,
``generate_causal`` (prefill + KV cache), LoRA (bias-free ``*_proj``
kernels), int8 weight-only decode, fused vocab-CE
(``hidden_and_embedding``), and the Megatron sharding rules
(``q|k|v_proj`` column-, ``o_proj|down_proj`` row-parallel).

Architecture (HF parity):
- token embeddings only (positions live in RoPE), no dropout;
- pre-norm blocks: ``x + attn(rms(x))`` then ``x + mlp(rms(x))``;
- rotary position embeddings in HF's rotate-half layout, applied to
  q/k after head split;
- grouped-query attention: ``num_kv_heads <= num_heads`` k/v heads,
  cached PRE-repeat (the GQA memory win), repeated to full heads for
  the attention kernel (Pallas flash on TPU);
- SwiGLU MLP ``down(silu(gate(x)) * up(x))``, all projections bias-free;
- RMSNorm (fp32 statistics island) with HF's epsilon placement;
- untied ``lm_head`` by default (``tie_word_embeddings`` supported —
  TinyLlama/Gemma-style).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    ACT2FN,
    remat_policy,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
    dot_product_attention,
    make_attention_mask,
)

NEG_INF = -1e9


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32                   # num_hidden_layers
    num_heads: int = 32                    # num_attention_heads
    num_kv_heads: int = 32                 # num_key_value_heads (GQA)
    intermediate_size: int = 11008
    max_position_embeddings: int = 2048
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    hidden_act: str = "silu"
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    bos_token_id: int = 1
    eos_token_id: int = 2
    pad_token_id: int = 0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"
    remat: bool = False
    remat_policy: str = "full"             # full | dots | dots_no_batch
    # int8 weight-only dense kernels for generation (models/quant.py)
    weight_quant: str = "none"             # none | int8
    # Mistral: attend only to the last N key positions (None = full
    # causal). On the default-positions training path the window runs
    # through the attention kernel (banded flash with tile-skipping on
    # TPU); custom position_ids and ring attention use a general
    # [B,1,S,S] banded mask instead.
    sliding_window: Optional[int] = None
    # first layer the window applies to (HF Qwen2 ``max_window_layers``
    # semantics: layers below it use full attention; 0 = window all)
    sliding_window_start_layer: int = 0
    # Qwen2: biases on q/k/v projections only (o/mlp stay bias-free)
    qkv_bias: bool = False
    # Gemma: q/k/v head size independent of hidden_size/num_heads
    # (None = hidden_size // num_heads, the Llama/Mistral/Qwen2 case)
    head_dim: Optional[int] = None
    # Gemma RMSNorm: scale applied as (1 + weight) in fp32 BEFORE the
    # cast back to the compute dtype (HF GemmaRMSNorm order)
    rms_unit_offset: bool = False
    # Gemma: embeddings multiplied by sqrt(hidden_size)
    embed_scale: bool = False
    # Llama-3.1+ long-context RoPE frequency scaling. Stored as a sorted
    # item tuple (NOT the HF dict) so the frozen config stays hashable;
    # ``rope_scaling_dict`` rebuilds the mapping. Supported rope_types:
    # "llama3" (NTK-by-parts smoothing) and "linear" (inv_freq/factor).
    rope_scaling: Optional[tuple] = None
    # Decode KV cache storage: "fp" keeps K/V in the param dtype; "int8"
    # stores symmetric per-(head, slot) int8 with an fp32 scale — long
    # -context decode is HBM-bound on the KV cache, so int8 halves the
    # cache bytes read per step vs bf16 (dequant fuses into the read).
    # Q/K/V math still runs in the compute dtype after dequant.
    kv_cache_dtype: str = "fp"             # fp | int8
    # GPipe pipeline parallelism over the block stack (models/pipeline.py;
    # training/scoring path — generation reloads dense)
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0         # 0 → = pipeline_stages
    # Mixtral: every ``moe_every``-th block's MLP becomes a token-routed
    # SwiGLU expert bank (models/moe.py::MixtralMoeBlock) sharded over
    # the ``expert`` mesh axis. HF Mixtral is MoE at EVERY layer
    # (moe_every=1, the default here); Switch-style sparse placement is
    # moe_every=2. Router/capacity semantics match the encoder MoE.
    num_experts: int = 0                   # num_local_experts
    expert_top_k: int = 2                  # num_experts_per_tok
    moe_every: int = 1
    expert_capacity_factor: float = 1.25
    router_aux_coef: float = 0.02          # router_aux_loss_coef

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def rope_scaling_dict(self) -> Optional[dict]:
        return dict(self.rope_scaling) if self.rope_scaling else None
    # which HF model_type this config round-trips as (llama | mistral |
    # qwen2 — same state-dict layout, different config.json)
    model_type: str = "llama"

    def __post_init__(self):
        if self.kv_cache_dtype not in ("fp", "int8"):
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r} "
                "(fp | int8)")
        if self.num_experts and self.model_type != "mixtral":
            # The only HF layout that can carry the expert bank is
            # Mixtral's: with any other model_type, save_pretrained
            # would write block_sparse_moe.* weights next to a
            # config.json that rebuilds a DENSE model, and the trained
            # experts would silently vanish on reload. Coerce the
            # layout-compatible variants (Mixtral IS Mistral attention +
            # experts); reject the ones whose knobs Mixtral's layout
            # cannot express. Enforced HERE so directly-constructed
            # configs get the same round-trip safety as from_pretrained.
            if self.model_type in ("llama", "mistral"):
                object.__setattr__(self, "model_type", "mixtral")
            else:
                raise ValueError(
                    f"num_experts > 0 is not supported for model_type "
                    f"{self.model_type!r}: the MoE export layout is HF "
                    "Mixtral's, which cannot express qkv biases / Gemma "
                    "norm semantics — upcycle a llama or mistral "
                    "checkpoint")


def llama_config_from_hf(hf_config: dict, **overrides) -> LlamaConfig:
    # silently-wrong-logits guards (repo convention: raise on unsupported
    # layouts rather than load-and-diverge, cf. the DeBERTa legacy-head
    # check in models/auto.py)
    scaling = hf_config.get("rope_scaling")
    rope_scaling = None
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type"))
        if rope_type == "default":
            pass
        elif rope_type in ("linear", "llama3"):
            required = (("factor",) if rope_type == "linear" else
                        ("factor", "low_freq_factor", "high_freq_factor",
                         "original_max_position_embeddings"))
            missing = [k for k in required if k not in scaling]
            if missing:
                # fail at load time with names, not as a KeyError mid-jit
                raise ValueError(
                    f"rope_scaling type {rope_type!r} is missing required "
                    f"keys {missing}: {scaling!r}")
            rope_scaling = tuple(sorted(scaling.items()))
        else:
            # yarn/dynamic-NTK etc.: loading would silently use wrong
            # RoPE frequencies and diverge from HF
            raise ValueError(
                f"rope_scaling type {rope_type!r} is not implemented "
                "(supported: default, linear, llama3 — the Llama-3.1+ "
                f"long-context scaling): {scaling!r}")
    mt = hf_config.get("model_type", "llama")
    window_start = 0
    extra = {}
    if mt == "gemma":
        extra = dict(
            rms_unit_offset=True,
            embed_scale=True,
        )
    if mt == "qwen2":
        # Qwen2's modeling class hardcodes q/k/v biases (not a config
        # field); the o/mlp projections stay bias-free. Its window is
        # PER-LAYER: layers >= max_window_layers slide, earlier ones use
        # full attention (HF layer_types derivation).
        qkv_bias = True
        if hf_config.get("use_sliding_window"):
            window = hf_config.get("sliding_window")
            window_start = hf_config.get("max_window_layers", 28)
        else:
            window = None
    else:
        qkv_bias = False
        # Mixtral is a Mistral derivative: same optional sliding window
        window = (hf_config.get("sliding_window")
                  if mt in ("mistral", "mixtral") else None)
    if mt == "mixtral":
        extra = dict(
            num_experts=hf_config["num_local_experts"],
            expert_top_k=hf_config.get("num_experts_per_tok", 2),
            # HF Mixtral: MoE at every layer; our exports persist a
            # sparser placement (+ the capacity factor, a framework
            # knob HF has no field for) as extra config.json keys
            moe_every=hf_config.get("moe_every", 1),
            # HF MixtralConfig default (0.001), NOT our field default:
            # a missing key must not silently 20x the aux penalty
            router_aux_coef=hf_config.get("router_aux_loss_coef", 0.001),
            expert_capacity_factor=hf_config.get("expert_capacity_factor",
                                                 1.25),
        )
    if hf_config.get("attention_bias") or hf_config.get("mlp_bias"):
        raise ValueError(
            "attention_bias/mlp_bias=true (biased projections under "
            f"model_type {mt!r}) is not supported: the modules are "
            "bias-free (Qwen2's hardcoded q/k/v biases ARE supported "
            "via model_type 'qwen2') and the checkpoint's biases would "
            "be silently dropped")
    kw = dict(
        model_type=mt, sliding_window=window, qkv_bias=qkv_bias,
        sliding_window_start_layer=window_start, rope_scaling=rope_scaling,
        **extra,
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        num_kv_heads=hf_config.get("num_key_value_heads",
                                   hf_config["num_attention_heads"]),
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config.get("max_position_embeddings",
                                              2048),
        rope_theta=hf_config.get("rope_theta", 10000.0),
        rms_norm_eps=hf_config.get("rms_norm_eps", 1e-5),
        # HF's GemmaMLP substitutes gelu_pytorch_tanh whenever
        # hidden_activation is absent/null (the legacy 'gelu' configs of
        # the original release included) — honour that, not hidden_act
        hidden_act=(hf_config.get("hidden_activation")
                    or ("gelu_pytorch_tanh" if mt == "gemma"
                        else hf_config.get("hidden_act", "silu"))),
        # HF reads head_dim generically (Mistral-Nemo, Llama-3.x and
        # Qwen2 derivatives serialize non-default values too)
        head_dim=hf_config.get("head_dim"),
        initializer_range=hf_config.get("initializer_range", 0.02),
        # Gemma's CLASS default is tied (unlike Llama's), so an absent
        # key means tied there
        tie_word_embeddings=hf_config.get("tie_word_embeddings",
                                          mt == "gemma"),
        bos_token_id=hf_config.get("bos_token_id", 1),
        eos_token_id=hf_config.get("eos_token_id", 2),
        pad_token_id=(hf_config["pad_token_id"]
                      if hf_config.get("pad_token_id") is not None
                      else hf_config.get("eos_token_id", 2)),
    )
    kw.update(overrides)
    kw.pop("use_pooler", None)             # encoder-family knob
    # MoE-upcycling (num_experts override on a dense checkpoint):
    # LlamaConfig.__post_init__ coerces the model_type to 'mixtral' (or
    # rejects variants Mixtral's layout can't express) so the expert
    # bank survives the export round-trip.
    return LlamaConfig(**kw)


def _dense(cfg: LlamaConfig, features: int, name: str,
           use_bias: bool = False) -> nn.Module:
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
        make_dense,
    )

    return make_dense(cfg, features,
                      nn.initializers.normal(cfg.initializer_range),
                      use_bias=use_bias, name=name)


class LlamaRMSNorm(nn.Module):
    """HF ``LlamaRMSNorm``: fp32 mean-square island, scale applied in the
    compute dtype (the weight multiplies AFTER the cast, HF order)."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        init = (nn.initializers.zeros if cfg.rms_unit_offset
                else nn.initializers.ones)
        scale = self.param("scale", init, (x.shape[-1],), cfg.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        x32 = x32 * lax.rsqrt(var + cfg.rms_norm_eps)
        if cfg.rms_unit_offset:
            # Gemma order: (1 + w) multiplied in fp32, THEN cast down
            return (x32 * (1.0 + scale.astype(jnp.float32))).astype(
                cfg.dtype)
        return (x32.astype(cfg.dtype) * scale.astype(cfg.dtype))


def _scaled_inv_freq(inv_freq, scaling: Optional[dict]):
    """Apply HF rope_scaling to the base inverse frequencies.

    - "linear": inv_freq / factor (position interpolation);
    - "llama3": NTK-by-parts (HF ``_compute_llama3_parameters``) — long
      wavelengths (past the original context) are interpolated by
      ``factor``, short ones kept, the band between ``low_freq_factor``
      and ``high_freq_factor`` smoothly blended.

    Both types have attention_factor 1.0 in HF, so cos/sin need no
    post-scaling. Unsupported types are rejected at config build.
    """
    if not scaling:
        return inv_freq
    rope_type = scaling.get("rope_type", scaling.get("type"))
    factor = scaling["factor"]
    if rope_type == "linear":
        return inv_freq / factor
    low_f = scaling["low_freq_factor"]
    high_f = scaling["high_freq_factor"]
    old_len = scaling["original_max_position_embeddings"]
    wavelen = 2.0 * jnp.pi / inv_freq
    scaled = jnp.where(wavelen > old_len / low_f, inv_freq / factor,
                       inv_freq)
    smooth = (old_len / wavelen - low_f) / (high_f - low_f)
    smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
    mid = (wavelen >= old_len / high_f) & (wavelen <= old_len / low_f)
    return jnp.where(mid, smoothed, scaled)


def rope_tables(position_ids, head_dim: int, theta: float,
                scaling: Optional[dict] = None):
    """(cos, sin) [B, 1, S, D] in HF's duplicated-half layout — computed
    ONCE per forward (they depend only on positions) and threaded to
    every layer, as HF's rotary module does. ``scaling`` is the HF
    rope_scaling mapping (``LlamaConfig.rope_scaling_dict``)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    inv_freq = _scaled_inv_freq(inv_freq, scaling)
    angles = position_ids.astype(jnp.float32)[:, :, None] * inv_freq
    cos = jnp.concatenate([jnp.cos(angles)] * 2, axis=-1)[:, None]
    sin = jnp.concatenate([jnp.sin(angles)] * 2, axis=-1)[:, None]
    return cos, sin


def apply_rope(x, rope):
    """HF rotate-half RoPE on [B, H, S, D] given precomputed tables."""
    cos, sin = rope
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos
            + rotated.astype(jnp.float32) * sin).astype(x.dtype)


def kv_quantize(x):
    """Symmetric per-(batch, head, slot) int8 quantization of a K or V
    slice [B, H, S, D]: scale = amax/127 over the head dim, zero rows
    keep scale 0 (dequant returns exact zeros). Returns (int8, fp32
    scale [B, H, S, 1])."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=-1, keepdims=True) / 127.0
    q = jnp.where(scale > 0, x32 / jnp.where(scale > 0, scale, 1.0), 0.0)
    return jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8), scale


def write_kv_cache(cached_k, cached_v, scales, k, v, cur, compute_dtype):
    """The ONE decode-cache storage protocol shared by every decoder
    family's attention (Llama family + GPT-2): per-row
    ``dynamic_update_slice`` writes of the new K/V at each row's write
    index ``cur`` [B]; when ``scales`` is a ``(k_scale, v_scale)``
    variable pair the values are stored int8 with per-(head, slot)
    fp32 scales and the returned buffers are dequantized to
    ``compute_dtype`` (the read fuses the dequant). Returns the FULL
    [B, H, max_len, D] key/value buffers for attention."""

    def row_write(buf, new, c):
        # buf [H, S, D], new [H, q, D], c scalar — one row's write
        return lax.dynamic_update_slice(buf, new, (0, c, 0))

    if scales is not None:
        k_scale, v_scale = scales
        qk, sk = kv_quantize(k)
        qv, sv = kv_quantize(v)
        cached_k.value = jax.vmap(row_write)(cached_k.value, qk, cur)
        cached_v.value = jax.vmap(row_write)(cached_v.value, qv, cur)
        k_scale.value = jax.vmap(row_write)(k_scale.value, sk, cur)
        v_scale.value = jax.vmap(row_write)(v_scale.value, sv, cur)
        k = (cached_k.value.astype(jnp.float32)
             * k_scale.value).astype(compute_dtype)
        v = (cached_v.value.astype(jnp.float32)
             * v_scale.value).astype(compute_dtype)
        return k, v
    k = jax.vmap(row_write)(cached_k.value, k, cur)
    v = jax.vmap(row_write)(cached_v.value, v, cur)
    cached_k.value, cached_v.value = k, v
    return k, v


def write_paged_kv(cached_k, cached_v, scales, block_tables, k, v, cur):
    """The paged-pool counterpart of :func:`write_kv_cache` — the ONE
    scatter-write protocol of the serve engine's fused decode path
    (``ops/pallas_paged_attention.py``). The cache variables hold BLOCK
    POOLS ``[num_blocks, block_size, H, D]`` instead of per-row dense
    buffers; ``k``/``v`` are one decode step's values [B, H, 1, D],
    written at logical position ``cur`` [B] of each row's
    ``block_tables`` [B, blocks]. With ``scales`` (a ``(k_scale,
    v_scale)`` pool-variable pair, [num_blocks, block_size, H, 1]
    fp32), values store int8 via :func:`kv_quantize` — bitwise the SAME
    quantization the dense int8 cache performs, which is what keeps
    paged serving token-exact against ``generate_causal`` under
    ``kv_cache_dtype='int8'``. Mutates the variables; the caller
    attends via ``ops.attention.paged_attention`` (the read fuses the
    dequant)."""
    from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
        scatter_paged_kv,
    )

    if scales is not None:
        k_scale, v_scale = scales
        qk, sk = kv_quantize(k)
        qv, sv = kv_quantize(v)
        cached_k.value = scatter_paged_kv(
            cached_k.value, block_tables, cur, qk[:, :, 0, :])
        cached_v.value = scatter_paged_kv(
            cached_v.value, block_tables, cur, qv[:, :, 0, :])
        k_scale.value = scatter_paged_kv(
            k_scale.value, block_tables, cur, sk[:, :, 0, :])
        v_scale.value = scatter_paged_kv(
            v_scale.value, block_tables, cur, sv[:, :, 0, :])
        return
    cached_k.value = scatter_paged_kv(
        cached_k.value, block_tables, cur, k[:, :, 0, :])
    cached_v.value = scatter_paged_kv(
        cached_v.value, block_tables, cur, v[:, :, 0, :])


class LlamaAttention(nn.Module):
    """GQA self-attention with RoPE and an optional incremental KV cache
    (cached pre-repeat: [B, H_kv, max_len, D]; stored int8 + per-slot
    scales under ``kv_cache_dtype='int8'``). ``use_window`` applies
    the config's sliding window to THIS layer (per-layer policy)."""

    config: LlamaConfig
    use_window: bool = False
    # window via the attention kernel (banded flash tile-skipping) vs a
    # general additive mask: kernel banding indexes ROWS, which equals
    # logical positions only for default (arange) position_ids
    kernel_window: bool = False

    @nn.compact
    def __call__(self, hidden, attn_mask=None, rope=None,
                 position_ids=None, deterministic: bool = True,
                 decode: bool = False):
        cfg = self.config
        head_dim = cfg.resolved_head_dim
        B, S, _ = hidden.shape

        def split(x, n_heads):
            return x.reshape(B, S, n_heads, head_dim).transpose(0, 2, 1, 3)

        qb = cfg.qkv_bias
        q = split(_dense(cfg, cfg.num_heads * head_dim, "q_proj",
                         use_bias=qb)(hidden), cfg.num_heads)
        k = split(_dense(cfg, cfg.num_kv_heads * head_dim, "k_proj",
                         use_bias=qb)(hidden), cfg.num_kv_heads)
        v = split(_dense(cfg, cfg.num_kv_heads * head_dim, "v_proj",
                         use_bias=qb)(hidden), cfg.num_kv_heads)

        q = apply_rope(q, rope)
        k = apply_rope(k, rope)

        causal = True
        if decode:
            int8_kv = cfg.kv_cache_dtype == "int8"
            kv_store = jnp.int8 if int8_kv else k.dtype
            is_init = self.has_variable("cache", "cached_key")
            cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                     k.shape, kv_store)
            cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                     v.shape, kv_store)
            if int8_kv:
                scale_shape = k.shape[:3] + (1,)
                k_scale = self.variable("cache", "cached_key_scale",
                                        jnp.zeros, scale_shape, jnp.float32)
                v_scale = self.variable("cache", "cached_value_scale",
                                        jnp.zeros, scale_shape, jnp.float32)
            # PER-ROW write indices [B]: rows may sit at different
            # depths (speculative decode accepts a different number of
            # tokens per row) — writes are per-row dynamic_update_slices
            # and the step mask broadcasts per row
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.zeros((B,), jnp.int32))
            if self.has_variable("cache", "block_tables"):
                # serve paged-pool decode: the cache vars hold BLOCK
                # POOLS and a per-row block table (the engine's fused
                # kernel path). Scatter the new K/V (pre-repeat — the
                # kernel groups queries per kv head natively), then
                # fused paged attention walks the tables directly; the
                # sliding window bands in-kernel from logical positions
                # (serve contexts are contiguous, so slot == position)
                from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import (
                    paged_attention,
                )

                if q.shape[2] != 1:
                    raise ValueError(
                        "paged decode is single-token (the fused kernel "
                        f"takes one query per slot, got q_len {q.shape[2]})")
                tables = self.get_variable("cache", "block_tables")
                cur = cache_index.value                   # [B]
                write_paged_kv(cached_k, cached_v,
                               (k_scale, v_scale) if int8_kv else None,
                               tables, k, v, cur)
                cache_index.value = cur + 1
                ctx = paged_attention(
                    q[:, :, 0, :], cached_k.value, cached_v.value,
                    tables, cur + 1, impl="pallas",
                    window=(cfg.sliding_window if self.use_window
                            else None),
                    k_scale_pool=k_scale.value if int8_kv else None,
                    v_scale_pool=v_scale.value if int8_kv else None)
                ctx = ctx.astype(cfg.dtype)[:, None, :, :]  # [B, 1, H, D]
                ctx = ctx.reshape(B, 1, cfg.num_heads * head_dim)
                return _dense(cfg, cfg.hidden_size, "o_proj")(ctx)
            if is_init:
                cur = cache_index.value                       # [B]
                max_len = cached_k.value.shape[2]
                q_len = q.shape[2]
                k, v = write_kv_cache(
                    cached_k, cached_v,
                    (k_scale, v_scale) if int8_kv else None, k, v, cur,
                    cfg.dtype)
                cache_index.value = cur + q_len
                key_pos = jnp.arange(max_len)[None, :]        # [1, S]
                qry_pos = (cur[:, None, None]
                           + jnp.arange(q_len)[None, :, None])  # [B, q, 1]
                valid = key_pos[None] <= qry_pos              # [B, q, S]
                step_mask = jnp.where(valid, 0.0, NEG_INF)[:, None]
                if cfg.sliding_window is not None and self.use_window:
                    # window in LOGICAL coordinates: buffer slots are not
                    # positions when the prompt is padded. Each valid
                    # slot's logical position is its rank among valid
                    # slots (the caller's buffer-validity mask), queries
                    # carry theirs in position_ids.
                    if attn_mask is not None:
                        valid_k = (attn_mask[:, 0, 0, :] > NEG_INF / 2)
                        key_logical = jnp.cumsum(
                            valid_k.astype(jnp.int32), axis=-1) - 1
                    else:
                        key_logical = jnp.broadcast_to(
                            jnp.arange(max_len), (B, max_len))
                    in_win = (key_logical[:, None, None, :]
                              > position_ids[:, None, :, None]
                              - cfg.sliding_window)
                    step_mask = step_mask + jnp.where(in_win, 0.0, NEG_INF)
                attn_mask = (step_mask if attn_mask is None
                             else attn_mask + step_mask)
                causal = False                 # the step mask IS causality

        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)

        window = (cfg.sliding_window
                  if (self.use_window and self.kernel_window and not decode)
                  else None)
        ctx = dot_product_attention(q, k, v, mask=attn_mask,
                                    impl=cfg.attention_impl, causal=causal,
                                    window=window)
        b, h, s, d = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return _dense(cfg, cfg.hidden_size, "o_proj")(ctx)


class LlamaMlp(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        act = ACT2FN[cfg.hidden_act]
        gate = _dense(cfg, cfg.intermediate_size, "gate_proj")(x)
        up = _dense(cfg, cfg.intermediate_size, "up_proj")(x)
        return _dense(cfg, cfg.hidden_size, "down_proj")(act(gate) * up)


class LlamaBlock(nn.Module):
    config: LlamaConfig
    use_window: bool = False
    kernel_window: bool = False
    layer_index: int = 0

    @nn.compact
    def __call__(self, hidden, masks=None, rope=None, position_ids=None,
                 deterministic: bool = True, decode: bool = False):
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
            is_moe_layer,
        )

        cfg = self.config
        plain, banded = masks if isinstance(masks, tuple) else (masks, None)
        attn_mask = banded if (self.use_window and banded is not None) \
            else plain
        attn = LlamaAttention(cfg, use_window=self.use_window,
                              kernel_window=self.kernel_window,
                              name="self_attn")(
            LlamaRMSNorm(cfg, name="input_ln")(hidden), attn_mask,
            rope, position_ids, deterministic, decode)
        hidden = hidden + attn
        normed = LlamaRMSNorm(cfg, name="post_attn_ln")(hidden)
        if cfg.num_experts and is_moe_layer(cfg, self.layer_index):
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.moe import (
                MixtralMoeBlock,
            )

            mlp = MixtralMoeBlock(cfg, name="moe")(normed, deterministic)
        else:
            mlp = LlamaMlp(cfg, name="mlp")(normed)
        return hidden + mlp


class LlamaModel(nn.Module):
    """Backbone: embeddings + blocks + final RMSNorm. Returns
    (hidden, lm weight [V, H]) so the head can fuse with vocab-CE."""

    config: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, position_ids=None,
                 deterministic: bool = True, decode: bool = False):
        cfg = self.config
        B, S = input_ids.shape
        default_positions = position_ids is None

        embed = nn.Embed(
            cfg.vocab_size, cfg.hidden_size,
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="embed_tokens")

        if position_ids is None:
            offset = 0
            if decode:
                if cfg.sliding_window is not None and attention_mask is not None:
                    # windowed decode banding runs in LOGICAL coordinates
                    # (key positions from the mask cumsum); defaulted
                    # query positions would be buffer-slot offsets, which
                    # diverge on padded prompts — silently mis-windowing.
                    # generate_causal always passes mask-derived
                    # positions; require the same of any caller.
                    raise ValueError(
                        "decode with sliding_window and an attention_mask "
                        "requires explicit position_ids (logical query "
                        "positions, e.g. mask.cumsum(-1) - 1 at each "
                        "step): defaulted buffer-slot positions would "
                        "mis-window padded prompts")
                is_init = self.has_variable("cache", "position_index")
                idx = self.variable("cache", "position_index",
                                    lambda: jnp.array(0, jnp.int32))
                if is_init:
                    offset = idx.value
                    idx.value = offset + S
            position_ids = offset + jnp.arange(S)[None, :]
            position_ids = jnp.broadcast_to(position_ids, (B, S))

        additive_mask = (make_attention_mask(attention_mask)
                        if attention_mask is not None else None)
        banded_mask = None
        # ring shards the seq axis and has no banded schedule — it gets
        # the general banded mask (detected → XLA fallback) instead
        kernel_window = (cfg.sliding_window is not None and not decode
                         and default_positions
                         and cfg.attention_impl != "ring")
        if (cfg.sliding_window is not None and not decode
                and not kernel_window):
            # Mistral banding, built ONCE from absolute positions: key
            # allowed iff 0 <= pos_q - pos_k < window. The general
            # [B,1,S,S] mask routes attention onto the XLA path (flash
            # covers pure-causal only); the decode path windows its
            # cache mask inside LlamaAttention (logical coordinates).
            # Windowed layers (i >= sliding_window_start_layer, the HF
            # Qwen2 max_window_layers policy) get the banded mask;
            # earlier layers keep full causal attention.
            pq = position_ids[:, None, :, None]
            pk = position_ids[:, None, None, :]
            band = (pq - pk < cfg.sliding_window) & (pq >= pk)
            band_mask = jnp.where(band, 0.0, NEG_INF)
            banded_mask = (band_mask if additive_mask is None
                           else additive_mask + band_mask)
        rope = rope_tables(position_ids, cfg.resolved_head_dim,
                           cfg.rope_theta, cfg.rope_scaling_dict)

        x = embed(input_ids)
        if cfg.embed_scale:
            # Gemma: normalizer in the embedding dtype (HF computes the
            # sqrt as a tensor of that dtype)
            x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
        if cfg.pipeline_stages:
            if decode:
                raise ValueError(
                    "pipeline_stages and incremental decode cannot "
                    "combine: the KV cache is stage-local state. Export "
                    "the pipelined checkpoint and reload it dense "
                    "(pipeline_stages=0) for generation")
            if cfg.num_experts:
                raise ValueError("pipeline_stages and num_experts cannot "
                                 "combine (pipelined MoE is not supported)")
            if cfg.sliding_window is not None:
                raise ValueError(
                    "pipeline_stages cannot combine with sliding_window "
                    "(Mistral/Qwen2): the per-layer window policy makes "
                    "stages heterogeneous, which the vmap-over-stages "
                    "GPipe schedule cannot express")
            if not default_positions:
                raise ValueError(
                    "pipeline_stages requires default position_ids: the "
                    "pipelined stack closes over batch-invariant RoPE "
                    "tables computed from arange positions")
            if cfg.weight_quant != "none":
                raise ValueError(
                    "pipeline_stages and weight_quant cannot combine "
                    "(int8 weight-only kernels are a decode-path "
                    "feature; the pipelined stack is training-only)")
            if cfg.attention_impl == "ring":
                raise ValueError(
                    "pipeline_stages cannot combine with attention_impl="
                    "'ring' (sequence parallelism): scale long sequences "
                    "with sp OR pipeline with pp, not both")
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                PipelinedLlamaStack,
            )
            x = PipelinedLlamaStack(cfg, name="pipelined_layers")(
                x, additive_mask, deterministic)
            x = LlamaRMSNorm(cfg, name="final_ln")(x)
            return x, embed.embedding
        block_cls = LlamaBlock
        if cfg.remat:
            block_cls = nn.remat(LlamaBlock, static_argnums=(5, 6),
                                 policy=remat_policy(cfg.remat_policy))
        for i in range(cfg.num_layers):
            windowed = (cfg.sliding_window is not None
                        and i >= cfg.sliding_window_start_layer)
            x = block_cls(cfg, use_window=windowed,
                          kernel_window=kernel_window, layer_index=i,
                          name=f"layers_{i}")(
                x, (additive_mask, banded_mask), rope, position_ids,
                deterministic, decode)
        x = LlamaRMSNorm(cfg, name="final_ln")(x)
        return x, embed.embedding


class LlamaForCausalLM(nn.Module):
    """HF ``LlamaForCausalLM`` parity. Same call signature as
    ``Gpt2LMHeadModel`` so the causal-lm task loss, ``generate_causal``
    and ``predict.py`` drive it unchanged; ``hidden_and_embedding``
    feeds the fused vocab-CE kernel (tied or untied head)."""

    config: LlamaConfig

    def setup(self):
        cfg = self.config
        self.backbone = LlamaModel(cfg)
        if not cfg.tie_word_embeddings:
            # plain fp Dense on purpose: the output projection stays full
            # precision under int8 weight-only decode (models/quant.py
            # excludes LM heads — quantization error there lands directly
            # on the logits)
            self.lm_head = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(cfg.initializer_range),
                name="lm_head")

    def _head_weight(self, tied_weight):
        if self.config.tie_word_embeddings:
            return tied_weight
        # nn.Dense kernel is [H, V]; the fused-CE contract wants [V, H]
        return self.variables["params"]["lm_head"]["kernel"].T

    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 position_ids=None, deterministic: bool = True,
                 decode: bool = False):
        # token_type_ids accepted for trainer-signature parity
        hidden, tied = self.backbone(input_ids, attention_mask,
                                     position_ids, deterministic, decode)
        if self.config.tie_word_embeddings:
            logits = jnp.einsum("bsh,vh->bsv", hidden,
                                tied.astype(self.config.dtype))
        else:
            logits = self.lm_head(hidden)
        return logits.astype(jnp.float32)

    def hidden_and_embedding(self, input_ids, attention_mask=None,
                             token_type_ids=None, position_ids=None,
                             deterministic: bool = True):
        """(hidden [B, S, H], lm weight [V, H]) — the fused-CE path."""
        hidden, tied = self.backbone(input_ids, attention_mask,
                                     position_ids, deterministic, False)
        return hidden, self._head_weight(tied)
