"""Auto-model construction and HF-layout export.

TPU-native replacement for the reference's model load/save surface:
``AutoTokenizer.from_pretrained`` + ``TFAutoModelForSequenceClassification
.from_pretrained`` (reference ``scripts/train.py:69,117``) and
``save_pretrained`` of model+tokenizer (``scripts/train.py:182-183``).

``from_pretrained(path, task=...)`` reads ``config.json`` to pick the
architecture family, builds the matching Flax module + config, initializes
the full param tree (fresh task head), and overlays the converted
checkpoint weights. ``save_pretrained(...)`` writes ``model.safetensors``
(+ ``config.json``) in HF layout so artifacts are loadable by the HF
ecosystem — the same interchange contract the reference relies on.

Offline-first: paths are local directories (this environment has no
network egress); a hub name with no local directory raises with a clear
message. ``from_scratch=True`` (or config-only dirs) skips weight load.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.models import (
    albert,
    bart,
    bert,
    deberta,
    distilbert,
    electra,
    gpt2,
    llama,
    roberta,
    t5,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.convert import (
    hf_to_params,
    load_hf_config,
    load_hf_state_dict,
    merge_into,
    params_to_hf,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import EncoderConfig
from huggingface_sagemaker_tensorflow_distributed_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# (family, task) → model class
MODEL_REGISTRY: dict[tuple[str, str], Any] = {
    ("bert", "seq-cls"): bert.BertForSequenceClassification,
    ("bert", "token-cls"): bert.BertForTokenClassification,
    ("bert", "qa"): bert.BertForQuestionAnswering,
    ("roberta", "seq-cls"): roberta.RobertaForSequenceClassification,
    ("roberta", "token-cls"): roberta.RobertaForTokenClassification,
    ("roberta", "qa"): roberta.RobertaForQuestionAnswering,
    ("distilbert", "seq-cls"): distilbert.DistilBertForSequenceClassification,
    ("distilbert", "token-cls"): distilbert.DistilBertForTokenClassification,
    ("distilbert", "qa"): distilbert.DistilBertForQuestionAnswering,
    ("electra", "seq-cls"): electra.ElectraForSequenceClassification,
    ("electra", "token-cls"): electra.ElectraForTokenClassification,
    ("electra", "qa"): electra.ElectraForQuestionAnswering,
    ("albert", "seq-cls"): albert.AlbertForSequenceClassification,
    ("albert", "token-cls"): albert.AlbertForTokenClassification,
    ("albert", "qa"): albert.AlbertForQuestionAnswering,
    ("t5", "seq2seq"): t5.T5ForConditionalGeneration,
    ("gpt2", "causal-lm"): gpt2.Gpt2LMHeadModel,
    ("llama", "causal-lm"): llama.LlamaForCausalLM,
    ("bert", "mlm"): bert.BertForMaskedLM,
    ("roberta", "mlm"): roberta.RobertaForMaskedLM,
    ("distilbert", "mlm"): distilbert.DistilBertForMaskedLM,
    ("albert", "mlm"): albert.AlbertForMaskedLM,
    ("deberta-v2", "seq-cls"): deberta.DebertaV2ForSequenceClassification,
    ("deberta-v2", "token-cls"): deberta.DebertaV2ForTokenClassification,
    ("deberta-v2", "qa"): deberta.DebertaV2ForQuestionAnswering,
    ("deberta-v2", "mlm"): deberta.DebertaV2ForMaskedLM,
    ("electra", "rtd"): electra.ElectraForPreTraining,
    ("electra", "mlm"): electra.ElectraForMaskedLM,
    ("bart", "seq2seq"): bart.BartForConditionalGeneration,
    ("mbart", "seq2seq"): bart.BartForConditionalGeneration,
}

CONFIG_BUILDERS = {
    "bert": bert.bert_config_from_hf,
    "roberta": roberta.roberta_config_from_hf,
    "distilbert": distilbert.distilbert_config_from_hf,
    "electra": electra.electra_config_from_hf,
    "albert": albert.albert_config_from_hf,
    "t5": t5.t5_config_from_hf,
    "gpt2": gpt2.gpt2_config_from_hf,
    "llama": llama.llama_config_from_hf,
    "deberta-v2": deberta.deberta_config_from_hf,
    "bart": bart.bart_config_from_hf,
    # mBART hardcodes pre-LN + per-stack final LN in its modeling class
    # (not in config.json), so the builder pins the variant flags
    "mbart": lambda hf, **ov: bart.bart_config_from_hf(
        hf, **{"normalize_before": True, "stack_final_ln": True, **ov}),
}

# Our config → HF config.json for export
def _bart_hf_config(c) -> dict:
    return {
        "model_type": "bart", "architectures": ["BartForConditionalGeneration"],
        "vocab_size": c.vocab_size, "d_model": c.d_model,
        "encoder_layers": c.encoder_layers, "decoder_layers": c.decoder_layers,
        "encoder_attention_heads": c.encoder_attention_heads,
        "decoder_attention_heads": c.decoder_attention_heads,
        "encoder_ffn_dim": c.encoder_ffn_dim,
        "decoder_ffn_dim": c.decoder_ffn_dim,
        "activation_function": c.activation_function,
        "dropout": c.dropout, "attention_dropout": c.attention_dropout,
        "activation_dropout": c.activation_dropout,
        "max_position_embeddings": c.max_position_embeddings,
        "init_std": c.init_std, "scale_embedding": c.scale_embedding,
        "pad_token_id": c.pad_token_id, "bos_token_id": c.bos_token_id,
        "eos_token_id": c.eos_token_id,
        "decoder_start_token_id": c.decoder_start_token_id,
        "forced_bos_token_id": c.forced_bos_token_id,
    }


_HF_CONFIG_EXPORTERS = {
    "bert": lambda c: {
        "model_type": "bert", "architectures": ["BertForSequenceClassification"],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_layers, "num_attention_heads": c.num_heads,
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "type_vocab_size": c.type_vocab_size, "hidden_act": c.hidden_act,
        "layer_norm_eps": c.layer_norm_eps,
        "hidden_dropout_prob": c.hidden_dropout,
        "attention_probs_dropout_prob": c.attention_dropout,
        "pad_token_id": c.pad_token_id, "initializer_range": c.initializer_range,
    },
    "roberta": lambda c: {
        "model_type": "roberta", "architectures": ["RobertaForSequenceClassification"],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_layers, "num_attention_heads": c.num_heads,
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "type_vocab_size": c.type_vocab_size, "hidden_act": c.hidden_act,
        "layer_norm_eps": c.layer_norm_eps,
        "hidden_dropout_prob": c.hidden_dropout,
        "attention_probs_dropout_prob": c.attention_dropout,
        "pad_token_id": c.pad_token_id, "initializer_range": c.initializer_range,
    },
    "distilbert": lambda c: {
        "model_type": "distilbert", "architectures": ["DistilBertForSequenceClassification"],
        "vocab_size": c.vocab_size, "dim": c.hidden_size,
        "n_layers": c.num_layers, "n_heads": c.num_heads,
        "hidden_dim": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "activation": c.hidden_act, "dropout": c.hidden_dropout,
        "attention_dropout": c.attention_dropout,
        "pad_token_id": c.pad_token_id, "initializer_range": c.initializer_range,
    },
    "albert": lambda c: {
        "model_type": "albert", "architectures": ["AlbertForSequenceClassification"],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "embedding_size": c.embedding_size or c.hidden_size,
        "num_hidden_layers": c.num_layers, "num_attention_heads": c.num_heads,
        "num_hidden_groups": 1, "inner_group_num": 1,
        "classifier_dropout_prob": (
            c.classifier_dropout if c.classifier_dropout is not None
            else c.hidden_dropout),
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "type_vocab_size": c.type_vocab_size, "hidden_act": c.hidden_act,
        "layer_norm_eps": c.layer_norm_eps,
        "hidden_dropout_prob": c.hidden_dropout,
        "attention_probs_dropout_prob": c.attention_dropout,
        "pad_token_id": c.pad_token_id, "initializer_range": c.initializer_range,
    },
    "electra": lambda c: {
        "model_type": "electra", "architectures": ["ElectraForSequenceClassification"],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "embedding_size": c.embedding_size or c.hidden_size,
        "num_hidden_layers": c.num_layers, "num_attention_heads": c.num_heads,
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "type_vocab_size": c.type_vocab_size, "hidden_act": c.hidden_act,
        "layer_norm_eps": c.layer_norm_eps,
        "hidden_dropout_prob": c.hidden_dropout,
        "attention_probs_dropout_prob": c.attention_dropout,
        "pad_token_id": c.pad_token_id, "initializer_range": c.initializer_range,
    },
    "deberta-v2": lambda c: {
        "model_type": "deberta-v2",
        "architectures": ["DebertaV2ForSequenceClassification"],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_layers, "num_attention_heads": c.num_heads,
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "type_vocab_size": c.type_vocab_size, "hidden_act": c.hidden_act,
        "layer_norm_eps": c.layer_norm_eps,
        "hidden_dropout_prob": c.hidden_dropout,
        "attention_probs_dropout_prob": c.attention_dropout,
        "pooler_dropout": c.pooler_dropout,
        "pooler_hidden_act": c.pooler_hidden_act,
        "pooler_hidden_size": c.hidden_size,
        "pad_token_id": c.pad_token_id,
        "initializer_range": c.initializer_range,
        "embedding_size": c.embedding_size or c.hidden_size,
        "position_biased_input": c.position_biased_input,
        "relative_attention": c.relative_attention,
        "position_buckets": c.position_buckets,
        "max_relative_positions": c.max_relative_positions,
        "share_att_key": c.share_att_key,
        "pos_att_type": list(c.pos_att_type),
        "norm_rel_ebd": c.norm_rel_ebd,
        **({"conv_kernel_size": c.conv_kernel_size,
            "conv_act": c.conv_act, "conv_groups": c.conv_groups}
           if c.conv_kernel_size else {}),
    },
    "gpt2": lambda c: {
        "model_type": "gpt2", "architectures": ["GPT2LMHeadModel"],
        "vocab_size": c.vocab_size, "n_positions": c.max_position_embeddings,
        "n_embd": c.hidden_size, "n_layer": c.num_layers,
        "n_head": c.num_heads, "n_inner": c.intermediate_size,
        "activation_function": c.hidden_act,
        "layer_norm_epsilon": c.layer_norm_eps,
        "resid_pdrop": c.hidden_dropout, "embd_pdrop": c.embd_dropout,
        "attn_pdrop": c.attention_dropout,
        "bos_token_id": c.bos_token_id, "eos_token_id": c.eos_token_id,
        "pad_token_id": c.pad_token_id,
        "initializer_range": c.initializer_range,
    },
    "llama": lambda c: {
        "model_type": c.model_type,
        "architectures": [{"llama": "LlamaForCausalLM",
                           "mistral": "MistralForCausalLM",
                           "qwen2": "Qwen2ForCausalLM",
                           "gemma": "GemmaForCausalLM",
                           "mixtral": "MixtralForCausalLM"}[c.model_type]],
        "vocab_size": c.vocab_size, "hidden_size": c.hidden_size,
        "num_hidden_layers": c.num_layers,
        "num_attention_heads": c.num_heads,
        "num_key_value_heads": c.num_kv_heads,
        "intermediate_size": c.intermediate_size,
        "max_position_embeddings": c.max_position_embeddings,
        "rope_theta": c.rope_theta, "rms_norm_eps": c.rms_norm_eps,
        "hidden_act": c.hidden_act,
        "tie_word_embeddings": c.tie_word_embeddings,
        "bos_token_id": c.bos_token_id, "eos_token_id": c.eos_token_id,
        "pad_token_id": c.pad_token_id,
        "initializer_range": c.initializer_range,
        **({"sliding_window": c.sliding_window} if c.model_type == "mistral"
           else {}),
        **({"sliding_window": c.sliding_window,
            "num_local_experts": c.num_experts,
            "num_experts_per_tok": c.expert_top_k,
            "router_aux_loss_coef": c.router_aux_coef,
            # framework knobs HF Mixtral has no fields for (extra keys
            # are legal in config.json; the builder reads them back)
            "moe_every": c.moe_every,
            "expert_capacity_factor": c.expert_capacity_factor}
           if c.model_type == "mixtral" else {}),
        **({"sliding_window": c.sliding_window or 4096,
            "use_sliding_window": c.sliding_window is not None,
            "max_window_layers": c.sliding_window_start_layer}
           if c.model_type == "qwen2" else {}),
        **({"head_dim": c.resolved_head_dim,
            "hidden_activation": c.hidden_act}
           if c.model_type == "gemma" else {}),
        **({"rope_scaling": c.rope_scaling_dict} if c.rope_scaling
           else {}),
        **({"head_dim": c.head_dim} if c.head_dim is not None
           and c.model_type != "gemma" else {}),
    },
    "bart": _bart_hf_config,
    "mbart": lambda c: {**_bart_hf_config(c), "model_type": "mbart",
                        "architectures": ["MBartForConditionalGeneration"]},
    "t5": lambda c: {
        "model_type": "t5", "architectures": ["T5ForConditionalGeneration"],
        "vocab_size": c.vocab_size, "d_model": c.d_model, "d_kv": c.d_kv,
        "d_ff": c.d_ff, "num_layers": c.num_layers,
        "num_decoder_layers": c.num_decoder_layers, "num_heads": c.num_heads,
        "relative_attention_num_buckets": c.relative_attention_num_buckets,
        "relative_attention_max_distance": c.relative_attention_max_distance,
        "dropout_rate": c.dropout_rate,
        "layer_norm_epsilon": c.layer_norm_epsilon,
        "feed_forward_proj": c.feed_forward_proj,
        "tie_word_embeddings": c.tie_word_embeddings,
        "pad_token_id": c.pad_token_id, "eos_token_id": c.eos_token_id,
        "decoder_start_token_id": c.decoder_start_token_id,
        "initializer_factor": c.initializer_factor,
    },
}


# families whose Encoder stack supports per-layer MoE FFNs / pipelining
# (T5 has its own blocks; ALBERT shares one layer across the stack)
_MOE_FAMILIES = ("bert", "roberta", "distilbert", "electra", "gpt2", "llama")
_PIPELINE_FAMILIES = _MOE_FAMILIES + ("t5", "bart", "mbart")

_MOE_CONFIG_KEYS = ("num_experts", "expert_top_k", "moe_every",
                    "expert_capacity_factor", "router_aux_coef")


# architecturally identical families that ship under their own
# model_type: same modules, same state-dict key layout
_FAMILY_ALIASES = {
    "xlm-roberta": "roberta",   # XLM-R == RoBERTa with a bigger vocab
    "camembert": "roberta",
    # same state-dict layout as Llama; the config builder reads the
    # variant knobs (sliding_window, Qwen2's hardcoded qkv biases) off
    # the original model_type
    "mistral": "llama",
    "qwen2": "llama",
    "gemma": "llama",
    # Mixtral = Mistral attention + a SwiGLU expert bank per layer; the
    # config builder reads the MoE shape off the original model_type
    "mixtral": "llama",
}


def detect_family(hf_config: dict) -> str:
    mt = hf_config.get("model_type", "")
    mt = _FAMILY_ALIASES.get(mt, mt)
    if mt in CONFIG_BUILDERS:
        return mt
    raise ValueError(f"unsupported model_type {mt!r} (supported: "
                     f"{sorted(CONFIG_BUILDERS) + sorted(_FAMILY_ALIASES)})")


def build_model(family: str, task: str, config: EncoderConfig, num_labels: int = 2):
    cls = MODEL_REGISTRY.get((family, task))
    if cls is None:
        raise ValueError(f"no model for family={family!r} task={task!r}")
    if task in ("qa", "seq2seq", "causal-lm", "mlm", "rtd"):
        return cls(config)
    return cls(config, num_labels=num_labels)


def init_params(model, config=None, seed: int = 0, seq_len: int = 8):
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.ones((1, seq_len), jnp.int32)
    mask = jnp.ones((1, seq_len), jnp.int32)
    if getattr(model, "is_encoder_decoder", False):
        variables = model.init(rng, dummy, mask, dummy, mask)
    else:
        variables = model.init(rng, dummy, mask)
    return variables["params"]


def from_pretrained(
    model_name_or_path: str,
    task: str = "seq-cls",
    num_labels: int = 2,
    dtype=jnp.float32,
    param_dtype=jnp.float32,
    seed: int = 0,
    from_scratch: bool = False,
    **config_overrides,
):
    """Load (or freshly init) a model. Returns (model, params, family, config)."""
    if not os.path.isdir(model_name_or_path):
        raise FileNotFoundError(
            f"{model_name_or_path!r} is not a local directory. This framework is "
            "offline-first: pass a local checkpoint directory containing "
            "config.json (+ model.safetensors), e.g. produced by "
            "`save_pretrained` or an HF download.")
    hf_config = load_hf_config(model_name_or_path)
    family = detect_family(hf_config)
    wants_moe = (config_overrides.get("num_experts", 0)
                 or hf_config.get("num_experts", 0))
    if wants_moe and family not in _MOE_FAMILIES:
        # T5 has its own config class (no MoE fields) and ALBERT shares
        # ONE layer across the stack (per-layer expert banks can't exist)
        raise ValueError(
            f"MoE (num_experts={wants_moe}) is not supported for "
            f"family {family!r}; supported: {sorted(_MOE_FAMILIES)}")
    wants_pp = config_overrides.get("pipeline_stages", 0)
    if wants_pp and family not in _PIPELINE_FAMILIES:
        raise ValueError(
            f"pipeline_stages={wants_pp} is not supported for family "
            f"{family!r}; supported: {sorted(_PIPELINE_FAMILIES)}")
    wants_kv = config_overrides.get("kv_cache_dtype", "fp")
    if wants_kv != "fp" and family not in ("llama", "gpt2"):
        # fail with names here, not as a TypeError inside a frozen
        # config constructor (same convention as the MoE/pp guards)
        raise ValueError(
            f"kv_cache_dtype={wants_kv!r} is only supported for the "
            f"decoder-only families (llama, gpt2), not {family!r}")
    if family in ("t5", "bart", "mbart") and task != "seq2seq":
        # failing loudly here beats a TypeError deep inside jit tracing
        # when the seq-cls loss feeds an encoder-decoder model
        raise ValueError(
            f"{model_name_or_path!r} is a {family} (encoder-decoder) "
            f"checkpoint; it only supports task='seq2seq', got task={task!r}")
    if (family == "deberta-v2" and task == "mlm"
            and hf_config.get("legacy") is False):
        raise ValueError(
            f"{model_name_or_path!r} uses the non-legacy DeBERTa MLM head "
            "(lm_predictions.lm_head); only the legacy cls.predictions "
            "layout is supported — silently loading would leave a random "
            "head (HF's own non-legacy forward is broken in transformers "
            "4.57: tie_weights clobbers lm_head.dense)")
    if family in ("gpt2", "llama") and task != "causal-lm":
        raise ValueError(
            f"{model_name_or_path!r} is a {family} (decoder-only) "
            f"checkpoint; it only supports task='causal-lm', got "
            f"task={task!r}")
    if family in ("bert", "albert") and task != "seq-cls":
        # HF Bert/Albert QA/token-cls models are built with
        # add_pooling_layer=False; only the seq-cls head uses the pooler.
        config_overrides.setdefault("use_pooler", False)
    if family in _MOE_FAMILIES:
        # a config.json we exported for an MoE model carries the MoE
        # fields — honour them so the expert bank is rebuilt on reload
        for key in _MOE_CONFIG_KEYS:
            if key in hf_config:
                config_overrides.setdefault(key, hf_config[key])
    config = CONFIG_BUILDERS[family](
        hf_config, dtype=dtype, param_dtype=param_dtype, **config_overrides)
    model = build_model(family, task, config, num_labels)
    params = init_params(model, config, seed=seed)
    has_weights = os.path.exists(os.path.join(model_name_or_path, "model.safetensors")) or \
        os.path.exists(os.path.join(model_name_or_path, "pytorch_model.bin"))
    if not from_scratch and has_weights:
        state = load_hf_state_dict(model_name_or_path)
        loaded = hf_to_params(state, family)
        if getattr(config, "pipeline_stages", 0):
            # checkpoints are stored per-layer; the pipelined modules
            # want the layer-stacked tree
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                GPT2_LAYER_LEAVES,
                stack_layer_params,
            )

            bb = loaded.get("backbone", {})
            if "encoder" in bb:
                bb = dict(bb)
                bb["pipelined_encoder"] = stack_layer_params(
                    bb.pop("encoder"), config.num_layers)
                loaded = {**loaded, "backbone": bb}
            elif family == "gpt2":
                bb = dict(bb)
                layers = {k: bb.pop(k) for k in list(bb)
                          if k.startswith("h_")}
                bb["pipelined_h"] = stack_layer_params(
                    layers, config.num_layers, GPT2_LAYER_LEAVES, "h_{}")
                loaded = {**loaded, "backbone": bb}
            elif family == "llama":
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                    llama_layer_leaves,
                )

                bb = dict(bb)
                layers = {k: bb.pop(k) for k in list(bb)
                          if k.startswith("layers_")}
                bb["pipelined_layers"] = stack_layer_params(
                    layers, config.num_layers,
                    llama_layer_leaves(config.qkv_bias), "layers_{}")
                loaded = {**loaded, "backbone": bb}
            elif family in ("t5", "bart", "mbart"):
                from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                    convert_encdec_stacks,
                )
                loaded = convert_encdec_stacks(loaded, family, config,
                                               to_stacked=True)
        params, missing = merge_into(params, loaded)
        logger.info("loaded %s (%s) — %d fresh head params", model_name_or_path,
                    family, len(missing))
        moe_path = os.path.join(model_name_or_path, "moe.safetensors")
        if os.path.exists(moe_path):
            # sidecar written by save_pretrained for MoE models: expert/
            # router weights under their native param paths
            from safetensors.numpy import load_file
            params, applied = _overlay_flat(params, load_file(moe_path))
            model_moe = {k for k in _flatten_params(params) if "/moe/" in k}
            if applied != model_moe:
                # a moe_every/num_experts override moved the expert
                # layers: refusing beats silently training random experts
                raise ValueError(
                    f"MoE sidecar {moe_path} does not line up with the "
                    f"model's expert layout (sidecar-only: "
                    f"{sorted(set(applied) - model_moe)[:4]}, model-only: "
                    f"{sorted(model_moe - applied)[:4]}); load with the "
                    "checkpoint's own num_experts/moe_every settings")
            logger.info("loaded %d MoE expert weights from %s",
                        len(applied), moe_path)
    else:
        logger.info("initialized %s (%s) from scratch", model_name_or_path, family)
    return model, params, family, config


def _flatten_params(params: Any) -> dict[str, np.ndarray]:
    from flax.traverse_util import flatten_dict

    return {k: np.asarray(v)
            for k, v in flatten_dict(params, sep="/").items()}


def _overlay_flat(params: Any, flat: dict[str, np.ndarray]) -> tuple[Any, set]:
    """Overlay a {native-path: array} dict onto a param tree. Returns
    (params, keys actually applied) so callers can detect sidecar/model
    layout mismatches instead of silently keeping random init."""
    from flax.traverse_util import flatten_dict, unflatten_dict

    tree = flatten_dict(params, sep="/")
    applied = set()
    for key, src in flat.items():
        if key not in tree:
            continue
        if tuple(np.shape(src)) != tuple(np.shape(tree[key])):
            raise ValueError(
                f"shape mismatch at {key}: sidecar {np.shape(src)} "
                f"vs model {np.shape(tree[key])}")
        tree[key] = jnp.asarray(src, dtype=jnp.asarray(tree[key]).dtype)
        applied.add(key)
    return unflatten_dict(tree, sep="/"), applied


def save_pretrained(output_dir: str, params: Any, family: str, config: EncoderConfig,
                    host0_only: bool = True) -> None:
    """Export params in HF layout (reference ``scripts/train.py:182-183``).

    Host-0 gated — the reference saves from every rank (racy on shared
    filesystems; its own comment warns about this, ``scripts/train.py:181``).
    """
    if jax.process_count() > 1:
        # Params may be sharded across non-addressable devices (fsdp/tp
        # spanning hosts): gather to fully-replicated host arrays first.
        # Collective — every host must participate before the host-0 gate.
        from jax.experimental import multihost_utils
        # tiled=True: reassemble each param's GLOBAL value (tiled=False
        # stacks per-process copies, and is unsupported for arrays whose
        # shards span processes)
        params = multihost_utils.process_allgather(params, tiled=True)
    if host0_only and jax.process_index() != 0:
        return
    os.makedirs(output_dir, exist_ok=True)
    params = jax.device_get(params)
    if getattr(config, "pipeline_stages", 0):
        # stacked → per-layer so the HF reverse rules apply
        from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
            GPT2_LAYER_LEAVES,
            unstack_layer_params,
        )

        bb = params.get("backbone", {})
        if "pipelined_encoder" in bb:
            bb = dict(bb)
            bb["encoder"] = unstack_layer_params(
                bb.pop("pipelined_encoder"), config.num_layers)
            params = {**params, "backbone": bb}
        elif "pipelined_h" in bb:
            bb = dict(bb)
            bb.update(unstack_layer_params(
                bb.pop("pipelined_h"), config.num_layers,
                GPT2_LAYER_LEAVES, "h_{}"))
            params = {**params, "backbone": bb}
        elif "pipelined_layers" in bb:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                llama_layer_leaves,
            )

            bb = dict(bb)
            bb.update(unstack_layer_params(
                bb.pop("pipelined_layers"), config.num_layers,
                llama_layer_leaves(config.qkv_bias), "layers_{}"))
            params = {**params, "backbone": bb}
        elif family in ("t5", "bart", "mbart"):
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                convert_encdec_stacks,
            )
            params = convert_encdec_stacks(params, family, config,
                                           to_stacked=False)
    state = params_to_hf(params, family)
    state = {k: np.ascontiguousarray(v) for k, v in state.items()}
    from safetensors.numpy import save_file
    save_file(state, os.path.join(output_dir, "model.safetensors"),
              metadata={"format": "pt"})
    cfg_dict = _HF_CONFIG_EXPORTERS[family](config)
    if getattr(config, "num_experts", 0) and family != "llama":
        # expert/router weights have no HF-layout counterpart: persist
        # them in a sidecar under native paths, and record the MoE shape
        # in config.json so from_pretrained rebuilds the expert bank.
        # (Mixtral/llama is the exception: HF DOES define an expert
        # layout, so params_to_hf exports the bank into
        # model.safetensors directly — no sidecar.)
        moe_state = {k: np.ascontiguousarray(v)
                     for k, v in _flatten_params(params).items()
                     if "/moe/" in k}
        save_file(moe_state, os.path.join(output_dir, "moe.safetensors"))
        for key in _MOE_CONFIG_KEYS:
            cfg_dict[key] = getattr(config, key)
    with open(os.path.join(output_dir, "config.json"), "w") as f:
        json.dump(cfg_dict, f, indent=2)
    logger.info("exported HF-layout checkpoint to %s", output_dir)
