"""RoBERTa models + task heads.

Covers the BASELINE.json breadth config "RoBERTa-base token-classification
on CoNLL-2003"; the reference reaches RoBERTa only implicitly through
``TFAutoModelForSequenceClassification.from_pretrained`` accepting any
BERT-family name (reference ``scripts/train.py:117``).

Differences from BERT reproduced here: position ids start at
``pad_token_id + 1`` and advance only on non-pad tokens; single token
type; LN eps 1e-5; seq-cls head is dense→tanh→out_proj on the CLS token
(HF ``RobertaClassificationHead``) rather than BERT's pooler head.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderBackbone,
    EncoderConfig,
    _dense,
    MlmHead,
)


def roberta_config_from_hf(hf_config: dict, **overrides) -> EncoderConfig:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 1),
        hidden_act=hf_config.get("hidden_act", "gelu"),
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-5),
        hidden_dropout=hf_config.get("hidden_dropout_prob", 0.1),
        attention_dropout=hf_config.get("attention_probs_dropout_prob", 0.1),
        pad_token_id=hf_config.get("pad_token_id", 1),
        position_offset=hf_config.get("pad_token_id", 1) + 1,
        use_pooler=False,
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


class RobertaClassificationHead(nn.Module):
    """dense → tanh → dropout → out_proj on CLS (HF parity)."""

    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, seq, deterministic: bool = True):
        cfg = self.config
        x = seq[:, 0]
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        x = jnp.tanh(_dense(cfg, cfg.hidden_size, "head_dense")(x))
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return _dense(cfg, self.num_labels, "classifier")(x)


class RobertaForSequenceClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        return RobertaClassificationHead(self.config, self.num_labels, name="head")(
            seq, deterministic)


class RobertaForTokenClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 9

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        x = nn.Dropout(self.config.hidden_dropout)(seq, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class RobertaForQuestionAnswering(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        logits = _dense(self.config, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class RobertaForMaskedLM(nn.Module):
    """Masked-LM head tied to the word embeddings (HF
    ``RobertaForMaskedLM`` parity; covers whole-word-masking pretraining —
    the reference's default checkpoint is
    ``bert-large-uncased-whole-word-masking``, reference ``launch.py:17``)."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        table = self.variables["params"]["backbone"]["embeddings"][
            "word_embeddings"]["embedding"]
        head = MlmHead(self.config, name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)
