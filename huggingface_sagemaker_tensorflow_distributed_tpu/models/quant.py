"""Int8 weight-only quantization for generation.

Beyond-parity capability. Autoregressive decode on TPU is HBM-bandwidth
-bound: every generated token re-reads the full weight set, so halving
(bf16) or quartering (fp32) the bytes behind each matmul raises decode
throughput roughly in proportion — compute stays in the model dtype and
the MXU never sees int8. Symmetric per-output-channel scales keep the
scheme zero-point-free, which is what XLA fuses cleanly: the dequant
(``int8 -> dtype multiply``) is a producer elementwise op folded into
the matmul's operand read, so the bf16 weight tensor never round-trips
through HBM.

The reference has no quantization story at all (its serving path is
``save_pretrained`` and whatever the downstream endpoint does,
reference ``scripts/train.py:182-183``); this is in-repo and targeted
at the decode bench (``bench.py --generate``).

Scope: GPT-2-family dense layers (qkv / attn_out / fc_in / fc_out —
``models/gpt2.py::_dense`` is the single chokepoint). Embeddings and
the tied LM head stay full precision: wte is a lookup (no bandwidth
win) and its transpose is the output projection, where quantization
error lands directly on the logits.
"""

from __future__ import annotations

import re
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict

# GPT-2 dense-kernel leaves that become int8 (path regex against the
# "/"-joined param path ending in "/kernel")
GPT2_QUANT_TARGETS = r"(qkv|attn_out|fc_in|fc_out)/kernel$"


class Int8Dense(nn.Module):
    """Drop-in for ``nn.Dense`` holding an int8 kernel + per-output
    -channel fp32 scales. Params come from :func:`quantize_params`
    (init gives zeros/ones placeholders — a quantized model is loaded,
    never trained; training stays full precision)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        q = self.param("kernel_q", nn.initializers.zeros,
                       (in_features, self.features), jnp.int8)
        scale = self.param("kernel_scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        # dequant is elementwise on the weight: XLA fuses it into the
        # dot's operand read; only int8 bytes cross HBM
        w = q.astype(self.dtype) * scale.astype(self.dtype)[None, :]
        return x @ w + bias.astype(self.dtype)


def quantize_kernel(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8: scale = max|w|/127 per column,
    q = round(w/scale). Returns (q int8 [in, out], scale fp32 [out])."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale[None, :]), -127, 127).astype(np.int8)
    return q, scale


def quantize_params(params: Any,
                    targets: str = GPT2_QUANT_TARGETS) -> tuple[Any, dict]:
    """Rewrite targeted ``.../kernel`` leaves into ``kernel_q`` +
    ``kernel_scale`` (the :class:`Int8Dense` layout); everything else
    passes through. Returns (quantized tree, stats dict)."""
    rx = re.compile(targets)
    flat = flatten_dict(params)
    out: dict = {}
    n_quant = bytes_before = bytes_after = 0
    for path, leaf in flat.items():
        path_s = "/".join(str(p) for p in path)
        if rx.search(path_s) and getattr(leaf, "ndim", 0) == 2:
            q, scale = quantize_kernel(np.asarray(leaf))
            out[path[:-1] + ("kernel_q",)] = jnp.asarray(q)
            out[path[:-1] + ("kernel_scale",)] = jnp.asarray(scale)
            n_quant += 1
            bytes_before += leaf.size * np.dtype(
                np.asarray(leaf).dtype).itemsize
            bytes_after += q.size + scale.size * 4
        else:
            out[path] = leaf
    if n_quant == 0:
        raise ValueError(f"quant target {targets!r} matched no kernels")
    stats = {"kernels_quantized": n_quant, "bytes_before": bytes_before,
             "bytes_after": bytes_after}
    return unflatten_dict(out), stats


def quantize_gpt2(model, params) -> tuple[Any, Any, dict]:
    """(model, params) -> (int8 model, int8 params, stats) for
    generation. The returned model is the same architecture with
    ``weight_quant='int8'`` (``models/gpt2.py::_dense`` swaps in
    :class:`Int8Dense`); KV cache, prefill+scan decode and sampling are
    untouched."""
    import dataclasses

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
    )

    cfg = model.config
    if not isinstance(cfg, Gpt2Config):
        raise ValueError(
            "int8 weight-only quantization currently covers the "
            "GPT-2 family only (the decode-bound one); got "
            f"{type(cfg).__name__}")
    qcfg = dataclasses.replace(cfg, weight_quant="int8")
    qmodel = type(model)(qcfg)
    qparams, stats = quantize_params(params)
    return qmodel, qparams, stats
