"""Int8 weight-only quantization for generation.

Beyond-parity capability. Autoregressive decode on TPU is HBM-bandwidth
-bound: every generated token re-reads the full weight set, so halving
(bf16) or quartering (fp32) the bytes behind each matmul raises decode
throughput roughly in proportion — compute stays in the model dtype and
the MXU never sees int8. Symmetric per-output-channel scales keep the
scheme zero-point-free, which is what XLA fuses cleanly: the dequant
(``int8 -> dtype multiply``) is a producer elementwise op folded into
the matmul's operand read, so the bf16 weight tensor never round-trips
through HBM.

The reference has no quantization story at all (its serving path is
``save_pretrained`` and whatever the downstream endpoint does,
reference ``scripts/train.py:182-183``); this is in-repo and targeted
at the decode bench (``bench.py --generate``).

Scope: the dense kernels of the generating families — GPT-2
(qkv / attn_out / fc_in / fc_out), T5 (query/key/value/attention_out,
wi / wi_0 / wi_1 / wo) and BART/mBART (q/k/v/o, fc1/fc2); each family's
``_dense`` helper is its single chokepoint. Embeddings and LM heads
(tied or not) stay full precision: embedding tables are lookups (no
bandwidth win) and the output projection is where quantization error
lands directly on the logits.
"""

from __future__ import annotations

import re
from typing import Any

import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict

# per-family dense-kernel leaves that become int8 (path regex against
# the "/"-joined param path ending in "/kernel"); LM heads excluded
GPT2_QUANT_TARGETS = r"(qkv|attn_out|fc_in|fc_out)/kernel$"
T5_QUANT_TARGETS = r"(query|key|value|attention_out|wi|wi_0|wi_1|wo)/kernel$"
BART_QUANT_TARGETS = r"(query|key|value|attention_out|fc1|fc2)/kernel$"
LLAMA_QUANT_TARGETS = (
    r"(q_proj|k_proj|v_proj|o_proj|gate_proj|up_proj|down_proj)/kernel$")


class Int8Dense(nn.Module):
    """Drop-in for ``nn.Dense`` holding an int8 kernel + per-output
    -channel fp32 scales. Params come from :func:`quantize_params`
    (init gives zeros/ones placeholders — a quantized model is loaded,
    never trained; training stays full precision)."""

    features: int
    dtype: Any = jnp.float32
    use_bias: bool = True                 # False for T5's bias-free denses

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        q = self.param("kernel_q", nn.initializers.zeros,
                       (in_features, self.features), jnp.int8)
        scale = self.param("kernel_scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        # dequant is elementwise on the weight: XLA fuses it into the
        # dot's operand read; only int8 bytes cross HBM
        w = q.astype(self.dtype) * scale.astype(self.dtype)[None, :]
        y = x @ w
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


def make_dense(cfg, features: int, kernel_init, *, use_bias: bool = True,
               name: str | None = None) -> nn.Module:
    """THE dense-construction chokepoint for the generating families:
    fp (``nn.Dense``) or int8 (:class:`Int8Dense`) by ``cfg.weight_quant``
    — so a new weight_quant mode lands here once, not per family."""
    if getattr(cfg, "weight_quant", "none") == "int8":
        return Int8Dense(features, dtype=cfg.dtype, use_bias=use_bias,
                         name=name)
    return nn.Dense(features, use_bias=use_bias, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype, kernel_init=kernel_init,
                    name=name)


def quantize_kernel(w: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-output-channel int8: scale = max|w|/127 per column,
    q = round(w/scale). Returns (q int8 [in, out], scale fp32 [out])."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale[None, :]), -127, 127).astype(np.int8)
    return q, scale


def quantize_params(params: Any, targets: str) -> tuple[Any, dict]:
    """Rewrite targeted ``.../kernel`` leaves into ``kernel_q`` +
    ``kernel_scale`` (the :class:`Int8Dense` layout); everything else
    passes through. Returns (quantized tree, stats dict)."""
    rx = re.compile(targets)
    flat = flatten_dict(params)
    out: dict = {}
    n_quant = bytes_before = bytes_after = 0
    for path, leaf in flat.items():
        path_s = "/".join(str(p) for p in path)
        if rx.search(path_s) and getattr(leaf, "ndim", 0) == 2:
            q, scale = quantize_kernel(np.asarray(leaf))
            out[path[:-1] + ("kernel_q",)] = jnp.asarray(q)
            out[path[:-1] + ("kernel_scale",)] = jnp.asarray(scale)
            n_quant += 1
            bytes_before += leaf.size * np.dtype(
                np.asarray(leaf).dtype).itemsize
            bytes_after += q.size + scale.size * 4
        else:
            out[path] = leaf
    if n_quant == 0:
        raise ValueError(f"quant target {targets!r} matched no kernels")
    stats = {"kernels_quantized": n_quant, "bytes_before": bytes_before,
             "bytes_after": bytes_after}
    return unflatten_dict(out), stats


def quantize_for_generation(model, params) -> tuple[Any, Any, dict]:
    """(model, params) -> (int8 model, int8 params, stats) for
    generation. The returned model is the same architecture with
    ``weight_quant='int8'`` (the family's ``_dense`` helper swaps in
    :class:`Int8Dense`); KV cache, decode schedules and sampling are
    untouched. Covers GPT-2, Llama, T5 and BART/mBART."""
    import dataclasses

    from huggingface_sagemaker_tensorflow_distributed_tpu.models.bart import (
        BartConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.gpt2 import (
        Gpt2Config,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.llama import (
        LlamaConfig,
    )
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.t5 import (
        T5Config,
    )

    cfg = model.config
    targets = {Gpt2Config: GPT2_QUANT_TARGETS, T5Config: T5_QUANT_TARGETS,
               BartConfig: BART_QUANT_TARGETS,
               LlamaConfig: LLAMA_QUANT_TARGETS}.get(type(cfg))
    if targets is None:
        raise ValueError(
            "int8 weight-only quantization covers the generating "
            "families (GPT-2, Llama, T5, BART/mBART); got "
            f"{type(cfg).__name__}")
    qcfg = dataclasses.replace(cfg, weight_quant="int8")
    qmodel = type(model)(qcfg)
    qparams, stats = quantize_params(params, targets)
    return qmodel, qparams, stats


# original (GPT-2-only) entry point; kept as an alias
quantize_gpt2 = quantize_for_generation
