"""DeBERTa-v2/v3: disentangled-attention encoders + task heads.

Extends the model zoo beyond the reference's BERT surface (reference
``scripts/train.py:117`` accepts any HF seq-cls checkpoint; DeBERTa-v3 is
the strongest open encoder family on GLUE — SURVEY.md D7). HF
``DebertaV2Model`` parity:

- **Disentangled attention**: content-to-content scores plus
  content→position (c2p) and position→content (p2c) terms computed from
  a shared relative-position embedding table with log-bucketed distances
  (``make_log_bucket_position``), each scaled by
  ``sqrt(head_dim * (1 + |pos_att_type|))``. v3 shares the content
  query/key projections for the position terms (``share_att_key``).
- Embeddings: word (+ optional absolute positions when
  ``position_biased_input``) + LN, pad positions zeroed, optional
  ``embed_proj`` when ``embedding_size != hidden_size``.
- Encoder-level rel-embedding table with optional LayerNorm
  (``norm_rel_ebd``), optional depthwise-ish ConvLayer merged after the
  first encoder layer (deberta-v2-xlarge).

The score grid is [B, H, Q, K] with two gathers per layer — inherently
materializing, so this family runs the XLA attention formulation (a
flash-style kernel would need the gathers fused; not attempted).
Numerics verified against HF torch in ``tests/test_deberta.py``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    ACT2FN,
    MlmHead,
    remat_policy,
)

NEG_INF = -1e9


@dataclass(frozen=True)
class DebertaV2Config:
    vocab_size: int = 128100
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 0
    hidden_act: str = "gelu"
    layer_norm_eps: float = 1e-7
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    pooler_dropout: float = 0.0
    pooler_hidden_act: str = "gelu"
    classifier_dropout: Optional[float] = None   # HF cls_dropout/drop_out
    initializer_range: float = 0.02
    pad_token_id: int = 0
    embedding_size: Optional[int] = None
    position_biased_input: bool = True
    relative_attention: bool = True
    position_buckets: int = 256
    max_relative_positions: int = -1             # -1: max_position_embeddings
    share_att_key: bool = True
    pos_att_type: tuple = ("c2p", "p2c")
    norm_rel_ebd: str = "layer_norm"
    conv_kernel_size: int = 0                    # 0 = no ConvLayer
    conv_act: str = "tanh"
    conv_groups: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"                  # disentangled → xla only
    remat: bool = False
    remat_policy: str = "full"           # full | dots | dots_no_batch

    @property
    def pos_ebd_size(self) -> int:
        maxp = (self.max_relative_positions if self.max_relative_positions > 0
                else self.max_position_embeddings)
        return self.position_buckets if self.position_buckets > 0 else maxp


def deberta_config_from_hf(hf_config: dict, **overrides) -> DebertaV2Config:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 0),
        hidden_act=hf_config.get("hidden_act", "gelu"),
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-7),
        hidden_dropout=hf_config.get("hidden_dropout_prob", 0.1),
        attention_dropout=hf_config.get("attention_probs_dropout_prob", 0.1),
        pooler_dropout=hf_config.get("pooler_dropout", 0.0),
        pooler_hidden_act=hf_config.get("pooler_hidden_act", "gelu"),
        classifier_dropout=hf_config.get("cls_dropout"),
        initializer_range=hf_config.get("initializer_range", 0.02),
        pad_token_id=hf_config.get("pad_token_id", 0),
        embedding_size=hf_config.get("embedding_size"),
        position_biased_input=hf_config.get("position_biased_input", True),
        relative_attention=hf_config.get("relative_attention", False),
        position_buckets=hf_config.get("position_buckets", -1),
        max_relative_positions=hf_config.get("max_relative_positions", -1),
        share_att_key=hf_config.get("share_att_key", False),
        # hub configs store pos_att_type as "c2p|p2c" (HF splits the
        # string for backwards compatibility — so must we)
        pos_att_type=tuple(
            x.strip() for x in pat.split("|")) if isinstance(
            (pat := hf_config.get("pos_att_type") or ()), str)
        else tuple(pat),
        norm_rel_ebd=hf_config.get("norm_rel_ebd", "none"),
        conv_kernel_size=hf_config.get("conv_kernel_size", 0) or 0,
        conv_act=hf_config.get("conv_act", "tanh"),
        conv_groups=hf_config.get("conv_groups", 1),
    )
    kw.update(overrides)
    kw.pop("use_pooler", None)
    return DebertaV2Config(**kw)


def make_log_bucket_position(rel, bucket_size: int, max_position: int):
    """HF ``make_log_bucket_position``: linear within ±bucket/2,
    log-spaced beyond, clamped sign-symmetric."""
    sign = jnp.sign(rel)
    mid = bucket_size // 2
    abs_pos = jnp.where((rel < mid) & (rel > -mid), mid - 1,
                        jnp.abs(rel)).astype(jnp.float32)
    log_pos = jnp.ceil(
        jnp.log(abs_pos / mid) / math.log((max_position - 1) / mid)
        * (mid - 1)) + mid
    return jnp.where(abs_pos <= mid, rel.astype(jnp.float32),
                     log_pos * sign).astype(jnp.int32)


def build_relative_position(q_len: int, k_len: int, bucket_size: int,
                            max_position: int):
    """[q_len, k_len] int32 relative positions (bucketed when enabled)."""
    rel = jnp.arange(q_len)[:, None] - jnp.arange(k_len)[None, :]
    if bucket_size > 0 and max_position > 0:
        rel = make_log_bucket_position(rel, bucket_size, max_position)
    return rel.astype(jnp.int32)


def _dense(cfg, features: int, name: str, use_bias: bool = True) -> nn.Dense:
    return nn.Dense(features, use_bias=use_bias, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    kernel_init=nn.initializers.normal(cfg.initializer_range),
                    name=name)


def _layernorm(cfg, name: str) -> nn.LayerNorm:
    return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name=name)


class DisentangledSelfAttention(nn.Module):
    """HF ``DisentangledSelfAttention`` parity (self-attention form)."""

    config: DebertaV2Config

    @nn.compact
    def __call__(self, hidden, qk_mask, rel_embeddings,
                 deterministic: bool = True):
        cfg = self.config
        H, heads = cfg.hidden_size, cfg.num_heads
        head_dim = H // heads
        B, S, _ = hidden.shape

        def split(x, length):
            return x.reshape(B, length, heads, head_dim).transpose(0, 2, 1, 3)

        query_proj = _dense(cfg, H, "query")
        key_proj = _dense(cfg, H, "key")
        q = split(query_proj(hidden), S)
        k = split(key_proj(hidden), S)
        v = split(_dense(cfg, H, "value")(hidden), S)

        scale_factor = 1 + len(cfg.pos_att_type)
        scale = math.sqrt(head_dim * scale_factor)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / scale

        if cfg.relative_attention and cfg.pos_att_type:
            span = cfg.pos_ebd_size
            maxp = (cfg.max_relative_positions if cfg.max_relative_positions > 0
                    else cfg.max_position_embeddings)
            rel_pos = build_relative_position(S, S, cfg.position_buckets, maxp)
            rel = nn.Dropout(cfg.hidden_dropout)(rel_embeddings,
                                                 deterministic=deterministic)
            rel = rel[: span * 2][None]                     # [1, 2*span, H]

            if cfg.share_att_key:
                # v3: the position terms reuse the CONTENT projections
                # (same module instances → same params)
                pos_key = key_proj(rel)
                pos_query = query_proj(rel)
            else:
                pos_key = (_dense(cfg, H, "pos_key")(rel)
                           if "c2p" in cfg.pos_att_type else None)
                pos_query = (_dense(cfg, H, "pos_query")(rel)
                             if "p2c" in cfg.pos_att_type else None)

            def split_pos(x):
                return x.reshape(1, 2 * span, heads, head_dim).transpose(0, 2, 1, 3)

            if "c2p" in cfg.pos_att_type:
                pk = split_pos(pos_key)                     # [1,h,2s,d]
                c2p = jnp.einsum("bhqd,xhkd->bhqk", q, pk).astype(jnp.float32)
                idx = jnp.clip(rel_pos + span, 0, span * 2 - 1)  # [S,S]
                c2p = jnp.take_along_axis(
                    c2p, jnp.broadcast_to(idx[None, None], (B, heads, S, S)),
                    axis=-1)
                scores = scores + c2p / scale
            if "p2c" in cfg.pos_att_type:
                pq = split_pos(pos_query)
                p2c = jnp.einsum("bhkd,xhqd->bhkq", k, pq).astype(jnp.float32)
                idx = jnp.clip(-rel_pos + span, 0, span * 2 - 1)
                p2c = jnp.take_along_axis(
                    p2c, jnp.broadcast_to(idx[None, None], (B, heads, S, S)),
                    axis=-1)
                scores = scores + p2c.transpose(0, 1, 3, 2) / scale

        scores = jnp.where(qk_mask, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        probs = nn.Dropout(cfg.attention_dropout)(probs,
                                                  deterministic=deterministic)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, heads * head_dim)
        return ctx


class DebertaLayer(nn.Module):
    """Post-LN layer: disentangled attention + FFN (HF DebertaV2Layer)."""

    config: DebertaV2Config

    @nn.compact
    def __call__(self, hidden, qk_mask, rel_embeddings,
                 deterministic: bool = True):
        cfg = self.config
        attn = DisentangledSelfAttention(cfg, name="attention")(
            hidden, qk_mask, rel_embeddings, deterministic)
        attn = _dense(cfg, cfg.hidden_size, "attention_out")(attn)
        attn = nn.Dropout(cfg.hidden_dropout)(attn, deterministic=deterministic)
        hidden = _layernorm(cfg, "attention_ln")(hidden + attn)
        x = _dense(cfg, cfg.intermediate_size, "intermediate")(hidden)
        x = ACT2FN[cfg.hidden_act](x)
        x = _dense(cfg, cfg.hidden_size, "ffn_out")(x)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)
        return _layernorm(cfg, "ffn_ln")(hidden + x)


class DebertaConv(nn.Module):
    """HF ``ConvLayer``: conv over tokens merged into the first layer's
    output through a LayerNorm residual."""

    config: DebertaV2Config

    @nn.compact
    def __call__(self, initial_hidden, layer0_out, input_mask,
                 deterministic: bool = True):
        cfg = self.config
        conv = nn.Conv(cfg.hidden_size, (cfg.conv_kernel_size,),
                       padding="SAME", feature_group_count=cfg.conv_groups,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="conv")(initial_hidden)
        conv = conv * input_mask[..., None].astype(conv.dtype)
        conv = ACT2FN[cfg.conv_act](
            nn.Dropout(cfg.hidden_dropout)(conv, deterministic=deterministic))
        out = _layernorm(cfg, "conv_ln")(layer0_out + conv)
        return out * input_mask[..., None].astype(out.dtype)


class DebertaBackbone(nn.Module):
    """Embeddings + disentangled encoder; returns final hidden states."""

    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        emb_size = cfg.embedding_size or cfg.hidden_size

        x = nn.Embed(cfg.vocab_size, emb_size,
                     embedding_init=nn.initializers.normal(cfg.initializer_range),
                     dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="word_embeddings")(input_ids)
        if cfg.position_biased_input:
            pos = nn.Embed(cfg.max_position_embeddings, emb_size,
                           embedding_init=nn.initializers.normal(cfg.initializer_range),
                           dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           name="position_embeddings")(jnp.arange(S)[None, :])
            x = x + pos
        if cfg.type_vocab_size > 0:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + nn.Embed(cfg.type_vocab_size, emb_size,
                             embedding_init=nn.initializers.normal(cfg.initializer_range),
                             dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             name="token_type_embeddings")(token_type_ids)
        if emb_size != cfg.hidden_size:
            x = _dense(cfg, cfg.hidden_size, "embed_proj", use_bias=False)(x)
        x = _layernorm(cfg, "embeddings_ln")(x)
        x = x * attention_mask[..., None].astype(x.dtype)
        x = nn.Dropout(cfg.hidden_dropout)(x, deterministic=deterministic)

        # rel-embedding table (encoder-level, shared by all layers);
        # declared as an Embed so the param path ends in /embedding like
        # every other table (conversion + sharding rules line up)
        rel_embeddings = None
        if cfg.relative_attention:
            rel_embeddings = nn.Embed(
                cfg.pos_ebd_size * 2, cfg.hidden_size,
                embedding_init=nn.initializers.normal(cfg.initializer_range),
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="rel_embeddings").embedding.astype(cfg.dtype)
            if "layer_norm" in cfg.norm_rel_ebd:
                rel_embeddings = _layernorm(cfg, "rel_ln")(rel_embeddings)

        # DeBERTa masks both query and key validity
        m = attention_mask.astype(bool)
        qk_mask = m[:, None, None, :] & m[:, None, :, None]

        initial = x
        layer_cls = DebertaLayer
        if cfg.remat:
            layer_cls = nn.remat(DebertaLayer, static_argnums=(4,),
                                 policy=remat_policy(cfg.remat_policy))
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, name=f"layer_{i}")(x, qk_mask, rel_embeddings,
                                                  deterministic)
            if i == 0 and cfg.conv_kernel_size > 0:
                x = DebertaConv(cfg, name="conv")(initial, x, attention_mask,
                                                  deterministic)
        return x


def _head_dropout(cfg) -> float:
    return (cfg.classifier_dropout if cfg.classifier_dropout is not None
            else cfg.hidden_dropout)


class DebertaV2ForSequenceClassification(nn.Module):
    """ContextPooler (CLS → dropout → dense → act) + classifier."""

    config: DebertaV2Config
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq = DebertaBackbone(cfg, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic)
        x = seq[:, 0]
        x = nn.Dropout(cfg.pooler_dropout)(x, deterministic=deterministic)
        x = ACT2FN[cfg.pooler_hidden_act](
            _dense(cfg, cfg.hidden_size, "pooler")(x))
        x = nn.Dropout(_head_dropout(cfg))(x, deterministic=deterministic)
        return _dense(cfg, self.num_labels, "classifier")(x)


class DebertaV2ForTokenClassification(nn.Module):
    config: DebertaV2Config
    num_labels: int = 9

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq = DebertaBackbone(cfg, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic)
        seq = nn.Dropout(cfg.hidden_dropout)(seq, deterministic=deterministic)
        return _dense(cfg, self.num_labels, "classifier")(seq)


class DebertaV2ForMaskedLM(nn.Module):
    """Masked-LM head tied to the word embeddings (HF legacy
    ``DebertaV2ForMaskedLM``/``DebertaV2OnlyMLMHead`` — same
    ``cls.predictions`` layout as BERT, so ``MlmHead`` is shared)."""

    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False):
        seq = DebertaBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic)
        table = self.variables["params"]["backbone"]["word_embeddings"]["embedding"]
        head = MlmHead(self.config, name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)


class DebertaV2ForQuestionAnswering(nn.Module):
    config: DebertaV2Config

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        cfg = self.config
        seq = DebertaBackbone(cfg, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic)
        logits = _dense(cfg, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]
