"""BERT models + task heads.

TPU-native replacement for ``TFAutoModelForSequenceClassification`` with
BERT checkpoints — the reference's default model path
(``bert-large-uncased-whole-word-masking``, reference ``launch.py:17``,
loaded at ``scripts/train.py:117``). Heads beyond seq-cls (token-cls,
QA) cover the breadth configs in BASELINE.json.

HF-parity notes: post-LN encoder, erf-exact GeLU, tanh pooler on the
CLS token; head structure mirrors HF ``BertForSequenceClassification``
(pooled → dropout → classifier) so converted checkpoints are numerically
identical (tested in ``tests/test_hf_parity.py``).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    EncoderBackbone,
    EncoderConfig,
    _dense,
    MlmHead,
)


def bert_config_from_hf(hf_config: dict, **overrides) -> EncoderConfig:
    """Map an HF BertConfig dict (config.json) to our EncoderConfig."""
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        hidden_size=hf_config["hidden_size"],
        num_layers=hf_config["num_hidden_layers"],
        num_heads=hf_config["num_attention_heads"],
        intermediate_size=hf_config["intermediate_size"],
        max_position_embeddings=hf_config["max_position_embeddings"],
        type_vocab_size=hf_config.get("type_vocab_size", 2),
        hidden_act=hf_config.get("hidden_act", "gelu"),
        layer_norm_eps=hf_config.get("layer_norm_eps", 1e-12),
        hidden_dropout=hf_config.get("hidden_dropout_prob", 0.1),
        attention_dropout=hf_config.get("attention_probs_dropout_prob", 0.1),
        pad_token_id=hf_config.get("pad_token_id", 0),
        initializer_range=hf_config.get("initializer_range", 0.02),
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


class BertForSequenceClassification(nn.Module):
    """Backbone → pooler → dropout → linear classifier (HF head parity)."""

    config: EncoderConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        _, pooled = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        x = nn.Dropout(self.config.hidden_dropout)(pooled, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class BertForTokenClassification(nn.Module):
    config: EncoderConfig
    num_labels: int = 9  # CoNLL-2003 default

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, position_ids=None,
                 segment_ids=None):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids,
            position_ids=position_ids, deterministic=deterministic,
            segment_ids=segment_ids)
        x = nn.Dropout(self.config.hidden_dropout)(seq, deterministic=deterministic)
        return _dense(self.config, self.num_labels, "classifier")(x)


class BertForQuestionAnswering(nn.Module):
    """Start/end span logits (SQuAD); HF ``qa_outputs`` parity."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True):
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids, deterministic=deterministic)
        logits = _dense(self.config, 2, "qa_outputs")(seq)
        start, end = jnp.split(logits, 2, axis=-1)
        return start[..., 0], end[..., 0]


class BertForMaskedLM(nn.Module):
    """Masked-LM head tied to the word embeddings (HF
    ``BertForMaskedLM`` parity; covers whole-word-masking pretraining —
    the reference's default checkpoint is
    ``bert-large-uncased-whole-word-masking``, reference ``launch.py:17``)."""

    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 deterministic: bool = True, return_fused_inputs: bool = False,
                 position_ids=None, segment_ids=None):
        # position_ids/segment_ids: token-packed MLM batches
        # (data.pipeline.pack_examples) — positions restart and attention
        # stays block-diagonal per packed example
        seq, _ = EncoderBackbone(self.config, name="backbone")(
            input_ids, attention_mask, token_type_ids,
            position_ids=position_ids, deterministic=deterministic,
            segment_ids=segment_ids)
        table = self.variables["params"]["backbone"]["embeddings"][
            "word_embeddings"]["embedding"]
        head = MlmHead(self.config, name="mlm_head")
        if return_fused_inputs:
            x, bias = head(seq, table, return_transform=True)
            return x, table, bias
        return head(seq, table)
