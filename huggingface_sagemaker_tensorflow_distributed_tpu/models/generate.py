"""Autoregressive generation for encoder-decoder models.

TPU-native replacement for the ``model.generate`` capability the
reference's model surface carries via HF ``transformers`` (SURVEY.md D7;
the reference itself only fine-tunes, reference ``scripts/train.py:145``,
but its model objects expose generation — parity requires it for the
seq2seq task family).

Design: the encoder runs once; the decoder runs inside a single jitted
``lax.scan`` over time steps with an incremental KV cache (created on a
zero-length init pass, updated per step with ``dynamic_update_slice`` —
see ``T5Attention``). Static shapes throughout: output length is fixed at
``max_new_tokens`` and finished sequences emit ``pad_token_id``, so one
compilation serves every batch. Greedy and temperature sampling; beam
search is deliberately deferred until a workload needs it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def init_cache(model, params, encoder_hidden, encoder_attention_mask,
               max_decoder_length: int):
    """Create the zero-filled decoder KV cache for ``max_decoder_length``.

    Runs the decoder once over a dummy full-length input with an
    uninitialized ``"cache"`` collection: each attention module allocates
    its buffers at full k/v shape but performs no writes (cache_index
    stays 0), so the returned cache is ready for step-wise decode.
    """
    batch = encoder_hidden.shape[0]
    dummy = jnp.ones((batch, max_decoder_length), jnp.int32)
    _, variables = model.apply(
        {"params": params}, dummy, encoder_hidden, encoder_attention_mask,
        decode=True, deterministic=True, mutable=["cache"],
        method=model.decode)
    return variables["cache"]


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "temperature"))
def _generate_jit(model, params, input_ids, attention_mask, max_new_tokens,
                  temperature, rng):
    cfg = model.config
    encoder_hidden = model.apply({"params": params}, input_ids,
                                 attention_mask, deterministic=True,
                                 method=model.encode)
    cache = init_cache(model, params, encoder_hidden, attention_mask,
                       max_new_tokens)
    batch = input_ids.shape[0]
    start = jnp.full((batch, 1), cfg.decoder_start_token_id, jnp.int32)

    def step(carry, _):
        token, cache, finished, rng = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token, encoder_hidden,
            attention_mask, decode=True, deterministic=True,
            mutable=["cache"], method=model.decode)
        logits = logits[:, -1, :].astype(jnp.float32)
        if temperature == 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
            nxt = nxt.astype(jnp.int32)
        nxt = jnp.where(finished, jnp.int32(cfg.pad_token_id), nxt)
        finished = finished | (nxt == cfg.eos_token_id)
        return (nxt[:, None], mutated["cache"], finished, rng), nxt

    carry = (start, cache, jnp.zeros((batch,), bool), rng)
    _, tokens = lax.scan(step, carry, None, length=max_new_tokens)
    return tokens.T  # [batch, max_new_tokens]


def generate(model, params, input_ids, attention_mask=None,
             max_new_tokens: int = 64, temperature: float = 0.0,
             seed: int = 0) -> jax.Array:
    """Generate output ids for a batch of source sequences.

    ``temperature=0`` → greedy; otherwise softmax sampling at that
    temperature. Returns [batch, max_new_tokens] ids, padded with
    ``pad_token_id`` after EOS.
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    return _generate_jit(model, params, input_ids, attention_mask,
                         int(max_new_tokens), float(temperature),
                         jax.random.PRNGKey(seed))
