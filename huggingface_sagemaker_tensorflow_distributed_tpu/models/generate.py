"""Autoregressive generation for encoder-decoder models.

TPU-native replacement for the ``model.generate`` capability the
reference's model surface carries via HF ``transformers`` (SURVEY.md D7;
the reference itself only fine-tunes, reference ``scripts/train.py:145``,
but its model objects expose generation — parity requires it for the
seq2seq task family).

Design: the encoder runs once; the decoder runs inside a single jitted
``lax.scan`` over time steps with an incremental KV cache (created on a
zero-length init pass, updated per step with ``dynamic_update_slice`` —
see ``T5Attention``). Static shapes throughout: output length is fixed at
``max_new_tokens`` and finished sequences emit ``pad_token_id``, so one
compilation serves every batch. Greedy, temperature sampling, and beam
search (beams flattened into the batch dim so every step stays one
batched decoder call — the TPU-friendly layout).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu import obs


def _traced_decode(phase: str, t0: float, out: jax.Array) -> jax.Array:
    """Telemetry epilogue shared by the generation entry points: on
    instrumented runs (a file sink is configured) block on the result so
    the measurement covers real decode wall time, and emit tokens/sec;
    on ordinary calls stay fully async — dispatch-only spans, no sync.
    ``t0`` is the entry-point's perf_counter at call start, so a first
    call's figure includes trace+compile (it shows as an outlier that
    correlates with the compile events; steady-state calls are honest).
    ``out`` is [batch, new_tokens]."""
    if obs.has_sink():
        import time

        with obs.span(f"{phase}/wait"):
            jax.block_until_ready(out)
        dt = max(time.perf_counter() - t0, 1e-9)
        obs.scalar(f"{phase}/tokens_per_sec",
                   out.shape[0] * out.shape[1] / dt,
                   args={"batch": int(out.shape[0]),
                         "new_tokens": int(out.shape[1])})
    return out


def init_cache(model, params, encoder_hidden, encoder_attention_mask,
               max_decoder_length: int):
    """Create the zero-filled decoder KV cache for ``max_decoder_length``.

    Runs the decoder once over a dummy full-length input with an
    uninitialized ``"cache"`` collection: each attention module allocates
    its buffers at full k/v shape but performs no writes (cache_index
    stays 0), so the returned cache is ready for step-wise decode.
    """
    batch = encoder_hidden.shape[0]
    dummy = jnp.ones((batch, max_decoder_length), jnp.int32)
    _, variables = model.apply(
        {"params": params}, dummy, encoder_hidden, encoder_attention_mask,
        decode=True, deterministic=True, mutable=["cache"],
        method=model.decode)
    return variables["cache"]


def _filter_top_k(logits, top_k: int):
    """Keep the ``top_k`` highest logits, mask the rest to -inf
    (oversized ``top_k`` keeps everything, HF TopKLogitsWarper)."""
    kth = lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_top_p(logits, top_p: float):
    """Nucleus filtering: keep the smallest prefix of the sorted
    distribution whose cumulative probability exceeds ``top_p`` (the
    first token past the threshold is kept, HF semantics)."""
    if top_p >= 1.0:
        return logits     # HF semantics: top_p=1.0 means no filtering
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    # strict < with a tolerance: float32 cumsum rounds exact-boundary
    # sums (0.5 + 0.3 → 0.79999995), which would leak one extra token
    # past a top_p sitting exactly on the cumulative mass
    keep_sorted = cum_before < top_p - 1e-6
    # the argmax always stays (top_p ≤ 1e-6 must not empty the support)
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # threshold logit = smallest kept logit
    kth = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                  axis=-1, keepdims=True)
    return jnp.where(logits < kth, -jnp.inf, logits)


def warp_logits_per_slot(logits, temperature, top_k, top_p):
    """Per-ROW warping for batches where every row carries its own
    sampling configuration (the serve engine's decode slots): the same
    temperature → top-k → top-p sequence as :func:`_warp_logits`, with
    the knobs as [rows] arrays instead of static scalars. Numeric
    conventions match the static filters exactly (strict-``<`` top-p
    boundary with the 1e-6 float32-cumsum tolerance, the argmax always
    kept, oversized/zero ``top_k`` keeping everything) so a per-slot
    configuration can never drift from what ``generate`` would sample.
    Rows with ``temperature == 0`` pass through UNWARPED — greedy rows
    select via argmax on the raw logits, not these."""
    V = logits.shape[-1]
    t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / t
    # dynamic top-k: k-th largest per row via sort + dynamic index
    # (k <= 0 or k >= V keeps everything, as _filter_top_k does)
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    k = jnp.clip(top_k, 0, V)
    kth = jnp.take_along_axis(sorted_desc,
                              jnp.maximum(k - 1, 0)[:, None], axis=-1)
    k_on = ((k > 0) & (k < V))[:, None]
    filtered = jnp.where(k_on & (scaled < kth), -jnp.inf, scaled)
    # dynamic top-p over the top-k survivors (the _warp_logits order)
    sorted_f = jnp.sort(filtered, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_f, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = cum_before < top_p[:, None] - 1e-6
    keep_sorted = keep_sorted.at[..., 0].set(True)
    pth = jnp.min(jnp.where(keep_sorted, sorted_f, jnp.inf),
                  axis=-1, keepdims=True)
    p_on = ((top_p > 0.0) & (top_p < 1.0))[:, None]
    return jnp.where(p_on & (filtered < pth), -jnp.inf, filtered)


def sample_per_slot(logits, temperature, top_k, top_p, keys, folds):
    """One per-row sampling step for mixed greedy/sampled batches (the
    serve engine's decode and final-prefill dispatches). ``logits``
    [rows, vocab] fp32; ``keys`` [rows, 2] uint32 per-request base PRNG
    keys; ``folds`` [rows] the request-global index of the token being
    drawn. The effective key is ``fold_in(base_key, fold)`` — a pure
    function of (request seed, token index), which is what makes
    sampled streams bitwise-reproducible across preemption/requeue
    (recompute preemption replays earlier tokens teacher-forced, then
    re-derives the SAME key for the next index). Greedy rows
    (``temperature == 0``) return the argmax of the RAW logits —
    bitwise the pre-sampling greedy path."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    warped = warp_logits_per_slot(logits, temperature, top_k, top_p)

    def draw(key, fold, row):
        return jax.random.categorical(jax.random.fold_in(key, fold), row)

    sampled = jax.vmap(draw)(keys, folds, warped).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "temperature", "top_k", "top_p"))
def _generate_jit(model, params, input_ids, attention_mask, max_new_tokens,
                  temperature, rng, top_k=0, top_p=0.0):
    cfg = model.config
    encoder_hidden = model.apply({"params": params}, input_ids,
                                 attention_mask, deterministic=True,
                                 method=model.encode)
    cache = init_cache(model, params, encoder_hidden, attention_mask,
                       max_new_tokens)
    batch = input_ids.shape[0]
    start = jnp.full((batch, 1), cfg.decoder_start_token_id, jnp.int32)

    forced_bos = getattr(cfg, "forced_bos_token_id", None)

    def step(carry, t):
        token, cache, finished, rng = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token, encoder_hidden,
            attention_mask, decode=True, deterministic=True,
            mutable=["cache"], method=model.decode)
        logits = logits[:, -1, :].astype(jnp.float32)
        if forced_bos is not None:
            logits = jnp.where(t == 0, _force_token(logits, forced_bos), logits)
        nxt, rng = _sample_next(logits, temperature, top_k, top_p, rng)
        nxt = jnp.where(finished, jnp.int32(cfg.pad_token_id), nxt)
        finished = finished | (nxt == cfg.eos_token_id)
        return (nxt[:, None], mutated["cache"], finished, rng), nxt

    carry = (start, cache, jnp.zeros((batch,), bool), rng)
    _, tokens = lax.scan(step, carry, jnp.arange(max_new_tokens))
    return tokens.T  # [batch, max_new_tokens]


def generate(model, params, input_ids, attention_mask=None,
             max_new_tokens: int = 64, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 0.0, seed: int = 0) -> jax.Array:
    """Generate output ids for a batch of source sequences.

    ``temperature=0`` → greedy; otherwise softmax sampling at that
    temperature, optionally truncated to the ``top_k`` most likely
    tokens and/or the ``top_p`` probability nucleus (0 disables each).
    Returns [batch, max_new_tokens] ids, padded with ``pad_token_id``
    after EOS.
    """
    import time

    t0 = time.perf_counter()
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    with obs.span("generate/seq2seq_dispatch"):
        out = _generate_jit(model, params, input_ids, attention_mask,
                            int(max_new_tokens), float(temperature),
                            jax.random.PRNGKey(seed), top_k=int(top_k),
                            top_p=float(top_p))
    return _traced_decode("generate/seq2seq", t0, out)


def _force_token(logits, token_id):
    """Replace a step's distribution with a point mass on ``token_id``
    (HF ``forced_bos_token_id`` semantics — mBART forces the target
    language id as the first generated token)."""
    forced = jnp.full_like(logits, -jnp.inf)
    return forced.at[..., token_id].set(0.0)


def _warp_logits(logits, temperature, top_k, top_p):
    """The ONE warping sequence (temperature → top-k → top-p) shared by
    plain sampling and speculative sampling, so the two paths cannot
    drift. Caller guarantees ``temperature > 0``."""
    logits = logits / temperature
    if top_k:
        logits = _filter_top_k(logits, top_k)
    if top_p:
        logits = _filter_top_p(logits, top_p)
    return logits


def _sample_next(logits, temperature, top_k, top_p, rng):
    """One sampling decision from [batch, vocab] fp32 logits; returns
    (next_token int32 [batch], rng)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng
    logits = _warp_logits(logits, temperature, top_k, top_p)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits, axis=-1).astype(jnp.int32), rng


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "temperature", "top_k", "top_p",
                                             "prefill_chunk"))
def _prefill_causal_jit(model, params, input_ids, attention_mask,
                        max_new_tokens, temperature, rng, top_k=0, top_p=0.0,
                        prefill_chunk=0):
    """Decoder-only PREFILL dispatch: allocate the full-length KV cache,
    write the prompt into it, and sample the first continuation token.
    Returns ``(first, cache, valid, finished, rng, n_real)`` — exactly
    the carry ``_decode_causal_jit`` starts its scan from. Left-padded
    prompts are supported: positions come from the padding-mask cumsum
    and padded cache slots stay masked for the whole decode.

    Split from the decode scan (ROADMAP "Decode-phase split") so the
    host sees the prefill/decode boundary: the wrapper can time TTFT
    separately from steady decode tokens/sec, and the serving path gets
    the same two-dispatch shape. The ops are unchanged — outputs are
    bit-identical to the old fused prefill+scan dispatch.

    ``prefill_chunk > 0`` splits the prefill into a ``lax.scan`` over
    fixed-size chunks (the wrapper pads the prompt width to a multiple):
    attention memory during prefill drops from O(P·total) to
    O(chunk·total) per layer — the knob that makes long-prompt serving
    fit, at the cost of re-reading the weights once per chunk. The
    chunks write the same cache slots the single pass would, so the
    decode that follows is bit-identical."""
    cfg = model.config
    B, P = input_ids.shape
    total = P + max_new_tokens

    # allocate full-length cache buffers (no writes on the init pass)
    _, variables = model.apply(
        {"params": params}, jnp.ones((B, total), jnp.int32), decode=True,
        deterministic=True, mutable=["cache"])
    cache = variables["cache"]

    # kv-buffer validity: prompt mask + not-yet-generated zeros
    valid = jnp.concatenate(
        [attention_mask.astype(jnp.int32),
         jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
    n_real = jnp.sum(attention_mask, axis=1).astype(jnp.int32)   # [B]

    # prefill: logical positions from the mask (left-pad aware)
    pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0).astype(jnp.int32)
    # per-row index of the last REAL token = last set mask bit (works
    # for left-padded, right-padded, and chunk-padded-after-left-padded
    # prompts alike)
    last_real = P - 1 - jnp.argmax(attention_mask[:, ::-1], axis=1)
    if prefill_chunk:
        C = prefill_chunk

        def chunk_step(carry, i):
            cache, last_logits = carry
            start = i * C
            ids_c = lax.dynamic_slice(input_ids, (0, start), (B, C))
            pos_c = lax.dynamic_slice(pos, (0, start), (B, C))
            lg, mut = model.apply(
                {"params": params, "cache": cache}, ids_c, valid,
                position_ids=pos_c, decode=True, deterministic=True,
                mutable=["cache"])
            # bank the last-real logits when they fall in this chunk
            rel = last_real - start                              # [B]
            sel = jnp.take_along_axis(
                lg.astype(jnp.float32),
                jnp.clip(rel, 0, C - 1)[:, None, None], axis=1)[:, 0]
            hit = (rel >= 0) & (rel < C)
            last_logits = jnp.where(hit[:, None], sel, last_logits)
            return (mut["cache"], last_logits), None

        (cache, last_logits), _ = lax.scan(
            chunk_step,
            (cache, jnp.zeros((B, cfg.vocab_size), jnp.float32)),
            jnp.arange(P // C))
    else:
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, input_ids, valid,
            position_ids=pos, decode=True, deterministic=True,
            mutable=["cache"])
        cache = mutated["cache"]
        last_logits = jnp.take_along_axis(
            logits, last_real[:, None, None], axis=1)[:, 0].astype(jnp.float32)
    first, rng = _sample_next(last_logits, temperature, top_k, top_p, rng)
    finished = first == cfg.eos_token_id
    return first, cache, valid, finished, rng, n_real


def _decode_causal(model, params, first, cache, valid, finished, rng,
                   n_real, max_new_tokens, temperature, top_k=0, top_p=0.0):
    """Decoder-only DECODE dispatch: the jitted token-by-token scan over
    the cache ``_prefill_causal_jit`` produced. Same ops as the old
    fused tail, so the concatenated output is bit-identical."""
    cfg = model.config
    B = first.shape[0]
    P = valid.shape[1] - max_new_tokens

    def step(carry, t):
        token, cache, valid, finished, rng = carry
        cur = P + t
        valid = lax.dynamic_update_slice(
            valid, jnp.ones((B, 1), jnp.int32), (0, cur))
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None], valid,
            position_ids=(n_real + t)[:, None], decode=True,
            deterministic=True, mutable=["cache"])
        nxt, rng = _sample_next(logits[:, -1, :].astype(jnp.float32),
                                temperature, top_k, top_p, rng)
        nxt = jnp.where(finished, jnp.int32(cfg.pad_token_id), nxt)
        finished = finished | (nxt == cfg.eos_token_id)
        return (nxt, mutated["cache"], valid, finished, rng), nxt

    carry = (first, cache, valid, finished, rng)
    _, rest = lax.scan(step, carry, jnp.arange(max_new_tokens - 1),
                       length=max_new_tokens - 1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


@functools.lru_cache(maxsize=2)
def _decode_causal_jit(donate: bool):
    """The jitted decode dispatch. The prefill's cache/valid buffers are
    donated on accelerator backends (the decode step consumes them; an
    undonated [B, total, layers] cache would cost one full HBM copy per
    generate call) — CPU doesn't implement donation and would warn."""
    kw = {}
    if donate:
        kw["donate_argnames"] = ("cache", "valid")
    return functools.partial(jax.jit, static_argnames=(
        "model", "max_new_tokens", "temperature", "top_k", "top_p"),
        **kw)(_decode_causal)


def generate_causal(model, params, input_ids, attention_mask=None,
                    max_new_tokens: int = 64, temperature: float = 0.0,
                    top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                    prefill_chunk: int = 0) -> jax.Array:
    """Decoder-only ``generate`` (GPT-2 family): greedy at
    ``temperature=0``, otherwise temperature/top-k/top-p sampling.
    Prompts may be left-padded (mark pads 0 in ``attention_mask``).
    ``prefill_chunk`` splits long-prompt prefill into fixed-size chunks
    (O(chunk·total) attention memory instead of O(P·total); the prompt
    is right-padded to a chunk multiple internally — same tokens out).
    Returns [batch, max_new_tokens] continuation ids, ``pad_token_id``
    after EOS.

    Prefill and decode are SEPARATE jitted dispatches (ROADMAP
    "Decode-phase split"): on instrumented runs the wrapper blocks on
    the prefill's first token and emits ``generate/causal_ttft_s``
    before timing the decode scan on its own
    (``generate/causal_decode_tokens_per_sec``) — so TTFT and steady
    tokens/sec no longer share one opaque span. Uninstrumented calls
    stay fully async: the decode dispatch chains on the prefill's
    device buffers with no host sync between them."""
    import time

    t0 = time.perf_counter()
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    prefill_chunk = int(prefill_chunk)
    if prefill_chunk < 0:
        raise ValueError(f"prefill_chunk must be >= 0, got {prefill_chunk}")
    if prefill_chunk and getattr(model.config, "num_experts", 0):
        raise ValueError(
            "prefill_chunk does not support MoE models (Mixtral): expert "
            "capacity is a function of the apply's sequence length, so "
            "chunked prefill could capacity-drop token->expert "
            "assignments the single-pass prefill never drops — the "
            "token-identical guarantee would silently break")
    if prefill_chunk >= input_ids.shape[1]:
        # chunking a prompt that fits one chunk would only PAD it up —
        # degenerate to the single-pass prefill
        prefill_chunk = 0
    if prefill_chunk:
        P = input_ids.shape[1]
        short = -P % prefill_chunk
        if short:
            # the appended slots are masked everywhere and never read
            # back, so any IN-VOCAB id works — and it must be in-vocab:
            # an out-of-range pad_token_id (tiny test configs) would
            # embed as NaN (jnp.take fill mode) and NaN survives the
            # additive mask through softmax
            pad_id = min(int(model.config.pad_token_id),
                         model.config.vocab_size - 1)
            input_ids = jnp.pad(input_ids, ((0, 0), (0, short)),
                                constant_values=pad_id)
            attention_mask = jnp.pad(attention_mask, ((0, 0), (0, short)))
    with obs.span("generate/causal_prefill",
                  {"prompt_len": int(input_ids.shape[1]),
                   "prefill_chunk": prefill_chunk} if obs.has_sink()
                  else None):
        first, cache, valid, finished, rng, n_real = _prefill_causal_jit(
            model, params, input_ids, attention_mask,
            int(max_new_tokens), float(temperature),
            jax.random.PRNGKey(seed), top_k=int(top_k),
            top_p=float(top_p), prefill_chunk=prefill_chunk)
        if obs.has_sink():
            jax.block_until_ready(first)
            obs.scalar("generate/causal_ttft_s",
                       time.perf_counter() - t0,
                       args={"prompt_len": int(input_ids.shape[1]),
                             "batch": int(input_ids.shape[0])})
    t_dec = time.perf_counter()
    decode_fn = _decode_causal_jit(jax.default_backend() != "cpu")
    with obs.span("generate/causal_decode"):
        out = decode_fn(model, params, first, cache=cache, valid=valid,
                        finished=finished, rng=rng, n_real=n_real,
                        max_new_tokens=int(max_new_tokens),
                        temperature=float(temperature), top_k=int(top_k),
                        top_p=float(top_p))
        if obs.has_sink():
            jax.block_until_ready(out)
            dt = max(time.perf_counter() - t_dec, 1e-9)
            obs.scalar("generate/causal_decode_tokens_per_sec",
                       out.shape[0] * out.shape[1] / dt)
    return _traced_decode("generate/causal", t0, out)


_NEG = jnp.float32(-1e9)


def _pool_merge(K, fin_scores, fin_tok, cand_scores, cand_tok):
    """Keep the best K of (current finished pool) ∪ (candidates) — the
    ONE finished-hypothesis merge both beam searches share."""
    all_scores = jnp.concatenate([fin_scores, cand_scores], axis=1)
    all_tok = jnp.concatenate([fin_tok, cand_tok], axis=1)
    new_scores, idx = lax.top_k(all_scores, K)
    return new_scores, jnp.take_along_axis(all_tok, idx[:, :, None],
                                           axis=1)


@functools.partial(jax.jit, static_argnames=("model", "num_beams",
                                             "max_new_tokens"))
def _beam_search_jit(model, params, input_ids, attention_mask, num_beams,
                     max_new_tokens, length_penalty):
    """Beam search with beams flattened into the batch dimension,
    HF-equivalent (``BeamSearchScorer`` semantics, the flax/t5x shape):

    per step one decoder call over [batch*beams], then the top ``2K`` of
    the ``K × vocab`` candidate grid. EOS candidates ranked within the
    top K are banked into a K-slot finished pool with their length
    penalty applied at add time (generated length = tokens before EOS +
    the start token, HF's ``process``); lower-ranked EOS candidates are
    dropped, exactly as HF's ``is_beam_token_worse_than_top_num_beams``.
    The best K non-EOS candidates continue as live beams (KV cache
    re-gathered by parent). A row stops banking once HF's ``is_done``
    criterion holds (worst pooled score >= best attainable at the
    current length). At the end, rows not done bank their live beams at
    generated length ``max_new_tokens`` (decoder start excluded, HF's
    ``finalize``); the best pooled hypothesis wins.
    """
    cfg = model.config
    B = input_ids.shape[0]
    K = num_beams
    V = cfg.vocab_size
    T = max_new_tokens

    encoder_hidden = model.apply({"params": params}, input_ids,
                                 attention_mask, deterministic=True,
                                 method=model.encode)
    # beams ride the batch dim: [B, ...] -> [B*K, ...]
    enc = jnp.repeat(encoder_hidden, K, axis=0)
    enc_mask = jnp.repeat(attention_mask, K, axis=0)
    cache = init_cache(model, params, enc, enc_mask, T)

    token = jnp.full((B * K, 1), cfg.decoder_start_token_id, jnp.int32)
    # beam 0 starts live, beams 1..K-1 at -inf so step 0 fans out from a
    # single root instead of K identical copies
    live_scores = jnp.tile(jnp.concatenate(
        [jnp.zeros((1,), jnp.float32),
         jnp.full((K - 1,), _NEG, jnp.float32)]), (B, 1))      # [B, K]
    live_tok = jnp.full((B, K, T), cfg.pad_token_id, jnp.int32)
    fin_scores = jnp.full((B, K), _NEG, jnp.float32)           # penalized
    fin_tok = jnp.full((B, K, T), cfg.pad_token_id, jnp.int32)
    done = jnp.zeros((B,), bool)

    pool_merge = functools.partial(_pool_merge, K)

    def step(carry, t):
        (token, cache, live_scores, live_tok, fin_scores, fin_tok,
         done) = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token, enc, enc_mask,
            decode=True, deterministic=True, mutable=["cache"],
            method=model.decode)
        logp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32)).reshape(B, K, V)
        forced_bos = getattr(cfg, "forced_bos_token_id", None)
        if forced_bos is not None:
            # mBART semantics: the first generated token is the forced
            # language id on every beam
            logp = jnp.where(t == 0, _force_token(logp, forced_bos), logp)
        cand = live_scores[:, :, None] + logp                  # [B, K, V]
        top2k, flat = lax.top_k(cand.reshape(B, K * V), 2 * K)
        parent = flat // V                                     # [B, 2K]
        tok2k = (flat % V).astype(jnp.int32)
        is_eos = tok2k == cfg.eos_token_id

        # candidate sequences: parent history + this token at position t
        seq2k = jnp.take_along_axis(live_tok, parent[:, :, None], axis=1)
        seq2k = lax.dynamic_update_index_in_dim(seq2k, tok2k, t, axis=2)

        # bank EOS candidates (HF hypothesis length: t generated tokens
        # before EOS + decoder_start = t + 1); done rows bank nothing,
        # and HF only banks EOS candidates ranked within the top K of
        # the sorted 2K list (BeamSearchScorer.process:
        # is_beam_token_worse_than_top_num_beams drops the rest)
        cur_len = (t + 1).astype(jnp.float32)
        rank_ok = jnp.arange(2 * K)[None, :] < K
        eos_norm = jnp.where(is_eos & rank_ok & ~done[:, None],
                             top2k / cur_len ** length_penalty, _NEG)
        fin_scores, fin_tok = pool_merge(fin_scores, fin_tok, eos_norm,
                                         seq2k)

        # best K non-EOS candidates continue as live beams
        live_cand = jnp.where(is_eos, _NEG, top2k)
        live_scores, keep = lax.top_k(live_cand, K)            # [B, K]
        emit = jnp.take_along_axis(tok2k, keep, axis=1)
        live_tok = jnp.take_along_axis(seq2k, keep[:, :, None], axis=1)
        parent_k = jnp.take_along_axis(parent, keep, axis=1)
        gather = (jnp.arange(B)[:, None] * K + parent_k).reshape(-1)
        cache = jax.tree.map(
            # k/v buffers are [B*K, ...]; cache_index is a shared scalar
            lambda x: x if x.ndim == 0 else jnp.take(x, gather, axis=0),
            mutated["cache"])

        # HF BeamHypotheses.is_done (early_stopping=False): the pool is
        # final once its worst member beats the best attainable score
        attainable = top2k[:, 0] / cur_len ** length_penalty
        done = done | (jnp.min(fin_scores, axis=1) >= attainable)
        return ((emit.reshape(B * K, 1), cache, live_scores, live_tok,
                 fin_scores, fin_tok, done), None)

    carry = (token, cache, live_scores, live_tok, fin_scores, fin_tok, done)
    (_, _, live_scores, live_tok, fin_scores, fin_tok, done), _ = lax.scan(
        step, carry, jnp.arange(T))

    # rows not done bank their live beams (HF finalize: generated_len =
    # final_tokens minus the decoder prompt = T, decoder_start excluded)
    live_norm = jnp.where(done[:, None], _NEG,
                          live_scores / jnp.float32(T) ** length_penalty)
    fin_scores, fin_tok = pool_merge(fin_scores, fin_tok, live_norm, live_tok)

    best = jnp.argmax(fin_scores, axis=1)                      # [B]
    return (jnp.take_along_axis(fin_tok, best[:, None, None], axis=1)[:, 0],
            jnp.take_along_axis(fin_scores, best[:, None], axis=1)[:, 0])


@functools.partial(jax.jit, static_argnames=("model", "num_beams",
                                             "max_new_tokens"))
def _beam_search_causal_jit(model, params, input_ids, attention_mask,
                            num_beams, max_new_tokens, length_penalty):
    """Beam search for DECODER-ONLY models (GPT-2 / Llama family), the
    same HF ``BeamSearchScorer`` semantics as ``_beam_search_jit``, with
    two structural differences:

    - there is no decoder-start token: the first candidate distribution
      comes from the PREFILL's last-real-token logits, so step 0 runs
      outside the scan (exactly ``generate_causal``'s shape). The
      prefill runs ONCE per input row at [B]; its cache leaves are then
      repeated across beams (the enc-dec variant's encode-once shape);
    - HF normalizes hypotheses by GENERATED length for decoder-only
      models too (``generated_len = cur_len - decoder_prompt_len`` in
      modern ``BeamSearchScorer``), so the ``t + 1`` convention is
      shared with the enc-dec scorer.

    Beams ride the batch dim ([B*K] rows); the KV cache — including the
    per-row ``cache_index`` vectors — is re-gathered by parent beam
    each step (only true scalars like the model-level position_index
    are exempt from the gather).
    """
    cfg = model.config
    B, P = input_ids.shape
    K, V, T = num_beams, cfg.vocab_size, max_new_tokens
    BK = B * K
    total = P + T

    _, variables = model.apply(
        {"params": params}, jnp.ones((B, total), jnp.int32), decode=True,
        deterministic=True, mutable=["cache"])
    cache = variables["cache"]
    valid_row = jnp.concatenate(
        [attention_mask, jnp.zeros((B, T), jnp.int32)], axis=1)
    pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1,
                   0).astype(jnp.int32)
    logits, mut = model.apply(
        {"params": params, "cache": cache}, input_ids, valid_row,
        position_ids=pos, decode=True, deterministic=True,
        mutable=["cache"])
    last_real = P - 1 - jnp.argmax(attention_mask[:, ::-1], axis=1)
    logp0 = jax.nn.log_softmax(jnp.take_along_axis(
        logits.astype(jnp.float32), last_real[:, None, None],
        axis=1)[:, 0])[:, None, :]                             # [B, 1, V]
    logp0 = jnp.broadcast_to(logp0, (B, K, V))
    # one prefill per row, K cache copies per row (encode-once shape)
    cache = jax.tree.map(
        lambda x: x if x.ndim == 0 else jnp.repeat(x, K, axis=0),
        mut["cache"])
    valid = jnp.repeat(valid_row, K, axis=0)                   # [BK, ...]
    n_real = jnp.repeat(jnp.sum(attention_mask, axis=1), K,
                        axis=0).astype(jnp.int32)

    live_scores = jnp.tile(jnp.concatenate(
        [jnp.zeros((1,), jnp.float32),
         jnp.full((K - 1,), _NEG, jnp.float32)]), (B, 1))      # [B, K]
    live_tok = jnp.full((B, K, T), cfg.pad_token_id, jnp.int32)
    fin_scores = jnp.full((B, K), _NEG, jnp.float32)           # penalized
    fin_tok = jnp.full((B, K, T), cfg.pad_token_id, jnp.int32)
    done = jnp.zeros((B,), bool)

    pool_merge = functools.partial(_pool_merge, K)

    def select(t, logp, cache, live_scores, live_tok, fin_scores,
               fin_tok, done):
        """One round of HF candidate selection/banking at emitted-token
        index ``t`` (generated hypothesis length = t + 1)."""
        cand = live_scores[:, :, None] + logp                  # [B, K, V]
        top2k, flat = lax.top_k(cand.reshape(B, K * V), 2 * K)
        parent = flat // V                                     # [B, 2K]
        tok2k = (flat % V).astype(jnp.int32)
        is_eos = tok2k == cfg.eos_token_id

        seq2k = jnp.take_along_axis(live_tok, parent[:, :, None], axis=1)
        seq2k = lax.dynamic_update_index_in_dim(seq2k, tok2k, t, axis=2)

        cur_len = (t + 1).astype(jnp.float32)
        rank_ok = jnp.arange(2 * K)[None, :] < K
        eos_norm = jnp.where(is_eos & rank_ok & ~done[:, None],
                             top2k / cur_len ** length_penalty, _NEG)
        fin_scores, fin_tok = pool_merge(fin_scores, fin_tok, eos_norm,
                                         seq2k)

        live_cand = jnp.where(is_eos, _NEG, top2k)
        live_scores, keep = lax.top_k(live_cand, K)            # [B, K]
        emit = jnp.take_along_axis(tok2k, keep, axis=1)
        live_tok = jnp.take_along_axis(seq2k, keep[:, :, None], axis=1)
        parent_k = jnp.take_along_axis(parent, keep, axis=1)
        gather = (jnp.arange(B)[:, None] * K + parent_k).reshape(-1)
        cache = jax.tree.map(
            # k/v buffers AND per-row cache_index are [BK, ...]; only
            # true scalars (model-level position_index) stay put
            lambda x: x if x.ndim == 0 else jnp.take(x, gather, axis=0),
            cache)

        attainable = top2k[:, 0] / cur_len ** length_penalty
        done = done | (jnp.min(fin_scores, axis=1) >= attainable)
        return (emit.reshape(BK, 1), cache, live_scores, live_tok,
                fin_scores, fin_tok, done)

    token, cache, live_scores, live_tok, fin_scores, fin_tok, done = \
        select(jnp.asarray(0), logp0, cache, live_scores, live_tok,
               fin_scores, fin_tok, done)

    def step(carry, t):
        (token, cache, valid, live_scores, live_tok, fin_scores, fin_tok,
         done) = carry
        # the token emitted at t-1 writes cache slot P + t - 1 and
        # carries logical position n_real + t - 1
        valid = lax.dynamic_update_slice(
            valid, jnp.ones((BK, 1), jnp.int32), (0, P + t - 1))
        logits, mut = model.apply(
            {"params": params, "cache": cache}, token, valid,
            position_ids=(n_real + t - 1)[:, None], decode=True,
            deterministic=True, mutable=["cache"])
        logp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32)).reshape(B, K, V)
        out = select(t, logp, mut["cache"], live_scores, live_tok,
                     fin_scores, fin_tok, done)
        return (out[0], out[1], valid) + out[2:], None

    carry = (token, cache, valid, live_scores, live_tok, fin_scores,
             fin_tok, done)
    (_, _, _, live_scores, live_tok, fin_scores, fin_tok, done), _ = \
        lax.scan(step, carry, jnp.arange(1, T))

    # HF finalize: rows not done bank live beams at generated length T
    live_norm = jnp.where(done[:, None], _NEG,
                          live_scores / jnp.float32(T) ** length_penalty)
    fin_scores, fin_tok = pool_merge(fin_scores, fin_tok, live_norm,
                                     live_tok)
    best = jnp.argmax(fin_scores, axis=1)                      # [B]
    return (jnp.take_along_axis(fin_tok, best[:, None, None], axis=1)[:, 0],
            jnp.take_along_axis(fin_scores, best[:, None], axis=1)[:, 0])


def beam_search_causal(model, params, input_ids, attention_mask=None,
                       num_beams: int = 4, max_new_tokens: int = 64,
                       length_penalty: float = 1.0,
                       return_scores: bool = False):
    """Beam-search decode for decoder-only models (GPT-2, dense Llama
    family). Returns [batch, max_new_tokens] continuation ids (padded
    after EOS); with ``return_scores``, also the winning hypotheses'
    length-penalized scores. MoE models are rejected for the same
    capacity reason as generate_speculative."""
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    if getattr(model.config, "num_experts", 0):
        raise ValueError(
            "beam_search_causal does not support MoE models (Mixtral): "
            "expert capacity depends on the apply's sequence length, so "
            "beam prefill vs single-token steps could route differently")
    import time

    t0 = time.perf_counter()
    with obs.span("generate/beam_causal_dispatch"):
        ids, scores = _beam_search_causal_jit(
            model, params, input_ids, attention_mask, int(num_beams),
            int(max_new_tokens), jnp.float32(length_penalty))
    _traced_decode("generate/beam_causal", t0, ids)
    return (ids, scores) if return_scores else ids


def beam_search_generate(model, params, input_ids, attention_mask=None,
                         num_beams: int = 4, max_new_tokens: int = 64,
                         length_penalty: float = 1.0,
                         return_scores: bool = False):
    """Beam-search decode. Returns [batch, max_new_tokens] ids (padded
    after EOS); with ``return_scores``, also the winning beams'
    length-penalized log-prob scores."""
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    import time

    t0 = time.perf_counter()
    with obs.span("generate/beam_dispatch"):
        ids, scores = _beam_search_jit(model, params, input_ids,
                                       attention_mask, int(num_beams),
                                       int(max_new_tokens),
                                       jnp.float32(length_penalty))
    _traced_decode("generate/beam", t0, ids)
    return (ids, scores) if return_scores else ids


# ---------------------------------------------------------------------------
# Speculative decoding (draft + verify)
# ---------------------------------------------------------------------------


def speculative_accept_greedy(t_pred, drafts):
    """GREEDY speculative acceptance for a batch of verify windows:
    ``t_pred`` [B, k+1] is the target's argmax prediction at every
    window position, ``drafts`` [B, k] the draft's proposals. Returns
    ``(n_acc, bonus)`` — the longest prefix of drafts matching the
    target's own choices, and the target's choice at the first miss
    (the whole window matching makes the bonus the target's k+1-th
    token). Emitting ``drafts[:n_acc] + [bonus]`` is therefore
    token-for-token the target's greedy continuation — the exactness
    contract both :func:`generate_speculative` and the serve engine's
    speculative decode path are gated on."""
    k = drafts.shape[1]
    match = (drafts == t_pred[:, :k]).astype(jnp.int32)
    n_acc = jnp.argmin(jnp.concatenate(
        [match, jnp.zeros((match.shape[0], 1), jnp.int32)], axis=1),
        axis=1)                                            # first miss
    bonus = jnp.take_along_axis(t_pred, n_acc[:, None], axis=1)[:, 0]
    return n_acc, bonus


def _speculative_accept(p, q, drafts, key):
    """Speculative SAMPLING acceptance for one row's verify window
    (Leviathan et al. 2023): draft token ``d_i ~ q_i`` is accepted with
    probability ``min(1, p_i(d_i)/q_i(d_i))``; at the first rejection
    the replacement is drawn from the residual ``max(p_i - q_i, 0)``
    (renormalized), and if every draft survives the bonus token is
    drawn from ``p_k``. The emitted marginal is EXACTLY the target
    distribution ``p`` at every position — the draft changes speed,
    never the distribution.

    ``p`` [k+1, V] target probs, ``q`` [k, V] draft probs, ``drafts``
    [k] the draft's sampled tokens. Returns (n_acc, next_token).
    """
    k = drafts.shape[0]
    key_u, key_res, key_bonus = jax.random.split(key, 3)
    u = jax.random.uniform(key_u, (k,))
    p_d = jnp.take_along_axis(p[:k], drafts[:, None], axis=1)[:, 0]
    q_d = jnp.take_along_axis(q, drafts[:, None], axis=1)[:, 0]
    # u < min(1, p/q)  ⟺  u*q < p  (division-free; q(d) > 0 a.s. since
    # d was sampled from q)
    accept = (u * q_d < p_d).astype(jnp.int32)
    n_acc = jnp.argmin(jnp.concatenate(
        [accept, jnp.zeros((1,), jnp.int32)]))                 # first reject
    res = jnp.maximum(p[n_acc] - q[jnp.minimum(n_acc, k - 1)], 0.0)
    # all-zero residual can only mean p == q at this position (then the
    # draft is never rejected); guard the renormalization anyway
    res = jnp.where(jnp.sum(res) > 0, res, p[n_acc])
    resampled = jax.random.categorical(key_res, jnp.log(res + 1e-30))
    bonus = jax.random.categorical(key_bonus, jnp.log(p[k] + 1e-30))
    nxt = jnp.where(n_acc == k, bonus, resampled)
    return n_acc, nxt.astype(jnp.int32)


def _spec_emit(drafts, n_acc, bonus, active, finished, pad, eos_id):
    """Assemble one speculative iteration's emitted window [B, k+1]:
    accepted draft prefix, the extra token at position n_acc, pads after
    the first EOS and for inactive rows. Returns (emit, n_new,
    finished) — shared by the decoder-only and seq2seq loops."""
    B, k = drafts.shape
    idx = jnp.arange(k + 1)[None]                              # [1, k+1]
    emit = jnp.where(idx < n_acc[:, None],
                     jnp.concatenate([drafts, drafts[:, -1:]], axis=1),
                     pad)
    emit = jnp.where(idx == n_acc[:, None], bonus[:, None], emit)
    n_new = jnp.where(active, n_acc + 1, 0)                    # [B]
    is_eos = (emit == eos_id) & (idx < n_new[:, None])
    after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1) -
             is_eos.astype(jnp.int32)) > 0
    emit = jnp.where(after | ~active[:, None], pad, emit)
    return emit, n_new, finished | jnp.any(is_eos, axis=1)


def _rewind_cache(cache, n):
    """Decode cache with every write index set to ``n`` (traced scalar).

    Stale K/V entries at slots >= n stay in the buffers, but the decode
    step mask is built from SLOT indices (``key_pos <= qry_pos``), so
    queries issued after the rewind can never attend to them, and the
    next writes overwrite them in place — rewinding is O(1), no buffer
    copy."""
    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name not in ("cache_index", "position_index"):
            return leaf
        arr = jnp.asarray(n, leaf.dtype)
        if arr.ndim > jnp.ndim(leaf):
            # per-row n onto a scalar leaf (the model-level
            # position_index, unused when explicit position_ids are
            # passed — which every rewinding caller does)
            arr = jnp.max(arr)
        return jnp.broadcast_to(arr, jnp.shape(leaf))

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, static_argnames=("model", "draft_model",
                                             "max_new_tokens",
                                             "speculate_k", "temperature",
                                             "top_k", "top_p"))
def _speculative_jit(model, params, draft_model, draft_params, input_ids,
                     prompt_mask, rng, max_new_tokens, speculate_k,
                     temperature, top_k=0, top_p=0.0):
    """Speculative decode, exact target semantics — greedy prefix
    matching at ``temperature=0``, Leviathan rejection SAMPLING at
    ``temperature>0`` (docstring of :func:`generate_speculative`). All
    shapes static: the draft scan is always ``k`` steps, the verify
    pass always ``k+1`` tokens, and the while_loop carries a fixed-size
    output buffer with ``k+1`` slack so the per-iteration window write
    never clamps.

    ``prompt_mask`` supports RIGHT-padded prompts so callers can bucket
    prompt lengths (one compilation per bucket, not per length): slot
    indices (cache writes) run over the padded width, logical positions
    (RoPE/wpe) come from the mask cumsum, and a ``valid`` kv-buffer mask
    keeps pad and stale slots invisible to attention."""
    cfg = model.config
    k = speculate_k
    B, P = input_ids.shape
    T = max_new_tokens
    pad = jnp.int32(cfg.pad_token_id)
    total = P + T + k + 1                   # cache room incl. overshoot

    def alloc(m, p):
        _, v = m.apply({"params": p}, jnp.ones((B, total), jnp.int32),
                       decode=True, deterministic=True, mutable=["cache"])
        return v["cache"]

    t_cache, d_cache = alloc(model, params), alloc(draft_model, draft_params)

    def row_put(row, upd, c):
        # row [total], upd [w], c scalar — one row's buffer write
        return lax.dynamic_update_slice(row, upd, (c,))

    # kv-buffer validity over all slots; logical prefill positions
    valid = jnp.concatenate(
        [prompt_mask, jnp.zeros((B, T + k + 1), jnp.int32)], axis=1)
    n_real = jnp.sum(prompt_mask, axis=1).astype(jnp.int32)    # [B]
    pos = jnp.clip(jnp.cumsum(prompt_mask, axis=1) - 1, 0).astype(jnp.int32)

    logits, mut = model.apply(
        {"params": params, "cache": t_cache}, input_ids, valid,
        position_ids=pos, decode=True, deterministic=True,
        mutable=["cache"])
    t_cache = mut["cache"]
    _, mut = draft_model.apply(
        {"params": draft_params, "cache": d_cache}, input_ids, valid,
        position_ids=pos, decode=True, deterministic=True,
        mutable=["cache"])
    d_cache = mut["cache"]

    last_logits = jnp.take_along_axis(
        logits.astype(jnp.float32), (n_real - 1)[:, None, None],
        axis=1)[:, 0]                                          # [B, V]
    def warp(lg):
        """Warped logits — applied identically to the target's and the
        draft's distributions, so the rejection acceptance operates on
        exactly the warped p and q (the theorem holds for any p; q only
        needs support on its own samples). Shares ``_warp_logits`` with
        plain sampling so the first emitted token (drawn via
        ``_sample_next``) follows the same distribution as the rest."""
        return _warp_logits(lg, temperature, top_k, top_p)

    rng, first_key = jax.random.split(rng)
    first, _ = _sample_next(last_logits, temperature, top_k, top_p,
                            first_key)
    out = jnp.full((B, T + k + 1), pad, jnp.int32)
    out = out.at[:, 0].set(first)
    state = (out, jnp.ones((B,), jnp.int32),                   # n_out
             jnp.full((B,), P, jnp.int32),                     # n_ctx: slots
             n_real,                                           # n_pos: logical
             first, t_cache, d_cache, valid,
             first == cfg.eos_token_id,                        # finished [B]
             jnp.zeros((), jnp.int32),                         # iterations
             jnp.zeros((), jnp.int32),                         # active windows
             rng)

    def cond(state):
        n_out, finished = state[1], state[8]
        return jnp.any((n_out < T) & ~finished)

    def body(state):
        (out, n_out, n_ctx, n_pos, last, t_cache, d_cache, valid,
         finished, iters, act_win, rng) = state
        active = (n_out < T) & ~finished                       # [B]
        rng, draft_key, accept_key = jax.random.split(rng, 3)

        # 1. draft k candidates autoregressively — greedy at
        #    temperature 0, sampled from the draft's (tempered)
        #    distribution otherwise, recording q for the acceptance
        #    test. (Its cache copy is discarded — step 3 replays the
        #    verified window instead.)
        def dstep(carry, t):
            tok, dc, vld = carry
            vld = jax.vmap(row_put)(vld, jnp.ones((B, 1), jnp.int32),
                                    n_ctx + t)
            lg, m = draft_model.apply(
                {"params": draft_params, "cache": dc}, tok[:, None], vld,
                position_ids=(n_pos + t)[:, None], decode=True,
                deterministic=True, mutable=["cache"])
            lg = lg[:, -1, :].astype(jnp.float32)
            if temperature == 0.0:
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                qp = jnp.zeros_like(lg)                        # unused
            else:
                warped = warp(lg)
                qp = jax.nn.softmax(warped, axis=-1)
                nxt = jax.random.categorical(
                    jax.random.fold_in(draft_key, t),
                    warped).astype(jnp.int32)
            return (nxt, m["cache"], vld), (nxt, qp)

        (_, _, _), (drafts, q_probs) = lax.scan(
            dstep, (last, d_cache, valid), jnp.arange(k))
        drafts = drafts.T                                      # [B, k]
        q_probs = jnp.swapaxes(q_probs, 0, 1)                  # [B, k, V]

        # 2. ONE target pass over [last, d_0..d_{k-1}] verifies all k
        #    candidates per row at the cost of a single decode step's
        #    HBM traffic (weights dominate at decode batch sizes)
        verify_in = jnp.concatenate([last[:, None], drafts], axis=1)
        vwin = jax.vmap(row_put)(valid, jnp.ones((B, k + 1), jnp.int32),
                                 n_ctx)
        vpos = n_pos[:, None] + jnp.arange(k + 1, dtype=jnp.int32)[None]
        lg, mut = model.apply(
            {"params": params, "cache": t_cache}, verify_in, vwin,
            position_ids=vpos, decode=True, deterministic=True,
            mutable=["cache"])

        if temperature == 0.0:
            # greedy: longest matching prefix per row, then the
            # target's own argmax token as bonus — token-exact vs
            # generate_causal
            t_pred = jnp.argmax(lg.astype(jnp.float32),
                                -1).astype(jnp.int32)          # [B, k+1]
            n_acc, bonus = speculative_accept_greedy(t_pred, drafts)
        else:
            # sampling: Leviathan rejection acceptance — the emitted
            # marginal is exactly the target's warped distribution
            p_probs = jax.nn.softmax(warp(lg.astype(jnp.float32)),
                                     axis=-1)
            row_keys = jax.vmap(
                lambda b: jax.random.fold_in(accept_key, b))(
                jnp.arange(B))
            n_acc, bonus = jax.vmap(_speculative_accept)(
                p_probs, q_probs, drafts, row_keys)
        # emit assembly + EOS padding shared with the seq2seq loop;
        # inactive rows emit only pads (their slots past n_out were
        # never written, so the write below is a value no-op for them)
        emit, n_new, finished = _spec_emit(drafts, n_acc, bonus, active,
                                           finished, pad,
                                           cfg.eos_token_id)

        out = jax.vmap(row_put)(out, emit, jnp.minimum(n_out, T))
        new_ctx = n_ctx + n_new
        # commit validity: accepted slots become 1, rejected stay 0
        valid = jax.vmap(row_put)(
            valid,
            (jnp.arange(k + 1)[None] < n_new[:, None]).astype(jnp.int32),
            n_ctx)

        # 3. commit caches: the target wrote the whole window — rewind
        #    its per-row indices to the accepted lengths; the draft's
        #    scan copy is replaced by ONE replay of the same window
        #    (idempotent rewrites + the slot its scan never reached),
        #    then rewound
        t_cache = _rewind_cache(mut["cache"], new_ctx)
        _, mdr = draft_model.apply(
            {"params": draft_params, "cache": d_cache}, verify_in, vwin,
            position_ids=vpos, decode=True, deterministic=True,
            mutable=["cache"])
        d_cache = _rewind_cache(mdr["cache"], new_ctx)

        last = jnp.where(active, bonus, last)
        return (out, n_out + n_new, new_ctx, n_pos + n_new, last,
                t_cache, d_cache, valid, finished, iters + 1,
                act_win + jnp.sum(active.astype(jnp.int32)), rng)

    state = lax.while_loop(cond, body, state)
    # (tokens, raw per-row counts incl. prefill, iterations, active
    # row×window pairs — the denominator for acceptance accounting)
    return state[0][:, :T], state[1], state[9], state[10]


def generate_speculative(model, params, draft_model, draft_params,
                         input_ids, attention_mask=None,
                         max_new_tokens: int = 64,
                         speculate_k: int = 4,
                         temperature: float = 0.0, top_k: int = 0,
                         top_p: float = 0.0, seed: int = 0,
                         return_stats: bool = False):
    """Speculative decoding: a small draft model proposes
    ``speculate_k`` tokens autoregressively, the target model scores the
    whole window in ONE decode pass, and a prefix is accepted plus one
    extra token from the target.

    At ``temperature=0`` (default) acceptance is the longest prefix
    matching the target's greedy choices — output is EXACTLY
    ``generate_causal``'s greedy continuation, token for token. At
    ``temperature>0`` it is speculative SAMPLING (Leviathan et al.
    rejection acceptance, :func:`_speculative_accept`): each emitted
    token's marginal is exactly the target's WARPED distribution
    (temperature, then optional ``top_k``/``top_p`` filtering — applied
    identically to the draft) — distribution-exact rather than
    bitwise-exact, since the rng consumption pattern differs from plain
    sampling. ``top_k``/``top_p`` require ``temperature > 0`` (greedy
    is argmax, which filtering cannot change). Either way the draft
    changes speed, never semantics.

    TPU-first shape discipline: fixed-k draft scan, fixed (k+1)-token
    verify, ``lax.while_loop`` over a static output buffer — one
    compilation regardless of acceptance pattern. Decode at small batch
    is HBM-bound on the target's weights, so verifying k+1 tokens costs
    about the same as one, and acceptance rate × (k+1) is the speedup.

    Batched: rows accept different numbers of tokens per iteration and
    advance independently — the KV caches keep PER-ROW write indices,
    and each row's stale slots hide behind the slot-indexed step mask.
    Prompts may be RIGHT-padded with ``attention_mask`` marking real
    tokens — bucket prompt widths and each bucket compiles once instead
    of every distinct length retracing the two-model while_loop. Works
    with any decoder following the slot-indexed KV-cache convention
    (GPT-2, the dense Llama family; MoE/Mixtral is rejected — expert
    capacity depends on the apply's sequence length, so verify windows
    could drop assignments single-token steps never drop).
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if input_ids.ndim == 1:
        input_ids = input_ids[None]
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    mask_np = np.asarray(attention_mask)
    if (mask_np[:, :-1] < mask_np[:, 1:]).any():
        raise ValueError(
            "generate_speculative requires RIGHT-padded prompts "
            "(attention_mask must be non-increasing per row): real "
            "tokens first, pads after")
    if (mask_np.sum(axis=1) < 1).any():
        raise ValueError("every prompt row needs at least one real token")
    if model.config.vocab_size != draft_model.config.vocab_size:
        raise ValueError(
            "draft and target must share a vocabulary (got "
            f"{draft_model.config.vocab_size} vs "
            f"{model.config.vocab_size})")
    if (getattr(model.config, "num_experts", 0)
            or getattr(draft_model.config, "num_experts", 0)):
        raise ValueError(
            "generate_speculative does not support MoE models (Mixtral):"
            " expert capacity is a function of the apply's sequence "
            "length, so the (k+1)-token verify window could capacity-"
            "drop token->expert assignments that generate_causal's "
            "single-token steps never drop — the greedy-exact guarantee "
            "would silently break")
    if speculate_k < 1:
        raise ValueError("speculate_k must be >= 1")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if (top_k or top_p) and temperature == 0.0:
        raise ValueError(
            "top_k/top_p warping requires temperature > 0 (greedy "
            "speculation is argmax, which filtering cannot change)")
    tokens, n_out, iters, act_win = _speculative_jit(
        model, params, draft_model, draft_params, input_ids,
        jnp.asarray(attention_mask, jnp.int32),
        jax.random.PRNGKey(int(seed)), int(max_new_tokens),
        int(speculate_k), float(temperature), top_k=int(top_k),
        top_p=float(top_p))
    if not return_stats:
        return tokens
    produced = np.asarray(n_out)
    # the first token comes from the prefill, not a verify window, so
    # window-accepted tokens per row = n_out - 1 (RAW, not capped at
    # max_new_tokens — the final window may overshoot the cap). Each
    # ACTIVE (row, window) pair yields 1..k+1 tokens, so dividing by
    # the active-pair count keeps the metric in that range even when
    # rows finish at different times.
    per_window = float(produced.sum() - len(produced)) / max(int(act_win), 1)
    return tokens, {"iterations": int(iters),
                    "tokens_generated":
                        np.minimum(produced, int(max_new_tokens)).tolist(),
                    "accepted_per_window": round(per_window, 3),
                    "window_ceiling": int(speculate_k) + 1}


def self_draft(model, params, num_layers: int):
    """(draft_model, draft_params): a layer-skip draft assembled from the
    target's own FIRST ``num_layers`` blocks, sharing its embeddings,
    final norm, and LM head — self-speculative decoding with no second
    checkpoint (LayerSkip/early-exit lineage). Acceptance depends on how
    much the skipped top layers refine token choices, but
    :func:`generate_speculative` guarantees the output is still exactly
    the target's greedy continuation regardless.

    Works for the decoder families whose per-layer params live under
    ``backbone/layers_{i}`` (Llama family) or ``backbone/h_{i}`` (GPT-2).
    """
    import dataclasses

    cfg = model.config
    if not 1 <= num_layers < cfg.num_layers:
        raise ValueError(
            f"self_draft num_layers must be in [1, {cfg.num_layers - 1}] "
            f"(target has {cfg.num_layers}), got {num_layers}")
    if getattr(cfg, "pipeline_stages", 0):
        raise ValueError("self_draft needs the dense stack "
                         "(pipeline_stages=0): decode reloads dense")
    draft_cfg = dataclasses.replace(cfg, num_layers=num_layers)
    draft_model = type(model)(draft_cfg)

    def keep(key):
        for prefix in ("layers_", "h_"):
            if key.startswith(prefix):
                return int(key[len(prefix):]) < num_layers
        return True

    backbone = params["backbone"]
    kept = {key: val for key, val in backbone.items() if keep(key)}
    if len(kept) == len(backbone):
        raise ValueError(
            "self_draft found no per-layer blocks to truncate (expected "
            "backbone/layers_{i} or backbone/h_{i} params)")
    return draft_model, {**params, "backbone": kept}


@functools.partial(jax.jit, static_argnames=("model", "draft_model",
                                             "max_new_tokens",
                                             "speculate_k", "temperature"))
def _speculative_seq2seq_jit(model, params, draft_model, draft_params,
                             input_ids, attention_mask, rng,
                             max_new_tokens, speculate_k, temperature):
    """Speculative decode for encoder-decoder models: each model encodes
    the source ONCE, then the decoder runs the same draft-window /
    one-pass-verify / per-row-rewind loop as the decoder-only variant.
    Structurally simpler than the causal loop — there is no prompt in
    the decoder (slot 0 is decoder_start, so slots == logical positions
    and no validity mask rides along); T5's relative-position bias
    follows the per-row cache indices automatically."""
    cfg = model.config
    k = speculate_k
    B = input_ids.shape[0]
    T = max_new_tokens
    pad = jnp.int32(cfg.pad_token_id)
    total = T + k + 2                       # decoder_start + overshoot

    enc_t = model.apply({"params": params}, input_ids, attention_mask,
                        deterministic=True, method=model.encode)
    enc_d = draft_model.apply({"params": draft_params}, input_ids,
                              attention_mask, deterministic=True,
                              method=draft_model.encode)
    t_cache = init_cache(model, params, enc_t, attention_mask, total)
    d_cache = init_cache(draft_model, draft_params, enc_d, attention_mask,
                         total)

    def t_step(cache, tokens):
        lg, mut = model.apply(
            {"params": params, "cache": cache}, tokens, enc_t,
            attention_mask, decode=True, deterministic=True,
            mutable=["cache"], method=model.decode)
        return lg.astype(jnp.float32), mut["cache"]

    def d_step(cache, tokens):
        lg, mut = draft_model.apply(
            {"params": draft_params, "cache": cache}, tokens, enc_d,
            attention_mask, decode=True, deterministic=True,
            mutable=["cache"], method=draft_model.decode)
        return lg.astype(jnp.float32), mut["cache"]

    start = jnp.full((B, 1), cfg.decoder_start_token_id, jnp.int32)
    lg, t_cache = t_step(t_cache, start)
    _, d_cache = d_step(d_cache, start)
    rng, first_key = jax.random.split(rng)
    first, _ = _sample_next(lg[:, -1], temperature, 0, 0.0, first_key)

    out = jnp.full((B, T + k + 1), pad, jnp.int32)
    out = out.at[:, 0].set(first)
    # n_out doubles as the slot count: slot 0 is decoder_start, every
    # accepted token occupies the next slot — unlike the causal loop
    # there is no prompt, so output index == cache depth always
    state = (out, jnp.ones((B,), jnp.int32),                   # n_out
             first, t_cache, d_cache,
             first == cfg.eos_token_id,                        # finished [B]
             jnp.zeros((), jnp.int32),                         # iterations
             jnp.zeros((), jnp.int32),                         # active windows
             rng)

    def cond(state):
        n_out, finished = state[1], state[5]
        return jnp.any((n_out < T) & ~finished)

    def body(state):
        (out, n_out, last, t_cache, d_cache, finished, iters,
         act_win, rng) = state
        active = (n_out < T) & ~finished
        rng, draft_key, accept_key = jax.random.split(rng, 3)

        def dstep(carry, t):
            tok, dc = carry
            lg, dc = d_step(dc, tok[:, None])
            lg = lg[:, -1, :]
            if temperature == 0.0:
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                qp = jnp.zeros_like(lg)
            else:
                warped = lg / temperature
                qp = jax.nn.softmax(warped, axis=-1)
                nxt = jax.random.categorical(
                    jax.random.fold_in(draft_key, t),
                    warped).astype(jnp.int32)
            return (nxt, dc), (nxt, qp)

        (_, _), (drafts, q_probs) = lax.scan(dstep, (last, d_cache),
                                             jnp.arange(k))
        drafts = drafts.T                                      # [B, k]
        q_probs = jnp.swapaxes(q_probs, 0, 1)                  # [B, k, V]

        verify_in = jnp.concatenate([last[:, None], drafts], axis=1)
        lg, t_cache2 = t_step(t_cache, verify_in)
        if temperature == 0.0:
            t_pred = jnp.argmax(lg, -1).astype(jnp.int32)      # [B, k+1]
            n_acc, bonus = speculative_accept_greedy(t_pred, drafts)
        else:
            p_probs = jax.nn.softmax(lg / temperature, axis=-1)
            row_keys = jax.vmap(
                lambda b: jax.random.fold_in(accept_key, b))(
                jnp.arange(B))
            n_acc, bonus = jax.vmap(_speculative_accept)(
                p_probs, q_probs, drafts, row_keys)

        emit, n_new, finished = _spec_emit(drafts, n_acc, bonus, active,
                                           finished, pad,
                                           cfg.eos_token_id)
        out = jax.vmap(lambda row, upd, c: lax.dynamic_update_slice(
            row, upd, (c,)))(out, emit, jnp.minimum(n_out, T))
        t_cache = _rewind_cache(t_cache2, n_out + n_new)
        _, mdr = d_step(d_cache, verify_in)
        d_cache = _rewind_cache(mdr, n_out + n_new)
        last = jnp.where(active, bonus, last)
        return (out, n_out + n_new, last, t_cache, d_cache,
                finished, iters + 1,
                act_win + jnp.sum(active.astype(jnp.int32)), rng)

    state = lax.while_loop(cond, body, state)
    return state[0][:, :T], state[1], state[6], state[7]


def generate_speculative_seq2seq(model, params, draft_model, draft_params,
                                 input_ids, attention_mask=None,
                                 max_new_tokens: int = 64,
                                 speculate_k: int = 4,
                                 temperature: float = 0.0, seed: int = 0,
                                 return_stats: bool = False):
    """Speculative decoding for encoder-decoder models (T5 family): the
    draft encodes the source with its own encoder, proposes
    ``speculate_k`` decoder tokens, and the target verifies the window
    in one decoder pass. ``temperature=0`` is token-exact vs
    :func:`generate` greedy; ``temperature>0`` is distribution-exact
    rejection sampling (same acceptance core as the decoder-only
    variant).

    T5-family only: its decode-side positions (the relative-position
    bias) derive entirely from the per-row cache indices, so rows can
    rewind independently. BART/mBART track an absolute decoder position
    in a shared scalar, which per-row rewinds would corrupt — rejected
    loudly (as is mBART's forced_bos, which the verify window does not
    thread).
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if input_ids.ndim == 1:
        input_ids = input_ids[None]
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    for m, tag in ((model, "target"), (draft_model, "draft")):
        name = type(m.config).__name__
        if name != "T5Config":
            raise ValueError(
                f"generate_speculative_seq2seq supports the T5 family "
                f"only ({tag} has {name}): BART's absolute decoder "
                "positions live in a shared scalar that per-row cache "
                "rewinds would corrupt")
        if getattr(m.config, "attention_impl", "xla") == "ring":
            raise ValueError(
                f"generate_speculative_seq2seq cannot run the {tag} "
                "with attention_impl='ring': the ring decode path "
                "collapses per-row cache offsets to their max, which "
                "would mis-bias rows behind the deepest one")
    if getattr(model.config, "forced_bos_token_id", None) is not None:
        raise ValueError("forced_bos_token_id is not supported under "
                         "speculative decoding")
    if model.config.vocab_size != draft_model.config.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if speculate_k < 1:
        raise ValueError("speculate_k must be >= 1")
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    tokens, n_out, iters, act_win = _speculative_seq2seq_jit(
        model, params, draft_model, draft_params, input_ids,
        attention_mask, jax.random.PRNGKey(int(seed)),
        int(max_new_tokens), int(speculate_k), float(temperature))
    if not return_stats:
        return tokens
    produced = np.asarray(n_out)
    per_window = float(produced.sum() - len(produced)) / max(int(act_win), 1)
    return tokens, {"iterations": int(iters),
                    "tokens_generated":
                        np.minimum(produced, int(max_new_tokens)).tolist(),
                    "accepted_per_window": round(per_window, 3),
                    "window_ceiling": int(speculate_k) + 1}
