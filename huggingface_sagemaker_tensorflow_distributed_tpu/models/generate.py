"""Autoregressive generation for encoder-decoder models.

TPU-native replacement for the ``model.generate`` capability the
reference's model surface carries via HF ``transformers`` (SURVEY.md D7;
the reference itself only fine-tunes, reference ``scripts/train.py:145``,
but its model objects expose generation — parity requires it for the
seq2seq task family).

Design: the encoder runs once; the decoder runs inside a single jitted
``lax.scan`` over time steps with an incremental KV cache (created on a
zero-length init pass, updated per step with ``dynamic_update_slice`` —
see ``T5Attention``). Static shapes throughout: output length is fixed at
``max_new_tokens`` and finished sequences emit ``pad_token_id``, so one
compilation serves every batch. Greedy, temperature sampling, and beam
search (beams flattened into the batch dim so every step stays one
batched decoder call — the TPU-friendly layout).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def init_cache(model, params, encoder_hidden, encoder_attention_mask,
               max_decoder_length: int):
    """Create the zero-filled decoder KV cache for ``max_decoder_length``.

    Runs the decoder once over a dummy full-length input with an
    uninitialized ``"cache"`` collection: each attention module allocates
    its buffers at full k/v shape but performs no writes (cache_index
    stays 0), so the returned cache is ready for step-wise decode.
    """
    batch = encoder_hidden.shape[0]
    dummy = jnp.ones((batch, max_decoder_length), jnp.int32)
    _, variables = model.apply(
        {"params": params}, dummy, encoder_hidden, encoder_attention_mask,
        decode=True, deterministic=True, mutable=["cache"],
        method=model.decode)
    return variables["cache"]


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "temperature"))
def _generate_jit(model, params, input_ids, attention_mask, max_new_tokens,
                  temperature, rng):
    cfg = model.config
    encoder_hidden = model.apply({"params": params}, input_ids,
                                 attention_mask, deterministic=True,
                                 method=model.encode)
    cache = init_cache(model, params, encoder_hidden, attention_mask,
                       max_new_tokens)
    batch = input_ids.shape[0]
    start = jnp.full((batch, 1), cfg.decoder_start_token_id, jnp.int32)

    def step(carry, _):
        token, cache, finished, rng = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token, encoder_hidden,
            attention_mask, decode=True, deterministic=True,
            mutable=["cache"], method=model.decode)
        logits = logits[:, -1, :].astype(jnp.float32)
        if temperature == 0.0:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
            nxt = nxt.astype(jnp.int32)
        nxt = jnp.where(finished, jnp.int32(cfg.pad_token_id), nxt)
        finished = finished | (nxt == cfg.eos_token_id)
        return (nxt[:, None], mutated["cache"], finished, rng), nxt

    carry = (start, cache, jnp.zeros((batch,), bool), rng)
    _, tokens = lax.scan(step, carry, None, length=max_new_tokens)
    return tokens.T  # [batch, max_new_tokens]


def generate(model, params, input_ids, attention_mask=None,
             max_new_tokens: int = 64, temperature: float = 0.0,
             seed: int = 0) -> jax.Array:
    """Generate output ids for a batch of source sequences.

    ``temperature=0`` → greedy; otherwise softmax sampling at that
    temperature. Returns [batch, max_new_tokens] ids, padded with
    ``pad_token_id`` after EOS.
    """
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    return _generate_jit(model, params, input_ids, attention_mask,
                         int(max_new_tokens), float(temperature),
                         jax.random.PRNGKey(seed))


_NEG = jnp.float32(-1e9)


@functools.partial(jax.jit, static_argnames=("model", "num_beams",
                                             "max_new_tokens"))
def _beam_search_jit(model, params, input_ids, attention_mask, num_beams,
                     max_new_tokens, length_penalty):
    """Beam search with beams flattened into the batch dimension.

    Per step: one decoder call over [batch*beams], log-probs folded into
    running beam scores, top-``num_beams`` of the ``beams × vocab``
    candidate grid kept, KV cache re-gathered by winning beam. A beam
    that emits EOS freezes: its only continuation is ``pad`` at zero
    additional log-prob, so its score stays fixed while live beams keep
    competing (the frozen-beam formulation — exact for the winning beam,
    no separate finished pool). Final pick per batch row maximizes
    ``score / length**length_penalty`` (HF semantics: penalty 1.0 =
    length-normalized, 0.0 = raw sum log-prob).
    """
    cfg = model.config
    B = input_ids.shape[0]
    K = num_beams
    V = cfg.vocab_size

    encoder_hidden = model.apply({"params": params}, input_ids,
                                 attention_mask, deterministic=True,
                                 method=model.encode)
    # beams ride the batch dim: [B, ...] -> [B*K, ...]
    enc = jnp.repeat(encoder_hidden, K, axis=0)
    enc_mask = jnp.repeat(attention_mask, K, axis=0)
    cache = init_cache(model, params, enc, enc_mask, max_new_tokens)

    token = jnp.full((B * K, 1), cfg.decoder_start_token_id, jnp.int32)
    # beam 0 starts live, beams 1..K-1 at -inf so step 0 fans out from a
    # single root instead of K identical copies
    scores = jnp.tile(jnp.concatenate(
        [jnp.zeros((1,), jnp.float32),
         jnp.full((K - 1,), _NEG, jnp.float32)]), (B, 1))      # [B, K]
    finished = jnp.zeros((B, K), bool)
    lengths = jnp.zeros((B, K), jnp.int32)
    tokens = jnp.full((B, K, max_new_tokens), cfg.pad_token_id, jnp.int32)

    def step(carry, t):
        token, cache, scores, finished, lengths, tokens = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token, enc, enc_mask,
            decode=True, deterministic=True, mutable=["cache"],
            method=model.decode)
        logp = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32)).reshape(B, K, V)
        # frozen beams: pad continues at zero cost, everything else -inf
        frozen = jnp.full((V,), _NEG).at[cfg.pad_token_id].set(0.0)
        logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
        cand = scores[:, :, None] + logp                       # [B, K, V]
        top_scores, flat_idx = lax.top_k(cand.reshape(B, K * V), K)
        beam_idx = flat_idx // V                               # [B, K]
        next_tok = (flat_idx % V).astype(jnp.int32)

        # re-gather every per-beam state by winning parent beam
        gather = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
        cache = jax.tree.map(
            # k/v buffers are [B*K, ...]; cache_index is a shared scalar
            lambda x: x if x.ndim == 0 else jnp.take(x, gather, axis=0),
            mutated["cache"])
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        tokens = jnp.take_along_axis(tokens, beam_idx[:, :, None], axis=1)

        emit = jnp.where(finished, jnp.int32(cfg.pad_token_id), next_tok)
        tokens = lax.dynamic_update_index_in_dim(tokens, emit, t, axis=2)
        lengths = lengths + (~finished).astype(jnp.int32)
        finished = finished | (emit == cfg.eos_token_id)
        return ((emit.reshape(B * K, 1), cache, top_scores, finished,
                 lengths, tokens), None)

    carry = (token, cache, scores, finished, lengths, tokens)
    (_, _, scores, finished, lengths, tokens), _ = lax.scan(
        step, carry, jnp.arange(max_new_tokens))

    norm = scores / jnp.maximum(lengths, 1).astype(
        jnp.float32) ** length_penalty
    best = jnp.argmax(norm, axis=1)                            # [B]
    return jnp.take_along_axis(
        tokens, best[:, None, None], axis=1)[:, 0], jnp.take_along_axis(
        norm, best[:, None], axis=1)[:, 0]


def beam_search_generate(model, params, input_ids, attention_mask=None,
                         num_beams: int = 4, max_new_tokens: int = 64,
                         length_penalty: float = 1.0,
                         return_scores: bool = False):
    """Beam-search decode. Returns [batch, max_new_tokens] ids (padded
    after EOS); with ``return_scores``, also the winning beams'
    length-penalized log-prob scores."""
    input_ids = jnp.asarray(input_ids, jnp.int32)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    attention_mask = jnp.asarray(attention_mask, jnp.int32)
    ids, scores = _beam_search_jit(model, params, input_ids, attention_mask,
                                   int(num_beams), int(max_new_tokens),
                                   jnp.float32(length_penalty))
    return (ids, scores) if return_scores else ids
