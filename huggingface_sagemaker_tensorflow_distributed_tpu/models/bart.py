"""BART: post-LN encoder-decoder LM (summarization's workhorse).

Extends the seq2seq surface beyond T5 (SURVEY.md D7 — the reference's
HF ecosystem carries BART via the same Auto* machinery as BERT). HF
``BartForConditionalGeneration`` parity:

- shared token embedding (optionally scaled by sqrt(d_model)) + LEARNED
  positions with BART's legacy offset of 2 (``embed_positions`` has
  ``max_position_embeddings + 2`` rows), per-stack ``layernorm_embedding``;
- post-LN blocks: residual → dropout → add → LayerNorm, with separate
  self-attn / cross-attn / FFN norms; activation dropout inside the FFN;
- attention with biased q/k/v/out projections, q pre-scaled by
  ``head_dim**-0.5``;
- LM head tied to the shared embedding. HF's ``final_logits_bias``
  buffer is NOT modeled: it is zeros in every published checkpoint (HF
  only resizes it when growing the vocab), and both load and export
  skip it.

``encode``/``decode`` expose the same apply-method interface as T5, so
``models/generate.py`` (greedy / sampling / beam search, incremental KV
cache) drives BART unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from huggingface_sagemaker_tensorflow_distributed_tpu.models.layers import (
    ACT2FN,
    remat_policy,
)
from huggingface_sagemaker_tensorflow_distributed_tpu.ops.attention import xla_attention

NEG_INF = -1e9
_POS_OFFSET = 2   # BartLearnedPositionalEmbedding's legacy offset


@dataclass(frozen=True)
class BartConfig:
    vocab_size: int = 50265
    d_model: int = 768
    encoder_layers: int = 6
    decoder_layers: int = 6
    encoder_attention_heads: int = 12
    decoder_attention_heads: int = 12
    encoder_ffn_dim: int = 3072
    decoder_ffn_dim: int = 3072
    activation_function: str = "gelu"
    dropout: float = 0.1
    attention_dropout: float = 0.0
    activation_dropout: float = 0.0
    max_position_embeddings: int = 1024
    init_std: float = 0.02
    scale_embedding: bool = False
    pad_token_id: int = 1
    bos_token_id: int = 0
    eos_token_id: int = 2
    decoder_start_token_id: int = 2
    # mBART: force this token (the target-language id) as the first
    # generated token; generation honours it in greedy/sampling/beam
    forced_bos_token_id: Optional[int] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"
    remat: bool = False
    remat_policy: str = "full"           # full | dots | dots_no_batch
    # mBART variant: pre-LN blocks + a final LayerNorm per stack
    normalize_before: bool = False
    stack_final_ln: bool = False
    # GPipe pipeline parallelism over both stacks (models/pipeline.py::
    # PipelinedBartStack): 0 = dense; generation reloads dense
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    # int8 weight-only dense kernels for generation (models/quant.py)
    weight_quant: str = "none"           # none | int8


def bart_config_from_hf(hf_config: dict, **overrides) -> BartConfig:
    kw = dict(
        vocab_size=hf_config["vocab_size"],
        d_model=hf_config["d_model"],
        encoder_layers=hf_config["encoder_layers"],
        decoder_layers=hf_config["decoder_layers"],
        encoder_attention_heads=hf_config["encoder_attention_heads"],
        decoder_attention_heads=hf_config["decoder_attention_heads"],
        encoder_ffn_dim=hf_config["encoder_ffn_dim"],
        decoder_ffn_dim=hf_config["decoder_ffn_dim"],
        activation_function=hf_config.get("activation_function", "gelu"),
        dropout=hf_config.get("dropout", 0.1),
        attention_dropout=hf_config.get("attention_dropout", 0.0),
        activation_dropout=hf_config.get("activation_dropout", 0.0),
        max_position_embeddings=hf_config.get("max_position_embeddings", 1024),
        init_std=hf_config.get("init_std", 0.02),
        scale_embedding=hf_config.get("scale_embedding", False),
        pad_token_id=hf_config.get("pad_token_id", 1),
        bos_token_id=hf_config.get("bos_token_id", 0),
        eos_token_id=hf_config.get("eos_token_id", 2),
        decoder_start_token_id=(
            hf_config["decoder_start_token_id"]
            if hf_config.get("decoder_start_token_id") is not None
            else hf_config.get("eos_token_id", 2)),
        forced_bos_token_id=hf_config.get("forced_bos_token_id"),
    )
    kw.update(overrides)
    kw.pop("use_pooler", None)
    return BartConfig(**kw)


def _dense(cfg, features: int, name: str) -> nn.Module:
    from huggingface_sagemaker_tensorflow_distributed_tpu.models.quant import (
        make_dense,
    )
    return make_dense(cfg, features, nn.initializers.normal(cfg.init_std),
                      name=name)


def _ln(cfg, name: str) -> nn.LayerNorm:
    return nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name=name)


def _padding_mask(attention_mask, dtype=jnp.float32):
    m = attention_mask[:, None, None, :].astype(dtype)
    return (1.0 - m) * NEG_INF


class BartAttention(nn.Module):
    """Biased-projection attention, q pre-scaled; optional causal cache
    (same incremental pattern as T5Attention)."""

    config: BartConfig
    num_heads: int

    @nn.compact
    def __call__(self, hidden, kv_hidden=None, mask=None,
                 deterministic: bool = True, decode: bool = False):
        cfg = self.config
        d = cfg.d_model
        head_dim = d // self.num_heads
        source = hidden if kv_hidden is None else kv_hidden

        def split(x):
            b, s, _ = x.shape
            return x.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        q = split(_dense(cfg, d, "query")(hidden)) * head_dim ** -0.5
        k = split(_dense(cfg, d, "key")(source))
        v = split(_dense(cfg, d, "value")(source))

        if decode and kv_hidden is None:
            is_init = self.has_variable("cache", "cached_key")
            cached_k = self.variable("cache", "cached_key", jnp.zeros, k.shape, k.dtype)
            cached_v = self.variable("cache", "cached_value", jnp.zeros, v.shape, v.dtype)
            cache_index = self.variable("cache", "cache_index",
                                        lambda: jnp.array(0, jnp.int32))
            if is_init:
                cur = cache_index.value
                max_len = cached_k.value.shape[2]
                q_len = q.shape[2]
                k = lax.dynamic_update_slice(cached_k.value, k, (0, 0, cur, 0))
                v = lax.dynamic_update_slice(cached_v.value, v, (0, 0, cur, 0))
                cached_k.value, cached_v.value = k, v
                cache_index.value = cur + q_len
                valid = jnp.arange(max_len)[None, :] <= (
                    cur + jnp.arange(q_len)[:, None])
                step_mask = jnp.where(valid, 0.0, NEG_INF)[None, None]
                mask = step_mask if mask is None else mask + step_mask

        if cfg.attention_dropout > 0 and not deterministic:
            # HF applies dropout to the attention probabilities during
            # training; the fused xla_attention path has no hook for it
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
            if mask is not None:
                logits = logits + mask.astype(jnp.float32)
            probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
            probs = nn.Dropout(cfg.attention_dropout)(probs, deterministic=False)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        else:
            ctx = xla_attention(q, k, v, mask=mask, scale=1.0)
        b, h, s, hd = ctx.shape
        out = _dense(cfg, d, "attention_out")(
            ctx.transpose(0, 2, 1, 3).reshape(b, s, h * hd))
        return out


class BartEncoderLayer(nn.Module):
    config: BartConfig

    @nn.compact
    def __call__(self, hidden, attn_mask=None, deterministic: bool = True):
        cfg = self.config
        drop = nn.Dropout(cfg.dropout)
        attn_ln = _ln(cfg, "self_attn_ln")
        ffn_ln = _ln(cfg, "ffn_ln")
        pre = cfg.normalize_before          # mBART: LN before each sublayer

        x = attn_ln(hidden) if pre else hidden
        attn = BartAttention(cfg, cfg.encoder_attention_heads,
                             name="self_attn")(x, mask=attn_mask,
                                               deterministic=deterministic)
        hidden = hidden + drop(attn, deterministic=deterministic)
        if not pre:
            hidden = attn_ln(hidden)
        x = ffn_ln(hidden) if pre else hidden
        x = ACT2FN[cfg.activation_function](
            _dense(cfg, cfg.encoder_ffn_dim, "fc1")(x))
        x = nn.Dropout(cfg.activation_dropout)(x, deterministic=deterministic)
        x = _dense(cfg, cfg.d_model, "fc2")(x)
        hidden = hidden + drop(x, deterministic=deterministic)
        return hidden if pre else ffn_ln(hidden)


class BartDecoderLayer(nn.Module):
    config: BartConfig

    @nn.compact
    def __call__(self, hidden, attn_mask=None, enc_hidden=None, enc_mask=None,
                 deterministic: bool = True, decode: bool = False):
        cfg = self.config
        drop = nn.Dropout(cfg.dropout)
        attn_ln = _ln(cfg, "self_attn_ln")
        cross_ln = _ln(cfg, "cross_ln")
        ffn_ln = _ln(cfg, "ffn_ln")
        pre = cfg.normalize_before

        x = attn_ln(hidden) if pre else hidden
        attn = BartAttention(cfg, cfg.decoder_attention_heads,
                             name="self_attn")(x, mask=attn_mask,
                                               deterministic=deterministic,
                                               decode=decode)
        hidden = hidden + drop(attn, deterministic=deterministic)
        if not pre:
            hidden = attn_ln(hidden)
        x = cross_ln(hidden) if pre else hidden
        cross = BartAttention(cfg, cfg.decoder_attention_heads,
                              name="cross_attn")(x, kv_hidden=enc_hidden,
                                                 mask=enc_mask,
                                                 deterministic=deterministic)
        hidden = hidden + drop(cross, deterministic=deterministic)
        if not pre:
            hidden = cross_ln(hidden)
        x = ffn_ln(hidden) if pre else hidden
        x = ACT2FN[cfg.activation_function](
            _dense(cfg, cfg.decoder_ffn_dim, "fc1")(x))
        x = nn.Dropout(cfg.activation_dropout)(x, deterministic=deterministic)
        x = _dense(cfg, cfg.d_model, "fc2")(x)
        hidden = hidden + drop(x, deterministic=deterministic)
        return hidden if pre else ffn_ln(hidden)


class BartStack(nn.Module):
    """Encoder or decoder stack: offset-2 learned positions +
    layernorm_embedding over the (shared) token embeds, then the
    post-LN layers."""

    config: BartConfig
    is_decoder: bool = False

    @nn.compact
    def __call__(self, embeds, attn_mask=None, enc_hidden=None,
                 enc_mask=None, deterministic: bool = True,
                 decode: bool = False):
        cfg = self.config
        positions = nn.Embed(
            cfg.max_position_embeddings + _POS_OFFSET, cfg.d_model,
            embedding_init=nn.initializers.normal(cfg.init_std),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="embed_positions")
        pos_offset = 0
        if self.is_decoder and decode:
            # physical decode position tracked alongside the KV caches
            is_init = self.has_variable("cache", "position_index")
            idx = self.variable("cache", "position_index",
                                lambda: jnp.array(0, jnp.int32))
            if is_init:
                pos_offset = idx.value
                idx.value = pos_offset + embeds.shape[1]
        pos_ids = pos_offset + jnp.arange(embeds.shape[1])[None, :] + _POS_OFFSET
        x = _ln(cfg, "embed_ln")(embeds + positions(pos_ids))
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        n_layers = cfg.decoder_layers if self.is_decoder else cfg.encoder_layers
        for i in range(n_layers):
            if self.is_decoder:
                layer_cls = BartDecoderLayer
                if cfg.remat:
                    layer_cls = nn.remat(
                        BartDecoderLayer, static_argnums=(5, 6),
                        policy=remat_policy(cfg.remat_policy))
                x = layer_cls(cfg, name=f"layer_{i}")(
                    x, attn_mask, enc_hidden, enc_mask, deterministic, decode)
            else:
                layer_cls = BartEncoderLayer
                if cfg.remat:
                    layer_cls = nn.remat(
                        BartEncoderLayer, static_argnums=(3,),
                        policy=remat_policy(cfg.remat_policy))
                x = layer_cls(cfg, name=f"layer_{i}")(
                    x, attn_mask, deterministic)
        if cfg.stack_final_ln:
            x = _ln(cfg, "final_ln")(x)
        return x


class BartForConditionalGeneration(nn.Module):
    """Encoder-decoder LM head tied to the shared embedding; same
    ``encode``/``decode`` generation interface as T5."""

    config: BartConfig

    is_encoder_decoder = True

    def setup(self):
        cfg = self.config
        self.shared = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(cfg.init_std),
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        if cfg.pipeline_stages:
            from huggingface_sagemaker_tensorflow_distributed_tpu.models.pipeline import (
                PipelinedBartStack,
            )
            self.encoder = PipelinedBartStack(cfg, is_decoder=False)
            self.decoder = PipelinedBartStack(cfg, is_decoder=True)
        else:
            self.encoder = BartStack(cfg, is_decoder=False)
            self.decoder = BartStack(cfg, is_decoder=True)

    def _embed_tokens(self, ids):
        cfg = self.config
        scale = cfg.d_model ** 0.5 if cfg.scale_embedding else 1.0
        return self.shared(ids) * scale

    def encode(self, input_ids, attention_mask=None, deterministic: bool = True):
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        return self.encoder(self._embed_tokens(input_ids),
                            attn_mask=_padding_mask(attention_mask),
                            deterministic=deterministic)

    def _teacher_forcing_mask(self, decoder_input_ids,
                              decoder_attention_mask):
        dec_len = decoder_input_ids.shape[1]
        i = jnp.arange(dec_len)[:, None]
        j = jnp.arange(dec_len)[None, :]
        causal = jnp.where(j <= i, 0.0, NEG_INF)[None, None]
        if decoder_attention_mask is not None:
            return causal + _padding_mask(decoder_attention_mask)
        return causal

    def decode(self, decoder_input_ids, encoder_hidden,
               encoder_attention_mask=None, decoder_attention_mask=None,
               deterministic: bool = True, decode: bool = False):
        cfg = self.config
        if decode:
            self_mask = None   # cache supplies causal masking
        else:
            self_mask = self._teacher_forcing_mask(decoder_input_ids,
                                                   decoder_attention_mask)
        enc_mask = (None if encoder_attention_mask is None
                    else _padding_mask(encoder_attention_mask))
        x = self.decoder(self._embed_tokens(decoder_input_ids),
                         attn_mask=self_mask,
                         enc_hidden=encoder_hidden, enc_mask=enc_mask,
                         deterministic=deterministic, decode=decode)
        return self.shared.attend(x.astype(cfg.dtype)).astype(jnp.float32)

    def __call__(self, input_ids, attention_mask=None, decoder_input_ids=None,
                 decoder_attention_mask=None, deterministic: bool = True):
        enc = self.encode(input_ids, attention_mask, deterministic)
        return self.decode(decoder_input_ids, enc, attention_mask,
                           decoder_attention_mask, deterministic)

    def seq2seq_hidden_and_embedding(self, input_ids, attention_mask=None,
                                     decoder_input_ids=None,
                                     decoder_attention_mask=None,
                                     deterministic: bool = True):
        """(pre-head decoder hidden [B, T, H] cast to compute dtype, tied
        embedding [V, H]) — the fused vocab-CE path; ``hidden·Wᵀ`` equals
        ``decode``'s logits without materializing [B, T, V]."""
        cfg = self.config
        enc = self.encode(input_ids, attention_mask, deterministic)
        x = self.decoder(self._embed_tokens(decoder_input_ids),
                         attn_mask=self._teacher_forcing_mask(
                             decoder_input_ids, decoder_attention_mask),
                         enc_hidden=enc,
                         enc_mask=_padding_mask(attention_mask)
                         if attention_mask is not None else None,
                         deterministic=deterministic)
        return x.astype(cfg.dtype), self.shared.embedding
